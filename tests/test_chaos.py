"""Chaos tier: seeded failpoint schedules replayed across topologies.

Run with ``pytest -m chaos`` (or ``tools/run_chaos.sh``, which sweeps
the seeds across both the in-process and ``RAY_TPU_CLUSTER=daemons``
topologies). Every test here is ALSO marked slow so the tier-1 sweep
(``-m 'not slow'``) never pays for cluster boots + fault windows.

Each schedule is deterministic for a given seed: probabilistic arms
draw from the registry's seeded RNG, hit-count arms count per seam, and
every assertion on fault counts reads the registry's thread-safe hit
log — never timing heuristics.
"""

import os
import threading
import time

import pytest

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu._private import failpoints as fp
from ray_tpu._private import rpc
from ray_tpu._private.retry import RetryPolicy

pytestmark = [pytest.mark.chaos, pytest.mark.slow]

SEEDS = [101, 202, 303]


@pytest.fixture(autouse=True)
def _reset_failpoints():
    yield
    fp.reset()


# ---------------------------------------------------------------------------
# in-process topology: strict exact-count replays
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", SEEDS)
def test_chaos_every_nth_rpc_drop_converges(seed):
    """Every-Nth-request drop on a live RPC server: every call converges
    under RetryPolicy and the drop count is exact (no background
    traffic shares this in-process server)."""

    class Svc:
        def __init__(self):
            self.served = 0

        def handle_bump(self, conn, rid, msg):
            self.served += 1
            return {"n": self.served}

    rpc.declare("bump", "k")
    svc = Svc()
    server = rpc.Server(svc).start()
    client = rpc.Client(server.addr, timeout=0.25)
    fp.activate("rpc.server.recv=drop:every=3", seed=seed)
    policy = RetryPolicy(max_attempts=6, base_s=0.005,
                         max_backoff_s=0.02)
    try:
        for k in range(12):
            policy.run(lambda: client.call("bump", k=k),
                       loop="chaos.rpc_drop", retry_on=(rpc.RpcError,))
        # 12 successes with every 3rd arrival dropped: the 12th success
        # lands on arrival 17 (drops at 3,6,9,12,15) => 17 hits, 5 drops
        assert svc.served == 12
        assert fp.fire_count("rpc.server.recv") == 5
        assert fp.hit_count("rpc.server.recv") == 17
        drops = fp.hit_log("rpc.server.recv")
        assert [e["fire"] for e in drops] == list(range(1, 6))
        assert all(e["method"] == "bump" for e in drops)
    finally:
        client.close()
        server.stop()


@pytest.mark.parametrize("seed", SEEDS)
def test_chaos_probabilistic_drop_is_seed_deterministic(seed):
    """The same seed replays the same probabilistic fault schedule —
    run the identical workload twice and compare the hit logs."""

    def run_once():
        fp.activate("chaos.coin=drop:p=0.5", seed=seed)
        outcomes = [fp.fire("chaos.coin") is fp.DROP for _ in range(40)]
        fired = fp.fire_count("chaos.coin")
        return outcomes, fired

    first, fired1 = run_once()
    second, fired2 = run_once()
    assert first == second and fired1 == fired2
    assert 0 < fired1 < 40


def test_chaos_stream_error_mid_generator(ray_start_regular):
    """A failpoint killing the stream after 2 items surfaces as a typed
    error on the consumer, never a hang or a silent truncation."""
    fp.activate("worker.generator_stream=error():after=2")

    @ray_tpu.remote(max_retries=0)
    def gen():
        yield from range(5)

    it = gen.remote()
    assert ray_tpu.get(next(it)) == 0
    assert ray_tpu.get(next(it)) == 1
    with pytest.raises(Exception):
        for _ in range(3):
            ray_tpu.get(next(it))
    assert fp.fire_count("worker.generator_stream") == 1


# ---------------------------------------------------------------------------
# daemons topology: whole-cluster seeded schedules
# ---------------------------------------------------------------------------

@pytest.fixture
def daemon_cluster():
    rt = ray_tpu.init(num_nodes=2, resources={"CPU": 4},
                      cluster="daemons")
    yield rt
    ray_tpu.shutdown()


@pytest.mark.parametrize("seed", SEEDS)
def test_chaos_seeded_schedule_daemons(seed, daemon_cluster):
    """The acceptance schedule: every-Nth lane-submit fault + one head
    kill mid-KV-traffic + retried tasks — converges to success for
    every seed, with exact fault counts from the registry log and
    retry counters visible in the Prometheus registry."""
    rt = daemon_cluster
    fp.activate("fast_lane.submit=error(OSError):every=3:max=5",
                seed=seed)

    @ray_tpu.remote
    def f(x):
        return x * 3

    out = ray_tpu.get([f.remote(i) for i in range(30)])
    assert out == [i * 3 for i in range(30)]

    # head respawn mid-put: kill the head, keep writing through the
    # redial window, and verify the persisted KV survived the restart
    backend = rt.cluster_backend
    backend.head.kv_put(b"chaos:key", b"v0")
    backend.head_proc.kill()
    backend.head.kv_put(b"chaos:key", b"v1")     # rides the redial
    assert backend.head.kv_get(b"chaos:key") == b"v1"

    # the cluster still runs tasks after the respawn
    out = ray_tpu.get([f.remote(i) for i in range(10)])
    assert out == [i * 3 for i in range(10)]

    # exact fault accounting from the registry log
    assert fp.fire_count("fast_lane.submit") == 5
    lane_log = fp.hit_log("fast_lane.submit")
    assert [e["fire"] for e in lane_log] == [1, 2, 3, 4, 5]

    # migrated retry loops surface in the Prometheus exposition
    from ray_tpu.util import metrics
    text = metrics.prometheus_text()
    assert "ray_tpu_retries_total" in text
    assert 'loop="head.redial"' in text


@pytest.mark.parametrize("seed", SEEDS)
def test_chaos_generator_body_exactly_once(seed, daemon_cluster,
                                           tmp_path):
    """Exactly-once-per-attempt: a PLAIN function with a side effect
    that returns a generator object must run its body once per attempt
    even while lane submits are failing over to the classic path
    (regression for the KIND_GEN_FALLBACK double-run)."""
    fp.activate("fast_lane.submit=error(OSError):p=0.4", seed=seed)
    marker_dir = str(tmp_path)

    @ray_tpu.remote
    def gen_with_side_effect(i):
        with open(os.path.join(marker_dir, f"{i}.ran"), "a") as fh:
            fh.write("x")
        return (j * 2 for j in range(3))

    refs = [gen_with_side_effect.remote(i) for i in range(12)]
    for r in refs:
        ray_tpu.get(r)
    for i in range(12):
        with open(os.path.join(marker_dir, f"{i}.ran")) as fh:
            assert fh.read() == "x", f"task {i} body ran != once"
    # the schedule actually exercised both paths
    assert 0 < fp.fire_count("fast_lane.submit") < fp.hit_count(
        "fast_lane.submit")


@pytest.mark.parametrize("seed", SEEDS)
def test_chaos_lane_death_mid_stream_daemons(seed, daemon_cluster):
    """Kill a daemon mid-stream: the consumer gets a typed error or the
    retried stream completes — never a wedge (deterministic per seed
    because the kill lands between two acked items)."""
    rt = daemon_cluster

    @ray_tpu.remote(max_retries=2)
    def slow_gen():
        for i in range(6):
            time.sleep(0.05)
            yield i

    it = slow_gen.remote()
    assert ray_tpu.get(next(it)) == 0
    # node death under a streaming task -> lineage replay skips acked
    # items (deterministic streams) or surfaces NodeDiedError
    victim = list(rt.cluster_backend.daemons.values())[0]
    try:
        rest = []
        mid_kill = {"done": False}

        def killer():
            victim.sigkill()
            mid_kill["done"] = True

        t = threading.Thread(target=killer)
        t.start()
        try:
            for ref in it:
                rest.append(ray_tpu.get(ref, timeout=30))
        except (exc.RayTpuError, exc.TaskError):
            pass        # typed error (incl. get timeout) is accepted
        t.join()
        assert mid_kill["done"]
        # convergence: whatever survived is a prefix-consistent stream
        assert rest == list(range(1, 1 + len(rest)))
    finally:
        # the second daemon keeps the cluster serviceable (generous
        # timeout: this tier runs on loaded CI boxes mid node-death)
        @ray_tpu.remote(max_retries=2)
        def ping():
            return "up"

        assert ray_tpu.get(ping.remote(), timeout=90) == "up"


@pytest.mark.parametrize("seed", SEEDS)
def test_chaos_push_task_delay_schedule(seed):
    """Env-activated schedule reaches SPAWNED daemon processes: delay
    arms on the daemon's push path slow leases without losing tasks."""
    os.environ["RAY_TPU_FAILPOINTS"] = (
        "daemon.push_task=delay(30):every=2")
    os.environ["RAY_TPU_FAILPOINTS_SEED"] = str(seed)
    try:
        rt = ray_tpu.init(num_nodes=1, resources={"CPU": 2},
                          cluster="daemons")
        try:
            @ray_tpu.remote(num_returns="streaming")
            def gen():
                yield from range(4)

            # streaming tasks ride the classic push path (the delayed
            # seam); the stream must still arrive complete and ordered
            assert [ray_tpu.get(r) for r in gen.remote()] == [0, 1, 2, 3]
        finally:
            ray_tpu.shutdown()
    finally:
        os.environ.pop("RAY_TPU_FAILPOINTS", None)
        os.environ.pop("RAY_TPU_FAILPOINTS_SEED", None)


# ---------------------------------------------------------------------------
# graceful drain under chaos: migration faults + crashes racing the drain
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", SEEDS)
def test_chaos_drain_migration_faults_fall_back_to_lineage(seed):
    """Seeded error arm on drain.migrate_object: objects whose
    migration is injected to fail still survive the departure — lineage
    reconstruction covers exactly what migration could not move, and
    every get() converges."""
    import numpy as np

    rt = ray_tpu.init(num_nodes=4, resources={"CPU": 4})
    try:
        @ray_tpu.remote(max_retries=5)
        def blob(i):
            return np.full((600, 600), i)

        refs = [blob.remote(i) for i in range(8)]
        ray_tpu.get(refs)
        victim = next(n for n in rt.nodes()
                      if any(n.store.contains(r.id) for r in refs))
        n_victim = sum(1 for r in refs if victim.store.contains(r.id))

        fp.activate("drain.migrate_object=error:p=0.5", seed=seed)
        assert rt.drain_node(victim.node_id, deadline_s=20,
                             reason="chaos")
        deadline = time.monotonic() + 25
        while (rt.get_node(victim.node_id) is not None
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert rt.get_node(victim.node_id) is None

        vals = ray_tpu.get(refs, timeout=60)
        assert all(vals[i][0][0] == i for i in range(8))
        # accounting: every sole copy either migrated (counted once —
        # retried copies are location-deduped) or was lost with the
        # node and lazily reconstructed by the get() above
        moved = rt.stats["drain_objects_migrated"]
        rebuilt = rt.stats["objects_reconstructed"]
        assert moved + rebuilt == n_victim, (moved, rebuilt, n_victim)
        # each sole copy reached the failpoint at least once
        assert fp.hit_count("drain.migrate_object") >= n_victim
    finally:
        ray_tpu.shutdown()


@pytest.mark.parametrize("seed", SEEDS)
def test_chaos_drain_races_worker_crashes_daemons(seed, daemon_cluster):
    """Drain one daemon while seeded lane faults crash/deny submits
    across the cluster: every task converges (completed, resubmitted
    off the draining node, or retried through the crash machinery) and
    the drained node leaves — drain and chaos never wedge each other."""
    rt = daemon_cluster
    fp.activate("fast_lane.submit=error(OSError):every=4:max=6",
                seed=seed)

    @ray_tpu.remote(max_retries=3)
    def work(i):
        time.sleep(0.02)
        return i * 7

    refs = [work.remote(i) for i in range(24)]
    victim = rt.alive_nodes()[0]
    assert rt.drain_node(victim.node_id, deadline_s=10, reason="chaos")
    refs += [work.remote(i) for i in range(24, 36)]

    out = ray_tpu.get(refs, timeout=120)
    assert out == [i * 7 for i in range(36)]
    deadline = time.monotonic() + 30
    while (rt.get_node(victim.node_id) is not None
           and time.monotonic() < deadline):
        time.sleep(0.1)
    assert rt.get_node(victim.node_id) is None
    # the surviving node keeps serving
    assert ray_tpu.get(work.remote(99), timeout=60) == 693
    # head membership reflects the drained departure
    views = {n["node_id"]: n
             for n in rt.cluster_backend.head.list_nodes()}
    assert not views[victim.node_id.hex()]["alive"]


@pytest.mark.parametrize("seed", SEEDS)
def test_chaos_drain_exec_pool_inflight_vs_pending(seed, tmp_path):
    """Drain a node whose sized exec pool is saturated (PR 10 pooled
    execution): pooled IN-FLIGHT tasks finish where they run, admitted-
    but-unstarted specs still in the pool queue are stolen back and
    handed to the scheduler WITHOUT consuming a retry (max_retries=0
    throughout — a burned retry would fail the task), and every body
    runs exactly once, under seeded lane-submit delay noise. Topology
    comes from the run_chaos.sh sweep (in-process + daemons)."""
    rt = ray_tpu.init(num_nodes=2, resources={"CPU": 8},
                      # pool far smaller than the ledger's admission
                      # width: admitted specs QUEUE in the pool, so the
                      # drain finds both in-flight and pending work
                      _system_config={"exec_pool_size": 2})
    try:
        fp.activate("fast_lane.submit=delay(10):p=0.25", seed=seed)
        marker_dir = str(tmp_path)

        @ray_tpu.remote(max_retries=0)
        def slow(i):
            with open(os.path.join(marker_dir, f"{i}.ran"), "a") as fh:
                fh.write("x")
            time.sleep(0.2)
            return i * 5

        refs = [slow.remote(i) for i in range(16)]
        time.sleep(0.15)    # let admission fill the pools mid-flood
        victim = rt.alive_nodes()[0]
        assert rt.drain_node(victim.node_id, deadline_s=30,
                             reason="chaos")
        out = ray_tpu.get(refs, timeout=120)
        assert out == [i * 5 for i in range(16)]
        # exactly once each: the pool-queue handback resubmits specs
        # that never started — a double run (or a retry-burning failure)
        # shows up as a doubled marker / missing result
        for i in range(16):
            with open(os.path.join(marker_dir, f"{i}.ran")) as fh:
                assert fh.read() == "x", f"task {i} body ran != once"
        assert rt.stats["tasks_retried"] == 0
        # clean drain: the node left via completion, not escalation
        deadline = time.monotonic() + 30
        while (rt.get_node(victim.node_id) is not None
               and time.monotonic() < deadline):
            time.sleep(0.1)
        assert rt.get_node(victim.node_id) is None
        assert rt.stats["drain_escalations_total"] == 0
        # the survivor keeps serving pooled work
        assert ray_tpu.get(slow.remote(99), timeout=60) == 495
    finally:
        ray_tpu.shutdown()


@pytest.mark.parametrize("seed", SEEDS)
def test_chaos_drain_deadline_races_escalation_daemons(seed,
                                                      daemon_cluster):
    """A drain whose window closes mid-load escalates into the node-
    death path while the driver's own timer races the head's: the
    escalation runs exactly once, tasks recover via retries, and the
    cluster converges."""
    rt = daemon_cluster

    @ray_tpu.remote(max_retries=3)
    def slow(i):
        time.sleep(0.5)
        return i

    refs = [slow.remote(i) for i in range(8)]
    time.sleep(0.2)
    victim = rt.alive_nodes()[0]
    fp.activate("drain.deadline=delay(25)", seed=seed)
    assert rt.drain_node(victim.node_id, deadline_s=0.3, reason="chaos")
    assert sorted(ray_tpu.get(refs, timeout=120)) == list(range(8))
    deadline = time.monotonic() + 30
    while (rt.get_node(victim.node_id) is not None
           and time.monotonic() < deadline):
        time.sleep(0.1)
    assert rt.get_node(victim.node_id) is None
    # the escalation was counted once (driver timer or head deadline —
    # whichever won; the loser found the node already gone)
    assert rt.stats["drain_escalations_total"] == 1


# ---------------------------------------------------------------------------
# multi-tenant fair-share under fault injection
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", SEEDS[:1])
def test_chaos_quota_exceeded_job_degrades_others_unharmed(seed):
    """A tenant that blows through its CPU quota while the
    ``admission.verdict`` seam is erroring degrades gracefully (its
    submits fall back to QUEUED — delayed, never lost) and the
    well-behaved tenant on the same cluster is unharmed: every task
    from BOTH jobs completes and the seam's hit log shows the faults
    actually fired."""
    from ray_tpu.tenancy import job_context

    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 2},
                      _system_config={"fairshare": True})
    try:
        # the greedy tenant gets a 1-CPU hard cap on a 2-CPU cluster
        rt.tenancy.set_quota("greedy", hard={"CPU": 1.0})

        @ray_tpu.remote
        def work(i):
            time.sleep(0.02)
            return i

        # every 2nd admission decision errors: those submits must
        # degrade to QUEUED (dispatch gate re-decides), not crash
        fp.activate("admission.verdict=error(RuntimeError):every=2:max=20",
                    seed=seed)
        with job_context("greedy"):
            greedy_refs = [work.remote(i) for i in range(20)]
        with job_context("polite"):
            polite_refs = [work.remote(i) for i in range(10)]
        fired = fp.fire_count("admission.verdict")
        assert fired > 0     # the schedule actually cut the seam
        # the polite job is unharmed: all results arrive
        assert sorted(ray_tpu.get(polite_refs, timeout=60)) == \
            list(range(10))
        # the degraded job is delayed, never lost: all results arrive
        # even though half its verdicts came from the error arm and its
        # quota held it to 1 CPU throughout
        assert sorted(ray_tpu.get(greedy_refs, timeout=120)) == \
            list(range(20))
        assert fp.hit_count("admission.verdict") >= fired
    finally:
        ray_tpu.shutdown()
