"""Importable Serve applications for the declarative-config tests
(the role of a user's app module named by ``import_path``)."""

from ray_tpu import serve


@serve.deployment(name="Scaler")
class Scaler:
    def __init__(self, factor: int = 2):
        self.factor = factor

    def __call__(self, x):
        return x * self.factor

    def reconfigure(self, user_config):
        self.factor = user_config.get("factor", self.factor)


# a pre-bound Application
app = Scaler.bind(2)


def build_app(args):
    """A builder callable: config args choose the bound arguments."""
    return Scaler.bind(int(args.get("factor", 3)))
