"""Stress/concurrency tests (VERDICT r1 weak #12): hammer the dispatch
loop, refcount __del__ cascades, and generator backpressure under
multi-consumer races.

Reference analogues: ``release/benchmarks`` many-task envelopes and
``python/ray/tests`` stress suites, scaled to a CI-sized single host.
"""

import threading
import time

import numpy as np
import pytest

import ray_tpu


def test_dispatch_loop_many_small_tasks(ray_start_regular):
    """A burst of small tasks through the process-worker plane."""

    @ray_tpu.remote
    def inc(x):
        return x + 1

    t0 = time.monotonic()
    refs = [inc.remote(i) for i in range(300)]
    out = ray_tpu.get(refs)
    elapsed = time.monotonic() - t0
    assert out == list(range(1, 301))
    assert elapsed < 60  # sanity bound, not a perf SLA


def test_concurrent_submitters(ray_start_regular):
    """Many driver threads submitting in parallel must not corrupt
    dispatch/refcount state."""

    @ray_tpu.remote
    def work(tid, i):
        return tid * 1000 + i

    errors = []
    results = {}

    def submitter(tid):
        try:
            refs = [work.remote(tid, i) for i in range(40)]
            results[tid] = ray_tpu.get(refs)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=submitter, args=(t,))
               for t in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors
    for tid, vals in results.items():
        assert vals == [tid * 1000 + i for i in range(40)]


def test_refcount_del_cascade(ray_start_regular):
    """Dropping thousands of refs (and chains of dependent refs) from
    multiple threads must not deadlock the refcounter (a __del__ cascade
    deadlock was fixed once; keep it dead)."""

    @ray_tpu.remote
    def blob():
        return np.zeros(64 * 1024)

    @ray_tpu.remote
    def passthrough(x):
        return x.sum()

    def churn():
        for _ in range(10):
            refs = [blob.remote() for _ in range(20)]
            mids = [passthrough.remote(r) for r in refs]
            del refs          # parent refs die while children in flight
            ray_tpu.get(mids)
            del mids

    threads = [threading.Thread(target=churn) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
        assert not t.is_alive(), "refcount churn deadlocked"


def test_generator_backpressure_multi_consumer(ray_start_regular):
    """Multiple threads consuming one backpressured stream: every item
    is delivered exactly once across consumers, producer never deadlocks."""

    @ray_tpu.remote(_generator_backpressure_num_objects=4)
    def gen(n):
        for i in range(n):
            yield i

    it = gen.remote(60)
    seen = []
    lock = threading.Lock()

    def consume():
        while True:
            try:
                ref = next(it)
            except StopIteration:
                return
            value = ray_tpu.get(ref)
            with lock:
                seen.append(value)

    threads = [threading.Thread(target=consume) for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
        assert not t.is_alive(), "consumer hung"
    assert sorted(seen) == list(range(60))


def test_many_actors_concurrent_calls(ray_start_regular):
    @ray_tpu.remote
    class Cell:
        def __init__(self, base):
            self.base = base
            self.n = 0

        def bump(self):
            self.n += 1
            return self.base + self.n

    actors = [Cell.remote(i * 100) for i in range(8)]
    refs = [a.bump.remote() for a in actors for _ in range(10)]
    out = ray_tpu.get(refs)
    assert len(out) == 80
    final = ray_tpu.get([a.bump.remote() for a in actors])
    assert final == [i * 100 + 11 for i in range(8)]


def test_wait_under_churn(ray_start_regular):
    """ray_tpu.wait over a moving set while tasks finish concurrently."""

    @ray_tpu.remote
    def sleepy(ms):
        time.sleep(ms / 1000.0)
        return ms

    refs = [sleepy.remote((i % 7) * 15) for i in range(60)]
    remaining = list(refs)
    collected = []
    while remaining:
        done, remaining = ray_tpu.wait(remaining, num_returns=1,
                                       timeout=30)
        assert done, "wait() starved despite pending work"
        collected.extend(ray_tpu.get(done))
    assert len(collected) == 60


def test_queued_task_backlog_10000(ray_start_regular):
    """Scale envelope, CI-sized slice of the reference's 1M-queued-task
    target (release/benchmarks/README.md:25-31): 10,000 no-op tasks
    queued before any get, then fully drained, results in order — and
    the drain rate must hold vs a 1,000-task run (no superlinear
    degradation as the backlog deepens)."""

    @ray_tpu.remote
    def val(i):
        return i

    t0 = time.perf_counter()
    out = ray_tpu.get([val.remote(i) for i in range(1000)], timeout=300)
    small_rate = 1000 / (time.perf_counter() - t0)
    assert out == list(range(1000))

    t0 = time.perf_counter()
    refs = [val.remote(i) for i in range(10_000)]
    out = ray_tpu.get(refs, timeout=900)
    big_rate = 10_000 / (time.perf_counter() - t0)
    assert out == list(range(10_000))
    # 10x backlog may not drain >3x slower per task (generous CI margin)
    assert big_rate > small_rate / 3, (
        f"superlinear degradation: {small_rate:.0f}/s @1k vs "
        f"{big_rate:.0f}/s @10k")


def test_many_actors_1000(ray_start_regular):
    """1,000 live actors (reference envelope: 40k cluster-wide; this is
    the single-host CI slice), every one answering."""

    @ray_tpu.remote(_in_process=True)
    class Cell:
        def __init__(self, i):
            self.i = i

        def get(self):
            return self.i

    cells = [Cell.remote(i) for i in range(1000)]
    out = ray_tpu.get([c.get.remote() for c in cells], timeout=600)
    assert out == list(range(1000))
    for c in cells:
        ray_tpu.kill(c)


def test_many_object_args_one_task(ray_start_regular):
    """1,000 object arguments to a single task (reference envelope:
    10k+ on a 64-core box; CI slice on 1 CPU)."""

    @ray_tpu.remote
    def total(*parts):
        return sum(parts)

    refs = [ray_tpu.put(i) for i in range(1000)]
    assert ray_tpu.get(total.remote(*refs), timeout=300) == sum(
        range(1000))


# ---------------------------------------------------------------------------
# scale-envelope tier (VERDICT r4 #4): the committed single-host slices
# of release/benchmarks/README.md:5-31. Marked `envelope` — run via
# `pytest -m envelope` (tools/run_ci.sh runs them as their own stage).
# ---------------------------------------------------------------------------

@pytest.mark.envelope
def test_queued_task_backlog_100k(ray_start_regular):
    """100,000 no-op tasks queued before any get, fully drained, with
    drain-rate parity vs a 10k run — the flat-degradation evidence for
    the reference's 1M-queued envelope (shape-bucketed dispatch keeps
    each completion O(#shapes), not O(backlog))."""

    @ray_tpu.remote(_in_process=True)
    def val(i):
        return i

    t0 = time.perf_counter()
    out = ray_tpu.get([val.remote(i) for i in range(10_000)],
                      timeout=900)
    rate_10k = 10_000 / (time.perf_counter() - t0)
    assert out == list(range(10_000))

    t0 = time.perf_counter()
    refs = [val.remote(i) for i in range(100_000)]
    submit_s = time.perf_counter() - t0
    out = ray_tpu.get(refs, timeout=3600)
    rate_100k = 100_000 / (time.perf_counter() - t0)
    assert out == list(range(100_000))
    assert rate_100k > rate_10k / 3, (
        f"superlinear degradation: {rate_10k:.0f}/s @10k vs "
        f"{rate_100k:.0f}/s @100k (submit {submit_s:.1f}s)")


@pytest.mark.envelope
def test_many_actors_5000(ray_start_regular):
    """5,000 live actors all answering (reference envelope: 40k
    cluster-wide on 64 hosts; this is the one-host slice)."""

    @ray_tpu.remote(_in_process=True)
    class Cell:
        def __init__(self, i):
            self.i = i

        def get(self):
            return self.i

    cells = [Cell.remote(i) for i in range(5000)]
    out = ray_tpu.get([c.get.remote() for c in cells], timeout=1800)
    assert out == list(range(5000))
    for c in cells:
        ray_tpu.kill(c)


@pytest.mark.envelope
def test_64_virtual_node_scheduling():
    """64 virtual nodes: spread tasks land on >= 32 distinct nodes and
    a STRICT_SPREAD placement group claims 16 distinct nodes (the
    many-node scheduling slice of the 2,000-node reference envelope)."""
    import ray_tpu
    from ray_tpu.util.placement_group import placement_group

    rt = ray_tpu.init(num_nodes=64, resources={"CPU": 2})
    try:
        @ray_tpu.remote(_in_process=True,
                        scheduling_strategy="SPREAD")
        def where():
            ctx = ray_tpu.get_runtime_context()
            return ctx.get_node_id()

        nodes = set(ray_tpu.get([where.remote() for _ in range(256)],
                                timeout=600))
        assert len(nodes) >= 32, f"spread reached only {len(nodes)} nodes"

        pg = placement_group([{"CPU": 1}] * 16, strategy="STRICT_SPREAD")
        assert pg.wait(60)
        pg_nodes = {b.node_id for b in pg.bundles}
        assert len(pg_nodes) == 16
    finally:
        ray_tpu.shutdown()
