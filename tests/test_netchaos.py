"""Network-chaos layer + partition fencing: unit tier (fast, tier-1).

Whole-cluster partition campaigns (one-way splits mid-burst, death-mark
then heal, flapping links) live in tests/test_chaos.py (`-m chaos`);
here we pin:

- the LinkPolicy registry contract (spec grammar, seeded determinism,
  window/flap schedules, hit log, disarmed zero-overhead);
- frame-level behavior on a real rpc Client/Server pair (whole-frame
  drops, one-way vs symmetric partitions, duplicate-delivery
  suppression);
- driver-side epoch/attempt fencing with fake result frames;
- head-side epoch minting + persistence across a head-service restart;
- the timeout audit (no unbounded control-plane round trips outside
  the justified allowlist) and the monotonic-clock liveness audit.
"""

import ast
import os
import threading
import time

import pytest

from ray_tpu._private import failpoints as fp
from ray_tpu._private import netchaos as nc
from ray_tpu._private import rpc

_PRIVATE = os.path.dirname(os.path.abspath(nc.__file__))


@pytest.fixture(autouse=True)
def _reset_chaos():
    yield
    nc.reset()
    fp.reset()


# ---------------------------------------------------------------------------
# registry / policy contract
# ---------------------------------------------------------------------------

def test_disarmed_by_default_and_zero_overhead(monkeypatch):
    """With RAY_TPU_NET_CHAOS unset the wire helpers must never consult
    the registry: the disarmed path is the pre-existing code path.
    Poisoning the registry proves no hook runs during a round trip."""
    assert not nc.ENABLED

    class _Poison:
        def apply(self, *a, **k):
            raise AssertionError("registry consulted while disarmed")

    monkeypatch.setattr(nc, "_registry", _Poison())

    class Svc:
        def handle_nc_echo(self, conn, rid, msg):
            return {"v": msg["v"]}

    rpc.declare("nc_echo", "v")
    server = rpc.Server(Svc()).start()
    client = rpc.Client(server.addr, timeout=2.0).link("daemon")
    try:
        assert client.call("nc_echo", v=5)["v"] == 5
    finally:
        client.close()
        server.stop()


def test_spec_grammar():
    pols = nc.parse_spec(
        "driver>daemon=drop=0.25:lat=10:jitter=5;"
        "daemon>head@n1=partition:start=500:dur=2000;"
        "a>b=flap=100/300:bw=1000;"
        "x>y=dup=0.5:sym")
    keys = [p.key for p in pols]
    assert keys == ["driver>daemon@*", "daemon>head@n1", "a>b@*",
                    "x>y@*", "y>x@*"]      # sym installs the mirror
    assert pols[0].drop_p == 0.25 and pols[0].lat_ms == 10.0
    assert pols[0].jitter_ms == 5.0
    assert pols[1].partition and pols[1].start_ms == 500.0
    assert pols[1].dur_ms == 2000.0
    assert pols[2].flap_on_ms == 100.0 and pols[2].flap_off_ms == 300.0
    assert pols[2].bw_bps == 1000.0
    assert pols[3].dup_p == 0.5 and pols[4].dup_p == 0.5
    with pytest.raises(ValueError):
        nc.parse_spec("no-arrow=drop=1")
    with pytest.raises(ValueError):
        nc.parse_spec("a>b=warp=9")


def test_seeded_drop_schedule_is_deterministic():
    def schedule(seed):
        reg = nc.Registry(seed)
        pol = nc.LinkPolicy("a", "b", drop_p=0.5)
        reg.install(pol)
        return [pol.decide(100, now=1.0)[0] == "drop"
                for _ in range(64)]

    first = schedule(42)
    assert schedule(42) == first
    assert any(first) and not all(first)    # actually probabilistic
    assert schedule(43) != first            # seed changes the draws


def test_per_link_rng_isolation():
    """One link's draws must not perturb another's (RNG derived from
    (seed, src>dst@link)) — schedules replay under interleaving."""
    def a_schedule(interleave):
        reg = nc.Registry(7)
        a = nc.LinkPolicy("a", "b", drop_p=0.5)
        b = nc.LinkPolicy("c", "d", drop_p=0.5)
        reg.install(a)
        reg.install(b)
        out = []
        for _ in range(32):
            out.append(a.decide(10, now=0.0)[0] == "drop")
            if interleave:
                b.decide(10, now=0.0)
        return out

    assert a_schedule(False) == a_schedule(True)


def test_window_start_dur_and_heal_transition():
    pol = nc.LinkPolicy("a", "b", partition=True,
                        start_ms=500.0, dur_ms=2000.0)
    t0 = 100.0
    # before the window opens: clean, no heal
    assert pol.decide(10, now=t0) == (None, 0.0, False)
    assert pol.decide(10, now=t0 + 0.2) == (None, 0.0, False)
    # inside the window: hard partition
    assert pol.decide(10, now=t0 + 0.6)[0] == "drop"
    assert pol.decide(10, now=t0 + 2.0)[0] == "drop"
    # window elapsed: clean again, heal reported exactly once
    assert pol.decide(10, now=t0 + 3.0) == (None, 0.0, True)
    assert pol.decide(10, now=t0 + 3.1) == (None, 0.0, False)


def test_flap_schedule_cycles():
    pol = nc.LinkPolicy("a", "b", partition=True,
                        flap_on_ms=100.0, flap_off_ms=300.0)
    t0 = 50.0
    pattern = [pol.decide(1, now=t0 + ms / 1000.0)[0]
               for ms in (0, 50, 150, 250, 350, 450, 550, 850)]
    # 100ms on / 300ms off, measured from first consult
    assert pattern == ["drop", "drop", None, None, None,
                       "drop", None, "drop"]


def test_bandwidth_and_latency_delay():
    pol = nc.LinkPolicy("a", "b", lat_ms=20.0, bw_bps=10000.0)
    effect, delay_s, healed = pol.decide(500, now=1.0)
    assert effect is None and not healed
    assert delay_s == pytest.approx(0.02 + 500 / 10000.0)
    assert pol.delays == 1


def test_partition_heal_seam_fires():
    fp.activate("net.partition_heal=delay(0);net.link_drop=delay(0)")
    reg = nc.activate("a>b=partition:dur=100")
    pol_now = time.monotonic()
    assert reg.apply("a", "b", "*", 10) is nc.DROP_FRAME
    assert fp.fire_count("net.link_drop") == 1
    # force the window shut, then one more consult reports the heal
    with reg._lock:
        reg._policies[0].first_use = pol_now - 10.0
    assert reg.apply("a", "b", "*", 10) is None
    assert fp.fire_count("net.partition_heal") == 1
    log = fp.hit_log("net.link_drop")
    assert log[0]["src"] == "a" and log[0]["dst"] == "b"


def test_hit_log_and_injected_counters():
    nc.activate("a>b=drop=1.0")
    reg = nc._registry
    for _ in range(3):
        reg.apply("a", "b", "*", 64)
    reg.apply("other", "b", "*", 64)        # no match: clean
    assert nc.injected_count("drop") == 3
    assert nc.injected_count() == 3
    entries = [e for e in rpc.wire_metric_entries()
               if e["name"] == "ray_tpu_link_chaos_injected_total"]
    assert entries and entries[0]["samples"] == [[[["effect", "drop"]], 3]]
    log = nc.hit_log("a>b@*")
    assert len(log) == 3
    assert all(e["effect"] == "drop" and e["nbytes"] == 64 for e in log)


def test_config_flag_activation_exports_env():
    class _Cfg:
        net_chaos = "driver>daemon=drop=0.1"
        net_chaos_seed = 9

    try:
        nc.maybe_activate_from_config(_Cfg())
        assert nc.ENABLED
        assert os.environ["RAY_TPU_NET_CHAOS"] == _Cfg.net_chaos
        assert os.environ["RAY_TPU_NET_CHAOS_SEED"] == "9"
    finally:
        nc.reset()
    assert "RAY_TPU_NET_CHAOS" not in os.environ
    assert not nc.ENABLED


# ---------------------------------------------------------------------------
# frame-level behavior on a real rpc pair
# ---------------------------------------------------------------------------

class _CountingSvc:
    def __init__(self):
        self.calls = 0

    def handle_nc_count(self, conn, rid, msg):
        self.calls += 1
        return {"v": msg["v"]}


rpc.declare("nc_count", "v")


def _pair(svc, timeout=0.5, local_role="t", peer_role="svc"):
    server = rpc.Server(svc).start()
    client = rpc.Client(server.addr, timeout=timeout)
    # per-socket role override: this test process plays role ``t``
    nc.register_link(client._sock, peer_role, local_role=local_role)
    return server, client


def test_one_way_partition_request_direction():
    """t>svc partition: requests vanish, the handler never runs, the
    caller gets a TYPED timeout (never a wedged thread)."""
    svc = _CountingSvc()
    server, client = _pair(svc)
    try:
        assert client.call("nc_count", v=1)["v"] == 1
        nc.activate("t>svc=partition")
        with pytest.raises(rpc.RpcError):
            client.call("nc_count", v=2)
        assert svc.calls == 1               # request never arrived
        assert nc.injected_count("drop") >= 1
        nc.reset()
        assert client.call("nc_count", v=3)["v"] == 3   # link healed
    finally:
        client.close()
        server.stop()


def test_one_way_partition_reply_direction():
    """svc>t partition (the REVERSE edge): the request goes through and
    EXECUTES — only the reply is lost. This is the half-open failure
    fencing exists for: work ran, the caller saw a timeout."""
    svc = _CountingSvc()
    server, client = _pair(svc)
    try:
        nc.activate("svc>t=partition")
        with pytest.raises(rpc.RpcError):
            client.call("nc_count", v=1)
        deadline = time.monotonic() + 2.0
        while svc.calls == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert svc.calls == 1               # the handler DID run
    finally:
        client.close()
        server.stop()


def test_symmetric_partition_blocks_both_directions():
    svc = _CountingSvc()
    server, client = _pair(svc)
    try:
        nc.activate("t>svc=partition:sym")
        with pytest.raises(rpc.RpcError):
            client.call("nc_count", v=1)
        assert svc.calls == 0
    finally:
        client.close()
        server.stop()


def test_duplicate_delivery_is_suppressed_at_the_caller():
    """dup=1.0 delivers every request frame twice: the handler runs
    twice (the wire really duplicated), but the caller observes exactly
    one reply — the second reply's rid finds no pending slot."""
    svc = _CountingSvc()
    server, client = _pair(svc, timeout=2.0)
    try:
        nc.activate("t>svc=dup=1.0")
        assert client.call("nc_count", v=7)["v"] == 7
        deadline = time.monotonic() + 2.0
        while svc.calls < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert svc.calls == 2
        assert nc.injected_count("dup") >= 1
    finally:
        client.close()
        server.stop()


def test_latency_policy_delays_round_trip():
    svc = _CountingSvc()
    server, client = _pair(svc, timeout=5.0)
    try:
        nc.activate("t>svc=lat=60")
        t0 = time.monotonic()
        assert client.call("nc_count", v=1)["v"] == 1
        assert time.monotonic() - t0 >= 0.055
    finally:
        client.close()
        server.stop()


# ---------------------------------------------------------------------------
# epoch / attempt fencing (fake frames against a real DaemonHandle)
# ---------------------------------------------------------------------------

class _NullSvc:
    def handle_nc_never(self, conn, rid, msg):
        return rpc.HOLD     # park forever: the reply is never sent


rpc.declare("nc_never")


def _fresh_handle():
    from ray_tpu._private.cluster import ArenaCache, DaemonHandle
    from ray_tpu._private.ids import NodeID
    server = rpc.Server(_NullSvc()).start()
    handle = DaemonHandle(NodeID.from_random(), server.addr, None,
                          ArenaCache())
    handle._fence_supported = True
    return server, handle


def _fenced_total(kind):
    from ray_tpu.util import metrics
    text = metrics.prometheus_text()
    for line in text.splitlines():
        if (line.startswith("ray_tpu_fenced_results_total")
                and f'kind="{kind}"' in line):
            return float(line.rsplit(" ", 1)[1])
    return 0.0


def test_stale_epoch_frame_is_fenced():
    server, handle = _fresh_handle()
    try:
        handle.epoch = 2
        slot = [threading.Event(), None, 0]
        handle._batch_waiters["t1"] = slot
        before = _fenced_total("epoch")
        # epoch 1 outcome from the superseded incarnation: fenced, and
        # the waiter stays armed for the live incarnation's outcome
        handle._ingest_batch([{"task": "t1", "ep": 1, "att": 0,
                               "outcome": "ok"}])
        assert not slot[0].is_set()
        assert "t1" in handle._batch_waiters
        assert _fenced_total("epoch") == before + 1
        # the live epoch's outcome resolves normally
        handle._ingest_batch([{"task": "t1", "ep": 2, "att": 0,
                               "outcome": "ok"}])
        assert slot[0].is_set() and slot[1]["ep"] == 2
        assert "t1" not in handle._batch_waiters
    finally:
        handle.mark_dead()
        server.stop()


def test_stale_attempt_outcome_is_fenced():
    server, handle = _fresh_handle()
    try:
        handle.epoch = 1
        slot = [threading.Event(), None, 1]     # live attempt = 1
        handle._batch_waiters["t2"] = slot
        before = _fenced_total("attempt")
        handle._ingest_batch([{"task": "t2", "ep": 1, "att": 0,
                               "outcome": "ok"}])
        assert not slot[0].is_set()             # attempt 0 replay fenced
        assert _fenced_total("attempt") == before + 1
        handle._ingest_batch([{"task": "t2", "ep": 1, "att": 1,
                               "outcome": "ok"}])
        assert slot[0].is_set() and slot[1]["att"] == 1
    finally:
        handle.mark_dead()
        server.stop()


def test_unfenced_daemon_frames_pass_through():
    """Frames from a pre-fence daemon carry no stamps and must resolve
    exactly as before (capability negotiation keeps old peers working).
    Frames are also never fenced when the hello lacked the capability,
    even if something resembling a stamp appears."""
    server, handle = _fresh_handle()
    try:
        handle._fence_supported = False
        handle.epoch = 5
        slot = [threading.Event(), None, 1]
        handle._batch_waiters["t3"] = slot
        handle._ingest_batch([{"task": "t3", "ep": 1, "outcome": "ok"}])
        assert slot[0].is_set()
    finally:
        handle.mark_dead()
        server.stop()


def test_stale_stream_push_is_fenced():
    server, handle = _fresh_handle()
    try:
        handle.epoch = 3

        class _Q:
            def __init__(self):
                self.items = []

            def put(self, x):
                self.items.append(x)

        class _Stream:
            def __init__(self):
                self.q = _Q()

        stream = _Stream()
        handle._streams["s1"] = stream
        handle._on_push("task_yield", {"task": "s1", "ep": 2, "v": 1})
        assert stream.q.items == []         # stale incarnation: dropped
        handle._on_push("task_yield", {"task": "s1", "ep": 3, "v": 2})
        assert len(stream.q.items) == 1
    finally:
        handle._streams.clear()
        handle.mark_dead()
        server.stop()


def test_late_stamped_frame_after_death_counts_dead():
    server, handle = _fresh_handle()
    try:
        handle.epoch = 1
        handle.mark_dead()
        before = _fenced_total("dead")
        handle._ingest_batch([{"task": "tX", "ep": 1, "att": 0,
                               "outcome": "ok"}])
        assert _fenced_total("dead") == before + 1
    finally:
        server.stop()


def test_mark_dead_fails_inflight_rpc():
    """Timeout audit: a one-way partition leaves classic timeout=None
    callers blocked — mark_dead (driven by the head's death-mark) must
    fail them with a typed error instead of wedging the thread."""
    from ray_tpu._private.cluster import DaemonCrashed
    server, handle = _fresh_handle()
    try:
        got = {}

        def call():
            try:
                handle._call("nc_never")    # no handler: blocks forever
            except (DaemonCrashed, rpc.RpcError, rpc.RemoteError) as e:
                got["err"] = e

        t = threading.Thread(target=call, daemon=True)
        t.start()
        time.sleep(0.2)                     # let the call get in flight
        handle.mark_dead()
        t.join(timeout=3.0)
        assert not t.is_alive()
        assert isinstance(got.get("err"), DaemonCrashed)
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# head-side epoch minting + persistence
# ---------------------------------------------------------------------------

class _FakeConn:
    def __init__(self):
        self.meta = {}

    def link(self, *a, **kw):
        return self


def _register(svc, node_id="n1"):
    return svc.handle_register_node(
        _FakeConn(), 0, {"node_id": node_id,
                         "resources": {"CPU": 1.0}, "labels": {},
                         "addr": ["127.0.0.1", 1]})


def test_head_mints_monotonic_epochs(tmp_path):
    from ray_tpu._private.head import HeadService
    path = str(tmp_path / "head_state.db")
    svc = HeadService(state_path=path)
    try:
        out1 = _register(svc)
        out2 = _register(svc)
        assert out1["epoch"] == 1 and out2["epoch"] == 2
        # stale-epoch heartbeat: the zombie incarnation is told to exit
        # and must NOT refresh the live incarnation's liveness
        beat = svc.handle_heartbeat(
            _FakeConn(), 0, {"node_id": "n1", "epoch": 1,
                             "available": {"CPU": 1.0}, "wall_ts": 0.0})
        assert beat.get("dead") and beat.get("stale_epoch")
        live = svc.handle_heartbeat(
            _FakeConn(), 0, {"node_id": "n1", "epoch": 2,
                             "available": {"CPU": 1.0}, "wall_ts": 0.0})
        assert live.get("ok")
    finally:
        svc._stop.set()

    # epochs survive a head restart: the next mint is STRICTLY higher
    svc2 = HeadService(state_path=path)
    try:
        assert _register(svc2)["epoch"] == 3
    finally:
        svc2._stop.set()


def test_membership_view_carries_epoch(tmp_path):
    from ray_tpu._private.head import HeadService
    svc = HeadService(state_path=str(tmp_path / "h.db"))
    try:
        _register(svc, "nA")
        view = svc._nodes["nA"].view()
        assert view["epoch"] == 1
    finally:
        svc._stop.set()


# ---------------------------------------------------------------------------
# audits: unbounded control-plane round trips + wall-clock liveness
# ---------------------------------------------------------------------------

# Every explicit `timeout=None` .call/._call round trip in
# ray_tpu/_private must be justified here. Entries are
# (file, method-or-None-for-dynamic): a new unbounded site fails this
# test; so does removing one (keep the list honest).
_UNBOUNDED_ALLOWLIST = {
    # classic submit_task compat path: the REPLY carries the task
    # outcome, so the round trip is task-duration by design; a wedged
    # link is bounded by the head's death-mark -> mark_dead ->
    # client._fail_all (test_mark_dead_fails_inflight_rpc)
    ("cluster.py", "submit_task"),
    # DaemonHandle._call forwards arbitrary methods, some of which
    # (classic submit) are task-duration; same death-mark bound
    ("cluster.py", None),
    # daemon -> driver core_op forwarding: object-availability waits
    # are data-dependent (ray.get semantics); the owner connection's
    # reader exit fails all pending slots on transport death
    ("daemon.py", "core_op"),
    # head pubsub long-poll: parks at the head until an event arrives,
    # unbounded by design; subscriber threads are torn down via close()
    ("head.py", "subscribe"),
}


def _call_sites_with_timeout_none(path):
    """(file, first-positional-literal-or-None) for every X.call/_call
    with an explicit timeout=None keyword."""
    with open(path, "r", encoding="utf-8") as f:
        tree = ast.parse(f.read())
    sites = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not (isinstance(func, ast.Attribute)
                and func.attr in ("call", "_call")):
            continue
        has_none = any(
            kw.arg == "timeout" and isinstance(kw.value, ast.Constant)
            and kw.value.value is None for kw in node.keywords)
        if not has_none:
            continue
        method = None
        if node.args and isinstance(node.args[0], ast.Constant):
            method = node.args[0].value
        sites.add((os.path.basename(path), method))
    return sites


def test_no_unbounded_control_plane_round_trips():
    found = set()
    for name in sorted(os.listdir(_PRIVATE)):
        if name.endswith(".py"):
            found |= _call_sites_with_timeout_none(
                os.path.join(_PRIVATE, name))
    assert found == _UNBOUNDED_ALLOWLIST, (
        f"unjustified timeout=None round trips: "
        f"{found - _UNBOUNDED_ALLOWLIST}; "
        f"stale allowlist entries: {_UNBOUNDED_ALLOWLIST - found}")


def test_liveness_paths_never_compare_wall_clock():
    """head.py/daemon.py liveness (heartbeat expiry, drain deadlines)
    must compare time.monotonic(), never time.time(): a wall-clock step
    (NTP slew, VM migration) must not mass-expire heartbeats. Wall
    clock is allowed in arithmetic (clock-offset estimates, persisted
    deadlines) but never inside a comparison."""
    def wall_compares(path):
        with open(path, "r", encoding="utf-8") as f:
            tree = ast.parse(f.read())
        bad = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Compare):
                continue
            for sub in ast.walk(node):
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "time"
                        and isinstance(sub.func.value, ast.Name)
                        and sub.func.value.id == "time"):
                    bad.append(node.lineno)
        return bad

    for name in ("head.py", "daemon.py"):
        assert wall_compares(os.path.join(_PRIVATE, name)) == [], name
