"""Flagship benchmark: Llama training-step throughput on the local chip(s).

Prints ONE JSON line: tokens/sec/chip on a Llama-family model sized to the
available memory, plus model FLOPs utilization (MFU) as ``vs_baseline``
(the reference repo publishes no tok/s numbers — BASELINE.md — so the
hardware roofline is the honest denominator).

Robustness contract (VERDICT r1 #1b): the TPU backend may fail or *hang*
on init, so the WHOLE benchmark runs in a child subprocess under a
timeout; the parent retries flaky backend failures with backoff and, on
persistent failure, re-runs the child on CPU so one JSON line (with an
explicit ``"error"`` field) is always emitted, exit code 0.

Modes:
  BENCH_SERVE=1    — serving benchmark (p50 TTFT + output tok/s) instead
                     of the training benchmark.
Knobs:
  BENCH_ATTEMPTS   — accelerator attempts before CPU fallback (default 2)
  BENCH_TIMEOUT    — per-attempt timeout, seconds (default 1200)
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

# Error signatures that are plausibly transient backend-init failures and
# worth retrying; anything else (e.g. ImportError) is deterministic.
_RETRYABLE = ("UNAVAILABLE", "Unavailable", "backend", "DEADLINE_EXCEEDED",
              "INTERNAL", "tunnel")


def _roofline_flops(device) -> float:
    """Peak bf16 FLOP/s for known TPU generations (per chip)."""
    kind = getattr(device, "device_kind", "").lower()
    table = {
        "v5e": 394e12, "v5 lite": 394e12, "v5litepod": 394e12,
        "v5p": 459e12,
        "v4": 275e12,
        "v6e": 918e12, "trillium": 918e12,
        "v3": 123e12,
        "v2": 45e12,
    }
    for key, val in table.items():
        if key in kind:
            return val
    env_gen = os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()
    for key, val in table.items():
        if key in env_gen:
            return val
    return 275e12  # conservative default


def _run_train(error: str | None) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models.llama import LlamaConfig, LlamaModel
    from ray_tpu.train.spmd import make_train_step

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"

    if on_tpu:
        cfg = LlamaConfig.bench_400m()
        batch, seq = 8, 2048
        steps, warmup = 20, 3
    else:  # CPU smoke path so bench.py always emits a line
        cfg = LlamaConfig.debug(vocab_size=512, max_seq_len=256)
        batch, seq = 2, 256
        steps, warmup = 3, 1

    model = LlamaModel(cfg)
    ts = make_train_step(model)
    params, opt_state = ts.init_fn(jax.random.key(0))

    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)),
                         jnp.int32)
    targets = jnp.roll(tokens, -1, axis=1)
    bt = (tokens, targets)

    for _ in range(warmup):
        params, opt_state, metrics = ts.step_fn(params, opt_state, bt)
    jax.block_until_ready(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, metrics = ts.step_fn(params, opt_state, bt)
    jax.block_until_ready(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * steps / dt
    n_params = cfg.num_params()
    # MFU convention: 6*N useful FLOPs/token (fwd 2N + bwd 4N); remat
    # recompute is NOT counted as useful work.
    mfu = (tokens_per_sec * 6 * n_params / _roofline_flops(dev)
           if on_tpu else 0.0)

    out = {
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu, 4),
        "detail": {
            "model_params": n_params,
            "config": "llama_400m" if on_tpu else "debug",
            "batch": batch, "seq": seq, "steps": steps,
            "device": getattr(dev, "device_kind", dev.platform),
            "step_ms": round(dt / steps * 1000, 2),
            "loss": float(metrics["loss"]),
        },
    }
    if error:
        out["error"] = error
    return out


def _child() -> int:
    """Run the actual benchmark and print its JSON line."""
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        # Env vars alone lose to sitecustomize re-pinning JAX_PLATFORMS;
        # the config-level override must happen inside this process.
        from ray_tpu._private.platform import force_cpu_platform
        force_cpu_platform()
    serve_mode = os.environ.get("BENCH_SERVE") == "1"
    error = os.environ.get("BENCH_ERROR") or None
    if serve_mode:
        from ray_tpu.llm.bench import run_serving_bench
        result = run_serving_bench(error=error)
    else:
        result = _run_train(error)
    print(json.dumps(result))
    return 0


def main() -> int:
    if "--child" in sys.argv:
        return _child()

    serve_mode = os.environ.get("BENCH_SERVE") == "1"
    attempts = int(os.environ.get("BENCH_ATTEMPTS", "2"))
    # 900s covers first-compile (~40s) + 20 timed steps with margin; a
    # HUNG tunnel otherwise burns attempts x timeout before the CPU
    # fallback can emit the line
    timeout = int(os.environ.get("BENCH_TIMEOUT", "900"))
    cmd = [sys.executable, os.path.abspath(__file__), "--child"]

    def try_once(env, t) -> tuple[str | None, str, bool]:
        """Returns (json_line, error, retryable). The child runs in its
        own session so a hung TPU init (possibly with helper grandchildren
        holding the stdout pipe) can be killed as a whole process group —
        plain subprocess.run would block forever in communicate()."""
        import signal
        proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, start_new_session=True,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        try:
            stdout, stderr = proc.communicate(timeout=t)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                proc.kill()
            try:
                proc.communicate(timeout=10)
            except subprocess.TimeoutExpired:
                pass
            return None, f"benchmark timed out after {t}s", True
        lines = [ln for ln in stdout.splitlines() if ln.strip()]
        if proc.returncode == 0 and lines:
            try:
                json.loads(lines[-1])
                return lines[-1], "", False
            except ValueError:
                pass
        err = (stderr or stdout or "").strip()[-400:]
        return None, err, any(sig in err for sig in _RETRYABLE)

    err = ""
    for attempt in range(attempts):
        line, err, retryable = try_once(os.environ.copy(), timeout)
        if line is not None:
            print(line)
            return 0
        if not retryable:
            break
        if attempt + 1 < attempts:
            time.sleep(15 * (attempt + 1))

    # Persistent accelerator failure: emit the line from a CPU child.
    env = os.environ.copy()
    env["BENCH_FORCE_CPU"] = "1"
    env["BENCH_ERROR"] = f"tpu backend unavailable: {err}"[:500]
    line, cpu_err, _ = try_once(env, 420)  # tiny debug config: fast
    if line is not None:
        print(line)
        return 0
    print(json.dumps({
        "metric": ("llm_serve_output_tokens_per_sec" if serve_mode
                   else "llama_train_tokens_per_sec_per_chip"),
        "value": 0.0,
        "unit": "tokens/s" if serve_mode else "tokens/s/chip",
        "vs_baseline": 0.0,
        "error": f"tpu: {err} | cpu fallback: {cpu_err}"[:700],
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
