"""Flagship benchmark: Llama training-step throughput on the local chip(s).

Prints ONE JSON line: tokens/sec/chip on a Llama-family model sized to the
available memory, plus model FLOPs utilization (MFU) as ``vs_baseline``
(the reference repo publishes no tok/s numbers — BASELINE.md — so the
hardware roofline is the honest denominator).

Robustness contract (VERDICT r2 #1): the TPU backend may fail or *hang*
on init, so the WHOLE benchmark runs in a child subprocess under a
timeout — and the parent itself is bounded by one TOTAL wall-clock
deadline (``BENCH_TOTAL_DEADLINE``, default 540 s) sized to fit inside
the driver's outer timeout.  Budget layout: one accelerator attempt
capped at ~300 s, then immediately the CPU-fallback child with whatever
remains (>=120 s reserved), then a last-resort inline JSON line.  The
child emits heartbeat lines on stderr ("HB <stage>") so a timed-out run
leaves a diagnosable tail instead of silence.

Modes:
  BENCH_SERVE=1          — serving benchmark: OPEN-LOOP load through
                           ray_tpu.loadgen against a Serve app
                           (serving.requests_per_second +
                           serving.ttft_p50_s/p99_s in the json)
                           instead of the training benchmark.
  BENCH_SERVE_HTTP=1     — proxy-level serving benchmark: the same
                           metrics measured at an HTTP client through
                           the asyncio ingress (full serving path).
Knobs:
  BENCH_TOTAL_DEADLINE   — total wall-clock budget, seconds (default 540)
  BENCH_TIMEOUT          — accelerator-attempt cap, seconds (default 300)
  BENCH_ATTEMPTS         — accelerator attempts if budget allows (default 1)
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time

# Error signatures that are plausibly transient backend-init failures and
# worth retrying when the budget allows; anything else is deterministic.
_RETRYABLE = ("UNAVAILABLE", "Unavailable", "backend", "DEADLINE_EXCEEDED",
              "INTERNAL", "tunnel")

_CPU_RESERVE = 120  # seconds kept back for the CPU-fallback child

# The axon PJRT plugin dials the relayed TPU terminal on these loopback
# ports (stateless InitRequest :8083, session :8082 — see
# tools/evidence/tpu_init_hang_r4.log). When the tunnel is down the
# plugin retries connecting FOREVER inside PJRT_Client_Create (no
# claim timeout), which is the "hang at importing jax backend" of
# rounds 1-3. A TCP preflight turns that into a fast, explained skip.
_TUNNEL_PORTS = (8083, 8082)


def _tunnel_up(timeout: float = 3.0) -> bool:
    """True only when EVERY terminal port accepts: a half-up tunnel
    (init :8083 alive, session :8082 dead) would pass a weaker check
    and still hang the attempt at the first session RPC."""
    import socket

    for port in _TUNNEL_PORTS:
        try:
            with socket.create_connection(("127.0.0.1", port), timeout):
                pass
        except OSError:
            return False
    return True


def _backend_up(timeout_s: int = 60) -> bool:
    """Deep preflight: actually initialize the PJRT backend in a
    throwaway child. The r4 evidence shows a HALF-UP relay that accepts
    TCP while PJRT init hangs forever — this catches it for ~60s
    instead of burning a whole 300s attempt (healthy cost ~10-15s)."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; d=jax.devices()[0]; "
             "assert d.platform != 'cpu'"],
            timeout=timeout_s, capture_output=True,
            start_new_session=True)
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False
    except Exception:
        return False


def _hb(stage: str) -> None:
    """Heartbeat on stderr: survives in the captured tail if we get killed."""
    print(f"HB {time.strftime('%H:%M:%S')} {stage}", file=sys.stderr, flush=True)


def _roofline_flops(device) -> float:
    """Peak bf16 FLOP/s for known TPU generations (per chip)."""
    kind = getattr(device, "device_kind", "").lower()
    table = {
        "v5e": 394e12, "v5 lite": 394e12, "v5litepod": 394e12,
        "v5p": 459e12,
        "v4": 275e12,
        "v6e": 918e12, "trillium": 918e12,
        "v3": 123e12,
        "v2": 45e12,
    }
    for key, val in table.items():
        if key in kind:
            return val
    env_gen = os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()
    for key, val in table.items():
        if key in env_gen:
            return val
    return 275e12  # conservative default


def _run_train(error: str | None) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models.llama import LlamaConfig, LlamaModel
    from ray_tpu.train.spmd import make_train_step

    _hb("importing jax backend")
    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    _hb(f"backend acquired: {getattr(dev, 'device_kind', dev.platform)}")

    if on_tpu:
        cfg = LlamaConfig.bench_400m()
        batch, seq = 8, 2048
        steps, warmup = 10, 2
        # A/B knobs (profiling evidence drives the committed defaults)
        batch = int(os.environ.get("BENCH_BATCH", batch))
        import dataclasses
        if os.environ.get("BENCH_REMAT") == "0":
            cfg = dataclasses.replace(cfg, remat=False)
        if os.environ.get("BENCH_REMAT_POLICY"):
            cfg = dataclasses.replace(
                cfg, remat_policy=os.environ["BENCH_REMAT_POLICY"])
        if os.environ.get("BENCH_ATTN"):
            cfg = dataclasses.replace(
                cfg, attention_impl=os.environ["BENCH_ATTN"])
        if os.environ.get("BENCH_FBQ"):
            cfg = dataclasses.replace(
                cfg, flash_block_q=int(os.environ["BENCH_FBQ"]),
                flash_block_k=int(os.environ.get(
                    "BENCH_FBK", os.environ["BENCH_FBQ"])))
    else:  # CPU smoke path so bench.py always emits a line
        cfg = LlamaConfig.debug(vocab_size=512, max_seq_len=256)
        batch, seq = 2, 256
        steps, warmup = 3, 1

    model = LlamaModel(cfg)
    ts = make_train_step(model)
    params, opt_state = ts.init_fn(jax.random.key(0))
    _hb("params initialized")

    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)),
                         jnp.int32)
    targets = jnp.roll(tokens, -1, axis=1)
    bt = (tokens, targets)

    for i in range(warmup):
        params, opt_state, metrics = ts.step_fn(params, opt_state, bt)
        jax.block_until_ready(metrics["loss"])
        _hb(f"warmup step {i} done" + (" (compiled)" if i == 0 else ""))

    t0 = time.perf_counter()
    for i in range(steps):
        params, opt_state, metrics = ts.step_fn(params, opt_state, bt)
    jax.block_until_ready(metrics["loss"])
    dt = time.perf_counter() - t0
    _hb(f"timed {steps} steps in {dt:.2f}s")

    tokens_per_sec = batch * seq * steps / dt
    n_params = cfg.num_params()
    # MFU convention: 6*N useful FLOPs/token (fwd 2N + bwd 4N); remat
    # recompute is NOT counted as useful work.
    mfu = (tokens_per_sec * 6 * n_params / _roofline_flops(dev)
           if on_tpu else 0.0)

    out = {
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu, 4),
        "platform": dev.platform,
        "tpu_fallback": not on_tpu,
        "detail": {
            "model_params": n_params,
            "config": "llama_400m" if on_tpu else "debug",
            "attention_impl": cfg.attention_impl,
            "batch": batch, "seq": seq, "steps": steps,
            "device": getattr(dev, "device_kind", dev.platform),
            "step_ms": round(dt / steps * 1000, 2),
            "loss": float(metrics["loss"]),
        },
    }
    if error:
        out["error"] = error
    return out


def _control_plane_probe(duration_s: float = 1.5,
                         drain_n: int = 2000) -> dict:
    """Quick control-plane throughput sample so every BENCH_*.json
    tracks the task-dispatch envelope alongside tokens/s: round-trip
    tasks/s through the full submit→schedule→execute→get loop, plus a
    queued submit-then-drain burst whose drain rate is the bottleneck
    the result pipeline targets (ROADMAP item 4 — the trajectory files
    finally track it). Bounded and best-effort: a failure must never
    cost the benchmark its tokens/s line."""
    out = {"tasks_per_second": 0.0, "drain_tasks_per_second": 0.0}
    own = False
    try:
        import ray_tpu

        own = not ray_tpu.is_initialized()
        if own:
            ray_tpu.init(num_nodes=1, resources={"CPU": 4})

        @ray_tpu.remote
        def _noop():
            return None

        ray_tpu.get([_noop.remote() for _ in range(50)])    # warm
        t0 = time.perf_counter()
        count = 0
        while time.perf_counter() - t0 < duration_s:
            ray_tpu.get([_noop.remote() for _ in range(100)])
            count += 100
        out["tasks_per_second"] = round(
            count / (time.perf_counter() - t0), 1)
        # queued drain: submit without consuming, then time the drain
        # leg alone (timing from before the submit loop would fold the
        # submit phase into the reported drain rate). Bounded: a wedged
        # drain path must degrade this row to 0, never hang the
        # benchmark's tokens/s line (GetTimeoutError -> except below).
        refs = [_noop.remote() for _ in range(drain_n)]
        t1 = time.perf_counter()
        ray_tpu.get(refs, timeout=120.0)
        out["drain_tasks_per_second"] = round(
            drain_n / (time.perf_counter() - t1), 1)
        return out
    except Exception:
        return out
    finally:
        if own:     # never leak the probe's own cluster on a failure
            try:
                ray_tpu.shutdown()
            except Exception:
                pass


def _objects_probe(seconds_per_size: float = 1.5) -> dict:
    """Object-plane throughput: worker-side put+get round trips at
    64KiB / 1MiB / 16MiB, reported as MiB/s moved (put + get both move
    the payload). This is the zero-copy object plane's headline row
    (docs/object_plane.md): with the shm arena attached, the 1MiB+
    points write/read the node arena in place instead of round-tripping
    pickles through daemon RPC. Best-effort and bounded: a failure must
    never cost the benchmark its tokens/s line."""
    out = {"put_get_64KiB_mbps": 0.0, "put_get_1MiB_mbps": 0.0,
           "put_get_16MiB_mbps": 0.0}
    own = False
    try:
        import ray_tpu

        own = not ray_tpu.is_initialized()
        if own:
            ray_tpu.init(num_nodes=1, resources={"CPU": 4})

        @ray_tpu.remote
        def _put_get_loop(nbytes, seconds):
            import time as _time

            import numpy as _np

            import ray_tpu as _rt
            a = _np.ones(nbytes // 4, dtype=_np.float32)
            r = _rt.put(a)
            _rt.get([r])        # warm the path
            n = 0
            t0 = _time.perf_counter()
            while _time.perf_counter() - t0 < seconds:
                r = _rt.put(a)
                b = _rt.get([r])[0]
                assert b.nbytes == nbytes
                del b, r
                n += 1
            return n, _time.perf_counter() - t0

        for size, label in ((64 << 10, "put_get_64KiB_mbps"),
                            (1 << 20, "put_get_1MiB_mbps"),
                            (16 << 20, "put_get_16MiB_mbps")):
            ref = _put_get_loop.remote(size, seconds_per_size)
            n, dt = ray_tpu.get(ref, timeout=60.0)
            out[label] = round((n * size * 2) / dt / (1 << 20), 1)
        return out
    except Exception:
        return out
    finally:
        if own:
            try:
                ray_tpu.shutdown()
            except Exception:
                pass


def _multitenancy_probe(duration_s: float = 1.2) -> dict:
    """Fair-share sample: two equal-weight tenant jobs drive the
    control-plane loop concurrently through ``run_multi_job_load``
    (fairshare admission on), reported as Jain's fairness index over
    weight-normalized goodput plus the cross-job E2E p99 ratio
    (docs/multitenancy.md). Best-effort and bounded: a failure must
    never cost the benchmark its tokens/s line."""
    out = {"fairness_index": 0.0, "isolation_p99_ratio": 0.0,
           "fairshare_enabled": False}
    own = False
    try:
        import ray_tpu
        from ray_tpu.loadgen import SLO, LoadSpec, run_multi_job_load

        own = not ray_tpu.is_initialized()
        if own:
            ray_tpu.init(num_nodes=1, resources={"CPU": 4},
                         _system_config={"fairshare": True})

        @ray_tpu.remote
        def _unit():
            return None

        def target(payload, rec, t0):
            ray_tpu.get(_unit.remote(), timeout=30.0)
            now = time.perf_counter() - t0
            rec.first_token_at = now
            rec.finished_at = now
            rec.output_tokens = 1

        ray_tpu.get([_unit.remote() for _ in range(20)])    # warm
        spec = LoadSpec(rate=120.0, duration_s=duration_s, clients=6,
                        prompt_len=1, output_len=1, stream=False,
                        timeout_s=30.0, drain_timeout_s=60.0,
                        slo=SLO(ttft_s=10.0, e2e_s=10.0))
        rep = run_multi_job_load(target, spec, jobs=2,
                                 weights=[1.0, 1.0],
                                 job_prefix="bench-tenant")
        mt = rep["multitenancy"]
        out["fairness_index"] = round(float(mt["fairness_index"]), 4)
        out["isolation_p99_ratio"] = round(
            float(mt["isolation_p99_ratio"]), 3)
        from ray_tpu._private import worker as _worker
        rt = _worker.global_runtime()
        ten = getattr(rt, "tenancy", None)
        out["fairshare_enabled"] = bool(ten is not None and ten.enabled)
        return out
    except Exception:
        return out
    finally:
        if own:
            try:
                ray_tpu.shutdown()
            except Exception:
                pass


def _tracing_overhead_probe() -> float:
    """Tracing overhead on the control-plane loop: balanced-order
    spans-on/spans-off pairs in one cluster, median of per-pair ratios
    (the methodology tools/perf_smoke.sh probe 4 uses; docs/
    observability.md budgets this at <=5%). Best-effort: a failure must
    never cost the benchmark its tokens/s line."""
    import statistics

    own = False
    prev_overrides = None
    try:
        import ray_tpu
        from ray_tpu._private import config as _config
        from ray_tpu._private.config import apply_system_config

        own = not ray_tpu.is_initialized()
        if own:
            ray_tpu.init(num_nodes=1, resources={"CPU": 4})
        # apply_system_config REPLACES the whole override table: capture
        # the caller's overrides so the probe's flag flips don't clobber
        # them (and a mid-probe failure can't leave tracing disabled)
        cur = _config._config
        prev_overrides = dict(cur._system) if cur is not None else {}

        @ray_tpu.remote
        def _noop():
            return None

        def burst() -> float:
            t0 = time.perf_counter()
            ray_tpu.get([_noop.remote() for _ in range(150)])
            return 150 / (time.perf_counter() - t0)

        ray_tpu.get([_noop.remote() for _ in range(50)])    # warm

        def flip(on: bool) -> None:
            apply_system_config({**prev_overrides, "task_trace": on})

        ratios = []
        for i in range(3):
            if i % 2 == 0:
                flip(True)
                r_on = burst()
                flip(False)
                r_off = burst()
            else:
                flip(False)
                r_off = burst()
                flip(True)
                r_on = burst()
            ratios.append(r_on / r_off)
        return round(
            max(0.0, (1.0 - statistics.median(ratios)) * 100.0), 1)
    except Exception:
        return 0.0
    finally:
        if prev_overrides is not None:
            try:
                from ray_tpu._private.config import apply_system_config
                apply_system_config(prev_overrides or None)
            except Exception:
                pass
        if own:
            try:
                ray_tpu.shutdown()
            except Exception:
                pass


def _child() -> int:
    """Run the actual benchmark and print its JSON line."""
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        # Env vars alone lose to sitecustomize re-pinning JAX_PLATFORMS;
        # the config-level override must happen inside this process.
        from ray_tpu._private.platform import force_cpu_platform
        force_cpu_platform()
    serve_mode = os.environ.get("BENCH_SERVE") == "1"
    error = os.environ.get("BENCH_ERROR") or None
    if os.environ.get("BENCH_SERVE_HTTP") == "1":
        from ray_tpu.llm.bench import run_http_proxy_bench
        result = run_http_proxy_bench(error=error)
    elif serve_mode:
        from ray_tpu.llm.bench import run_serving_bench
        result = run_serving_bench(error=error)
    else:
        result = _run_train(error)
    if os.environ.get("BENCH_CONTROL_PLANE", "1") != "0":
        from ray_tpu._private.config import cfg as _cfg
        result["control_plane"] = {
            **_control_plane_probe(),
            # which wire/dispatch core produced these rows — A/B runs
            # flip RAY_TPU_ASYNC_CORE and diff the same json key
            "async_core": bool(_cfg().async_core),
            # spans-on vs spans-off delta, paired + median-of-ratios in
            # ONE cluster (sequential unpaired probes are a noise
            # lottery on shared hosts — see tools/perf_smoke.sh probe 4)
            "tracing_overhead_pct": _tracing_overhead_probe(),
            # every section carries the platform stamp so a partial
            # json consumer can't mistake a CPU-fallback row for TPU
            "platform": result.get("platform", "unknown"),
            "tpu_fallback": result.get("tpu_fallback", True)}
        result["objects"] = {
            **_objects_probe(),
            "platform": result.get("platform", "unknown"),
            "tpu_fallback": result.get("tpu_fallback", True)}
        result["multitenancy"] = {
            **_multitenancy_probe(),
            "platform": result.get("platform", "unknown"),
            "tpu_fallback": result.get("tpu_fallback", True)}
    print(json.dumps(result))
    return 0


def _emit(line: str) -> int:
    """Print the final BENCH json line — and degrade LOUDLY, not
    silently, when it was produced on the CPU fallback (standing
    ROADMAP issue: rounds 1-5 shipped CPU numbers that read like TPU
    numbers)."""
    print(line)
    try:
        obj = json.loads(line)
    except ValueError:
        return 0
    if obj.get("tpu_fallback"):
        bar = "!" * 72
        print(
            f"{bar}\n"
            f"! BENCH RAN ON CPU FALLBACK "
            f"(platform={obj.get('platform', '?')}).\n"
            f"! These are NOT accelerator numbers — do not compare "
            f"against TPU rounds.\n"
            f"! error: {str(obj.get('error', 'none'))[:200]}\n"
            f"{bar}", file=sys.stderr, flush=True)
    return 0


def main() -> int:
    if "--child" in sys.argv:
        return _child()

    serve_mode = os.environ.get("BENCH_SERVE") == "1"
    total = int(os.environ.get("BENCH_TOTAL_DEADLINE", "540"))
    attempt_cap = int(os.environ.get("BENCH_TIMEOUT", "300"))
    # two preflight-gated attempts: a DOWN tunnel short-circuits both in
    # seconds, a FLAPPING one gets a second chance (r4 evidence: the
    # relay goes half-up and comes back)
    attempts = int(os.environ.get("BENCH_ATTEMPTS", "2"))
    deadline = time.monotonic() + total
    cmd = [sys.executable, os.path.abspath(__file__), "--child"]

    def remaining() -> float:
        return deadline - time.monotonic()

    def try_once(env, t) -> tuple[str | None, str, bool]:
        """Returns (json_line, error, retryable).  The child runs in its
        own session so a hung TPU init (possibly with helper grandchildren
        holding pipes open) can be killed as a whole process group.  The
        child's stdout/stderr go to temp FILES, not pipes, so a timeout
        still leaves a readable tail (heartbeats) behind."""
        t = max(5, int(t))
        with tempfile.TemporaryFile("w+") as fout, \
                tempfile.TemporaryFile("w+") as ferr:
            proc = subprocess.Popen(
                cmd, stdout=fout, stderr=ferr, text=True,
                env=env, start_new_session=True,
                cwd=os.path.dirname(os.path.abspath(__file__)))
            timed_out = False
            try:
                proc.wait(timeout=t)
            except subprocess.TimeoutExpired:
                timed_out = True
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    proc.kill()
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass
            fout.seek(0)
            ferr.seek(0)
            stdout = fout.read()
            stderr = ferr.read()
        heartbeats = [ln for ln in stderr.splitlines() if ln.startswith("HB ")]
        last_hb = heartbeats[-1] if heartbeats else "no heartbeat"
        if timed_out:
            return None, f"timed out after {t}s (last: {last_hb})", True
        lines = [ln for ln in stdout.splitlines() if ln.strip()]
        if proc.returncode == 0 and lines:
            try:
                json.loads(lines[-1])
                return lines[-1], "", False
            except ValueError:
                pass
        # Classify retryability on the traceback only — heartbeat lines
        # contain words like "backend" and would make every deterministic
        # failure look transient.
        no_hb = "\n".join(ln for ln in (stderr or stdout or "").splitlines()
                          if not ln.startswith("HB "))
        err = no_hb.strip()[-400:]
        return None, f"{err} (last: {last_hb})", any(
            sig in err for sig in _RETRYABLE)

    err = ""
    for attempt in range(attempts):
        if not _tunnel_up():
            err = ("tunnel down: 127.0.0.1:8083/:8082 closed (the axon "
                   "PJRT plugin would retry-connect forever; see "
                   "tools/evidence/tpu_init_hang_r4.log)")
            # a flapping relay may come back: re-probe while attempts
            # and budget remain instead of giving up on the first miss
            if attempt + 1 < attempts and remaining() > _CPU_RESERVE + 45:
                time.sleep(10)
                continue
            break
        budget = min(attempt_cap, remaining() - _CPU_RESERVE)
        if budget < 30:  # not enough room left for a real attempt
            err = err or "no budget left for accelerator attempt"
            break
        if not _backend_up(min(60, int(budget) // 2)):
            err = ("tunnel half-up: TCP ports accept but PJRT backend "
                   "init hangs (see tools/evidence/tpu_tunnel_flap_r4"
                   ".log)")
            if attempt + 1 < attempts and remaining() > _CPU_RESERVE + 45:
                time.sleep(10)
                continue
            break
        line, err, retryable = try_once(os.environ.copy(), budget)
        if line is not None:
            return _emit(line)
        if not retryable:
            break
        if attempt + 1 < attempts and remaining() > _CPU_RESERVE + 45:
            time.sleep(10)

    # Persistent accelerator failure: emit the line from a CPU child.
    env = os.environ.copy()
    env["BENCH_FORCE_CPU"] = "1"
    env["BENCH_ERROR"] = f"tpu backend unavailable: {err}"[:500]
    line, cpu_err, _ = try_once(env, max(60, remaining() - 10))
    if line is not None:
        return _emit(line)
    return _emit(json.dumps({
        "metric": ("llm_serve_requests_per_second" if serve_mode
                   else "llama_train_tokens_per_sec_per_chip"),
        "value": 0.0,
        "unit": "req/s" if serve_mode else "tokens/s/chip",
        "vs_baseline": 0.0,
        "platform": "none",
        "tpu_fallback": True,
        "error": f"tpu: {err} | cpu fallback: {cpu_err}"[:700],
    }))


if __name__ == "__main__":
    sys.exit(main())
