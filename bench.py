"""Flagship benchmark: Llama training-step throughput on the local chip(s).

Prints ONE JSON line: tokens/sec/chip on a Llama-family model sized to the
available memory, plus model FLOPs utilization (MFU) as ``vs_baseline``
(the reference repo publishes no tok/s numbers — BASELINE.md — so the
hardware roofline is the honest denominator).
"""

from __future__ import annotations

import json
import sys
import time


def _roofline_flops(device) -> float:
    """Peak bf16 FLOP/s for known TPU generations (per chip)."""
    kind = getattr(device, "device_kind", "").lower()
    table = {
        "v5e": 394e12, "v5 lite": 394e12, "v5litepod": 394e12,
        "v5p": 459e12,
        "v4": 275e12,
        "v6e": 918e12, "trillium": 918e12,
        "v3": 123e12,
        "v2": 45e12,
    }
    for key, val in table.items():
        if key in kind:
            return val
    return 275e12  # conservative default


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models.llama import LlamaConfig, LlamaModel
    from ray_tpu.train.spmd import make_train_step

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"

    if on_tpu:
        cfg = LlamaConfig.bench_400m()
        batch, seq = 8, 2048
        steps, warmup = 20, 3
    else:  # CPU smoke path so bench.py always emits a line
        cfg = LlamaConfig.debug(vocab_size=512, max_seq_len=256)
        batch, seq = 2, 256
        steps, warmup = 3, 1

    model = LlamaModel(cfg)
    ts = make_train_step(model)
    params, opt_state = ts.init_fn(jax.random.key(0))

    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)),
                         jnp.int32)
    targets = jnp.roll(tokens, -1, axis=1)
    bt = (tokens, targets)

    for _ in range(warmup):
        params, opt_state, metrics = ts.step_fn(params, opt_state, bt)
    jax.block_until_ready(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, metrics = ts.step_fn(params, opt_state, bt)
    jax.block_until_ready(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * steps / dt
    n_params = cfg.num_params()
    # MFU convention: 6*N useful FLOPs/token (fwd 2N + bwd 4N); remat
    # recompute is NOT counted as useful work.
    mfu = (tokens_per_sec * 6 * n_params / _roofline_flops(dev)
           if on_tpu else 0.0)

    print(json.dumps({
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu, 4),
        "detail": {
            "model_params": n_params,
            "config": "llama_400m" if on_tpu else "debug",
            "batch": batch, "seq": seq, "steps": steps,
            "device": getattr(dev, "device_kind", dev.platform),
            "step_ms": round(dt / steps * 1000, 2),
            "loss": float(metrics["loss"]),
        },
    }))


if __name__ == "__main__":
    sys.exit(main())
