"""`ray-tpu` CLI (reference: `python/ray/scripts/scripts.py` — status,
memory, timeline, microbenchmark; `ray job` CLI in
`dashboard/modules/job/cli.py`)."""

from __future__ import annotations

import argparse
import json
import sys


def _init_runtime(args):
    import ray_tpu
    if not ray_tpu.is_initialized():
        ray_tpu.init(num_nodes=args.num_nodes)
    return ray_tpu


def cmd_status(args) -> int:
    ray_tpu = _init_runtime(args)
    from ray_tpu.util import state as st
    print(json.dumps({
        "nodes": st.list_nodes(),
        "cluster_resources": ray_tpu.cluster_resources(),
        "available_resources": ray_tpu.available_resources(),
    }, indent=2, default=str))
    return 0


def cmd_summary(args) -> int:
    _init_runtime(args)
    from ray_tpu.util import state as st
    print(json.dumps({"tasks": st.summarize_tasks(),
                      "actors": len(st.list_actors()),
                      "placement_groups": len(st.list_placement_groups())},
                     indent=2))
    return 0


def cmd_memory(args) -> int:
    ray_tpu = _init_runtime(args)
    from ray_tpu._private import worker as _worker
    rt = _worker.global_runtime()
    rows = []
    for node in rt.nodes():
        rows.append({"node_id": node.node_id.hex()[:16],
                     "used_bytes": node.store.used_bytes(),
                     "num_objects": len(node.store.object_ids()),
                     "stats": dict(node.store.stats)})
    print(json.dumps(rows, indent=2))
    return 0


def cmd_timeline(args) -> int:
    _init_runtime(args)
    from ray_tpu.util import state as st
    path = st.timeline(args.output)
    print(f"wrote chrome trace to {path}")
    return 0


def cmd_microbenchmark(args) -> int:
    from ray_tpu._private.perf import run_microbenchmarks
    for row in run_microbenchmarks(duration_s=args.duration):
        print(json.dumps(row))
    return 0


def cmd_dashboard(args) -> int:
    _init_runtime(args)
    from ray_tpu.dashboard import start_dashboard
    host, port = start_dashboard(port=args.port)
    print(f"dashboard at http://{host}:{port}")
    try:
        import time
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        return 0


def cmd_job_submit(args) -> int:
    _init_runtime(args)
    from ray_tpu.job import JobSubmissionClient
    client = JobSubmissionClient()
    job_id = client.submit_job(entrypoint=args.entrypoint)
    status = client.wait_until_finished(job_id, timeout=args.timeout)
    print(client.get_job_logs(job_id), end="")
    print(f"job {job_id}: {status}")
    return 0 if status == "SUCCEEDED" else 1


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="ray-tpu", description="ray_tpu cluster CLI")
    parser.add_argument("--num-nodes", type=int, default=1)
    sub = parser.add_subparsers(dest="command", required=True)

    sub.add_parser("status")
    sub.add_parser("summary")
    sub.add_parser("memory")
    p = sub.add_parser("timeline")
    p.add_argument("--output", default="/tmp/ray_tpu_timeline.json")
    p = sub.add_parser("microbenchmark")
    p.add_argument("--duration", type=float, default=2.0)
    p = sub.add_parser("dashboard")
    p.add_argument("--port", type=int, default=8265)
    p = sub.add_parser("job-submit")
    p.add_argument("entrypoint")
    p.add_argument("--timeout", type=float, default=300.0)

    args = parser.parse_args(argv)
    handler = {
        "status": cmd_status, "summary": cmd_summary,
        "memory": cmd_memory, "timeline": cmd_timeline,
        "microbenchmark": cmd_microbenchmark, "dashboard": cmd_dashboard,
        "job-submit": cmd_job_submit,
    }[args.command]
    return handler(args)


if __name__ == "__main__":
    sys.exit(main())
