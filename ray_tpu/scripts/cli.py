"""`ray-tpu` CLI (reference: `python/ray/scripts/scripts.py` —
start:676 / stop / status, memory, timeline, microbenchmark; `ray job`
CLI in `dashboard/modules/job/cli.py`).

Cluster lifecycle: ``ray-tpu start --head`` stands up a head + node
daemons as persistent OS processes (daemons survive driver disconnects);
any driver joins with ``ray_tpu.init(address="host:port")``; ``ray-tpu
stop`` tears the cluster down. The address of the last locally started
cluster is recorded in ``/tmp/ray_tpu/current_cluster.json`` so
``stop``/``status`` work without arguments.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

CLUSTER_FILE = "/tmp/ray_tpu/current_cluster.json"


def _init_runtime(args):
    import ray_tpu
    if not ray_tpu.is_initialized():
        ray_tpu.init(num_nodes=args.num_nodes)
    return ray_tpu


def cmd_status(args) -> int:
    ray_tpu = _init_runtime(args)
    from ray_tpu.util import state as st
    print(json.dumps({
        "nodes": st.list_nodes(),
        "cluster_resources": ray_tpu.cluster_resources(),
        "available_resources": ray_tpu.available_resources(),
    }, indent=2, default=str))
    return 0


def cmd_summary(args) -> int:
    _init_runtime(args)
    from ray_tpu.util import state as st
    print(json.dumps({"tasks": st.summarize_tasks(),
                      "actors": len(st.list_actors()),
                      "placement_groups": len(st.list_placement_groups())},
                     indent=2))
    return 0


def cmd_memory(args) -> int:
    ray_tpu = _init_runtime(args)
    from ray_tpu._private import worker as _worker
    rt = _worker.global_runtime()
    rows = []
    for node in rt.nodes():
        rows.append({"node_id": node.node_id.hex()[:16],
                     "used_bytes": node.store.used_bytes(),
                     "num_objects": len(node.store.object_ids()),
                     "stats": dict(node.store.stats)})
    print(json.dumps(rows, indent=2))
    return 0


def cmd_timeline(args) -> int:
    _init_runtime(args)
    from ray_tpu.util import state as st
    # merged cluster trace: one lane per process (driver / daemon /
    # worker), clock-corrected spans from the head's task-event store
    path = st.cluster_timeline(args.output)
    print(f"wrote chrome trace to {path}")
    return 0


def cmd_profile(args) -> int:
    """Cluster-wide stack profile: on-demand burst fan-out to every
    process (driver / daemons / workers) merged with the head's
    federated continuous aggregates, written as speedscope JSON (one
    lane per process — the profiling counterpart of `ray-tpu
    timeline`)."""
    _init_runtime(args)
    from ray_tpu.util import state as st
    node = args.node if not args.all else None
    out = st.cluster_profile(duration_s=args.duration, node=node,
                             path=args.output, fmt=args.format)
    for rec in out["records"]:
        print(f"  {rec['proc']:<24} {rec.get('mode', '?'):<10} "
              f"{rec.get('samples', 0):>6} samples")
    print(f"wrote {args.format} profile ({len(out['records'])} "
          f"processes) to {args.output}")
    return 0


def cmd_microbenchmark(args) -> int:
    from ray_tpu._private.perf import run_microbenchmarks
    for row in run_microbenchmarks(duration_s=args.duration):
        print(json.dumps(row))
    return 0


def cmd_dashboard(args) -> int:
    _init_runtime(args)
    from ray_tpu.dashboard import start_dashboard
    host, port = start_dashboard(port=args.port)
    print(f"dashboard at http://{host}:{port}")
    try:
        import time
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        return 0


def _resolve_address(args) -> str:
    addr = getattr(args, "address", None)
    if addr:
        return addr
    try:
        with open(CLUSTER_FILE) as f:
            return json.load(f)["address"]
    except (OSError, KeyError, ValueError):
        raise SystemExit(
            "no --address given and no local cluster recorded "
            f"({CLUSTER_FILE}); start one with `ray-tpu start --head`")


def cmd_start(args) -> int:
    """Stand up a persistent head + N node daemons (scripts.py:676)."""
    if not args.head:
        raise SystemExit("only --head mode is supported: pass --head "
                         "(joining remote workers use the daemon module "
                         "with --head host:port directly)")
    from ray_tpu._private.cluster import _spawn
    from ray_tpu._private.ids import NodeID

    session = os.path.join("/tmp", "ray_tpu",
                           f"cluster_{os.getpid()}")
    os.makedirs(session, exist_ok=True)
    head_args = ["--state-path", os.path.join(session, "head_state.db")]
    if args.port:
        head_args += ["--port", str(args.port)]
    head_proc, head_port = _spawn(
        "ray_tpu._private.head", head_args,
        output_path=os.path.join(session, "head.log"))
    address = f"127.0.0.1:{head_port}"

    resources = args.resources or json.dumps(
        {"CPU": float(os.cpu_count() or 4)})
    daemon_pids = []
    for _ in range(args.num_daemons):
        proc, _port = _spawn("ray_tpu._private.daemon", [
            "--head", address,
            "--node-id", NodeID.from_random().hex(),
            "--resources", resources,
            "--object-store-bytes", str(args.object_store_bytes),
            "--persist",
        ], output_path=os.path.join(session, "daemon.log"))
        daemon_pids.append(proc.pid)

    os.makedirs(os.path.dirname(CLUSTER_FILE), exist_ok=True)
    with open(CLUSTER_FILE, "w") as f:
        json.dump({"address": address, "head_pid": head_proc.pid,
                   "daemon_pids": daemon_pids, "session": session}, f)
    print(f"ray_tpu cluster started at {address} "
          f"({args.num_daemons} daemons)")
    print(f'connect with: ray_tpu.init(address="{address}")')
    if not args.block:
        return 0
    # --block: stay up and respawn a crashed head on the same port
    import time
    try:
        while True:
            time.sleep(0.5)
            if head_proc.poll() is not None:
                try:
                    head_proc, _ = _spawn(
                        "ray_tpu._private.head",
                        ["--state-path",
                         os.path.join(session, "head_state.db"),
                         "--port", str(head_port)],
                        output_path=os.path.join(session, "head.log"))
                except (RuntimeError, OSError):
                    continue
                try:   # keep stop's pid fallback pointing at the LIVE head
                    with open(CLUSTER_FILE) as f:
                        rec = json.load(f)
                    rec["head_pid"] = head_proc.pid
                    with open(CLUSTER_FILE, "w") as f:
                        json.dump(rec, f)
                except (OSError, ValueError):
                    pass
    except KeyboardInterrupt:
        return cmd_stop(args)


def cmd_stop(args) -> int:
    """Tear down the cluster recorded in the cluster file (or at
    --address): stop every registered daemon, then the head."""
    import signal

    address = _resolve_address(args)
    host, port = address.rsplit(":", 1)
    from ray_tpu._private import rpc
    from ray_tpu._private.head import HeadClient
    from ray_tpu._private.rpc import Client

    stopped = 0
    try:
        head = HeadClient((host, int(port)))
        for info in head.list_nodes():
            if not info["alive"]:
                continue
            try:
                Client(tuple(info["addr"]), timeout=5.0).call(
                    "daemon_stop", timeout=2.0)
                stopped += 1
            except (rpc.RpcError, OSError):
                pass
        head.stop_head()
        head.close()
    except (OSError, rpc.RpcError):
        # head already gone: fall back to recorded pids
        try:
            with open(CLUSTER_FILE) as f:
                rec = json.load(f)
            for pid in [rec.get("head_pid"), *rec.get("daemon_pids", [])]:
                if pid:
                    try:
                        os.kill(pid, signal.SIGKILL)
                    except (OSError, ProcessLookupError):
                        pass
        except (OSError, ValueError):
            pass
    try:
        os.unlink(CLUSTER_FILE)
    except OSError:
        pass
    print(f"stopped cluster at {address} ({stopped} daemons)")
    return 0


def cmd_cluster_status(args) -> int:
    """Membership of a running cluster (no runtime init)."""
    address = _resolve_address(args)
    host, port = address.rsplit(":", 1)
    from ray_tpu._private.head import HeadClient

    head = HeadClient((host, int(port)))
    nodes = head.list_nodes()
    head.close()
    print(json.dumps({"address": address, "nodes": nodes}, indent=2,
                     default=str))
    return 0


def cmd_drain(args) -> int:
    """Gracefully drain a node of a running cluster (no runtime init):
    the head fences new placements, connected drivers migrate work off,
    and the deadline escalates to the death path."""
    address = _resolve_address(args)
    host, port = address.rsplit(":", 1)
    from ray_tpu._private.head import HeadClient

    head = HeadClient((host, int(port)))
    try:
        out = head.drain_node(args.node_id, args.deadline_s, args.reason)
    finally:
        head.close()
    out.pop("i", None)      # rpc correlation id, not user-facing
    print(json.dumps({"address": address, "node_id": args.node_id,
                      **out}, indent=2, default=str))
    return 0 if out.get("ok") else 1


def cmd_serve_deploy(args) -> int:
    """Deploy Serve applications from a YAML/JSON config (the
    `serve deploy` role)."""
    _init_runtime(args)
    from ray_tpu import serve

    handles = serve.run_config(args.config_file)
    print(f"deployed {len(handles)} application(s): "
          f"{sorted(handles)}")
    if args.http_port >= 0:
        port = serve.start_http_proxy(port=args.http_port)
        print(f"http proxy on :{port}")
        import time
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            return 0
    return 0


def cmd_job_submit(args) -> int:
    _init_runtime(args)
    from ray_tpu.job import JobSubmissionClient
    client = JobSubmissionClient()
    job_id = client.submit_job(entrypoint=args.entrypoint)
    status = client.wait_until_finished(job_id, timeout=args.timeout)
    print(client.get_job_logs(job_id), end="")
    print(f"job {job_id}: {status}")
    return 0 if status == "SUCCEEDED" else 1


def cmd_loadgen(args) -> int:
    # reached only when a global flag precedes the subcommand
    # (`ray-tpu --num-nodes 2 loadgen ...`); the bare form short-circuits
    # before argparse in main()
    from ray_tpu.loadgen.__main__ import main as loadgen_main
    return loadgen_main(args.rest)


def cmd_attach(args) -> int:
    """Open a shell (or run a command) wired to the running cluster
    (reference: `ray attach` opens a shell on the head; the local
    equivalent exports RAY_TPU_ADDRESS so `ray_tpu.init()` with no
    arguments joins the cluster)."""
    import subprocess

    address = getattr(args, "cluster", "") or _try_cluster_address()
    if not address:
        raise SystemExit("no running cluster (start one with "
                         "`ray-tpu start --head` or `ray-tpu up`)")
    env = dict(os.environ)
    env["RAY_TPU_ADDRESS"] = address
    cmd = args.cmd or [os.environ.get("SHELL", "/bin/bash")]
    print(f"attached to {address} (RAY_TPU_ADDRESS set)")
    return subprocess.call(cmd, env=env)


def cmd_up(args) -> int:
    """Create the cluster described by a YAML config (reference:
    `ray up`, scripts.py:1419 over autoscaler commands.py)."""
    from ray_tpu.cluster_launcher import up
    state = up(args.config_file)
    workers = sum(1 for n in state["nodes"] if n["kind"] == "worker")
    print(f"cluster {state['cluster_name']!r} up at {state['address']} "
          f"({workers} workers)")
    print(f'connect with: ray_tpu.init(address="{state["address"]}")')
    return 0


def cmd_down(args) -> int:
    from ray_tpu.cluster_launcher import down
    n = down(args.config_file)
    print(f"terminated {n} nodes")
    return 0


def cmd_debug(args) -> int:
    """List active remote-debugger sessions or attach to one
    (reference: the `ray debug` CLI over ray.util.rpdb). Listing reads
    the RUNNING cluster's head KV (cluster file or --cluster), never a
    fresh isolated runtime."""
    from ray_tpu.util import rpdb
    if args.session:
        host, _, port = args.session.rpartition(":")
        token = getattr(args, "token", None)
        if not token:
            # externally-bound sessions require their KV-advertised
            # token; resolve it from the running cluster when possible
            cluster = getattr(args, "cluster", "") or _try_cluster_address()
            if cluster:
                from ray_tpu._private.head import HeadClient
                chost, cport = cluster.rsplit(":", 1)
                head = HeadClient((chost, int(cport)))
                try:
                    for s in rpdb.sessions_from_kv(head):
                        if (str(s.get("port")) == port
                                and s.get("host") == (host
                                                      or "127.0.0.1")
                                and s.get("token")):
                            token = s["token"]
                            break
                finally:
                    head.close()
        rpdb.connect(host or "127.0.0.1", int(port), token=token)
        return 0
    sessions = []
    cluster = getattr(args, "cluster", "") or _try_cluster_address()
    if cluster:
        from ray_tpu._private.head import HeadClient
        host, port = cluster.rsplit(":", 1)
        head = HeadClient((host, int(port)))
        try:
            sessions = rpdb.sessions_from_kv(head)
        finally:
            head.close()
    else:
        # same-process fallback (tests / embedded drivers)
        import ray_tpu
        if ray_tpu.is_initialized():
            sessions = rpdb.active_sessions()
    if not sessions:
        print("no active debugger sessions")
        return 0
    for s in sessions:
        print(f"{s['host']}:{s['port']}  pid={s['pid']} "
              f"task={s.get('task_id')}  {s.get('banner', '')}")
    return 0


def _try_cluster_address() -> str:
    try:
        with open(CLUSTER_FILE) as f:
            return json.load(f)["address"]
    except Exception:
        return ""


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "loadgen":
        # pass-through BEFORE argparse: the loadgen CLI owns its whole
        # flag surface (argparse.REMAINDER drops a leading `--help`)
        from ray_tpu.loadgen.__main__ import main as loadgen_main
        return loadgen_main(argv[1:])
    parser = argparse.ArgumentParser(
        prog="ray-tpu", description="ray_tpu cluster CLI")
    parser.add_argument("--num-nodes", type=int, default=1)
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("start")
    p.add_argument("--head", action="store_true")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--num-daemons", type=int, default=2)
    p.add_argument("--resources", default="",
                   help="JSON resource map per daemon")
    p.add_argument("--object-store-bytes", type=int,
                   default=256 * 1024 * 1024)
    p.add_argument("--block", action="store_true",
                   help="stay attached; supervise the head")
    p = sub.add_parser("stop")
    p.add_argument("--address", default="")
    p = sub.add_parser("cluster-status")
    p.add_argument("--address", default="")
    p = sub.add_parser("drain")
    p.add_argument("node_id", help="node id (hex) to drain gracefully")
    p.add_argument("--address", default="")
    p.add_argument("--deadline-s", type=float, default=30.0,
                   dest="deadline_s",
                   help="drain window before escalating to node death")
    p.add_argument("--reason", default="manual drain")
    sub.add_parser("status")
    sub.add_parser("summary")
    sub.add_parser("memory")
    p = sub.add_parser("timeline")
    p.add_argument("--output", default="/tmp/ray_tpu_timeline.json")
    p = sub.add_parser("profile")
    p.add_argument("--node", default="",
                   help="profile only the daemon whose node id (hex) "
                        "starts with this prefix")
    p.add_argument("--all", action="store_true",
                   help="whole cluster: driver + every daemon/worker + "
                        "head aggregates (the default when --node is "
                        "not given)")
    p.add_argument("--duration", type=float, default=2.0,
                   help="burst sampling window in seconds")
    p.add_argument("--output", default="/tmp/ray_tpu_profile.json")
    p.add_argument("--format", choices=["speedscope", "collapsed"],
                   default="speedscope")
    p = sub.add_parser("microbenchmark")
    p.add_argument("--duration", type=float, default=2.0)
    p = sub.add_parser("dashboard")
    p.add_argument("--port", type=int, default=8265)
    p = sub.add_parser("serve-deploy")
    p.add_argument("config_file")
    p.add_argument("--http-port", type=int, default=-1,
                   help=">=0: start the HTTP proxy and block")
    p = sub.add_parser("job-submit")
    p.add_argument("entrypoint")
    p.add_argument("--timeout", type=float, default=300.0)
    p = sub.add_parser(
        "loadgen", add_help=False,
        help="open-loop serving load generator "
             "(see `ray-tpu loadgen --help`)")
    p.add_argument("rest", nargs=argparse.REMAINDER)
    p = sub.add_parser("up")
    p.add_argument("config_file", help="cluster YAML (see "
                                       "ray_tpu/cluster_launcher.py)")
    p = sub.add_parser("down")
    p.add_argument("config_file")
    p = sub.add_parser("attach")
    p.add_argument("cmd", nargs="*",
                   help="command to run attached (default: $SHELL)")
    p.add_argument("--cluster", default="",
                   help="head host:port (default: the cluster file)")
    p = sub.add_parser("debug")
    p.add_argument("session", nargs="?", default="",
                   help="host:port of a session to attach; empty = list")
    p.add_argument("--cluster", default="",
                   help="head host:port (default: the cluster file)")
    p.add_argument("--token", default="",
                   help="session token for externally-bound sessions "
                        "(default: resolved from the cluster KV)")

    args, extra = parser.parse_known_args(argv)
    if args.command == "loadgen":
        # global-flag-prefixed form (`ray-tpu --num-nodes 2 loadgen …`):
        # REMAINDER cannot capture leading option-like tokens
        # (bpo-17050), so hand loadgen everything after its own name.
        # Safe slice: the only global flag takes an int value, so the
        # first "loadgen" token IS the subcommand.
        args.rest = argv[argv.index("loadgen") + 1:]
    elif extra:
        parser.error("unrecognized arguments: " + " ".join(extra))
    handler = {
        "start": cmd_start, "stop": cmd_stop,
        "cluster-status": cmd_cluster_status, "drain": cmd_drain,
        "status": cmd_status, "summary": cmd_summary,
        "memory": cmd_memory, "timeline": cmd_timeline,
        "profile": cmd_profile,
        "microbenchmark": cmd_microbenchmark, "dashboard": cmd_dashboard,
        "serve-deploy": cmd_serve_deploy, "job-submit": cmd_job_submit,
        "up": cmd_up, "down": cmd_down, "attach": cmd_attach,
        "debug": cmd_debug, "loadgen": cmd_loadgen,
    }[args.command]
    return handler(args)


if __name__ == "__main__":
    sys.exit(main())
