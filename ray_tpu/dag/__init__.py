"""ray_tpu.dag — static DAGs over tasks/actors (Compiled Graphs).

Reference: `python/ray/dag/` (17.7k LoC): DAG nodes built with `.bind()`,
executed dynamically or compiled (`dag_node.py:280
experimental_compile`, `compiled_dag_node.py:809`) into a static schedule
over pre-allocated channels (SURVEY.md §8.10).

TPU-native split: ACCELERATOR dataflow (pipeline/tensor exchange) belongs
in a single jitted SPMD program (`ray_tpu.parallel.pipeline` — ppermute
rings ARE the channels). This module keeps the HOST-level capability:
declarative task/actor DAGs, compiled to a topologically-ordered schedule
that re-executes without per-call graph traversal.
"""

from ray_tpu.dag.node import (ClassMethodNode, DAGNode, FunctionNode,
                              InputNode, MultiOutputNode)
from ray_tpu.dag.compiled import CompiledDAG

__all__ = ["InputNode", "DAGNode", "FunctionNode", "ClassMethodNode",
           "MultiOutputNode", "CompiledDAG"]
