"""DAG node types (reference: `python/ray/dag/dag_node.py`,
`function_node.py`, `class_node.py`, `input_node.py`)."""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Tuple


class DAGNode:
    _ids = itertools.count()

    def __init__(self, args: Tuple = (), kwargs: Optional[Dict] = None):
        self.id = next(DAGNode._ids)
        self.args = args
        self.kwargs = kwargs or {}

    def upstream(self) -> List["DAGNode"]:
        out = []
        for a in list(self.args) + list(self.kwargs.values()):
            if isinstance(a, DAGNode):
                out.append(a)
        return out

    # -- execution --------------------------------------------------------
    def execute(self, *input_args, **input_kwargs):
        """Dynamic execution: walk the DAG, submit tasks, return the final
        ObjectRef(s) (reference: DAGNode.execute)."""
        from ray_tpu.dag.compiled import _execute_dag
        return _execute_dag(self, input_args, input_kwargs)

    def experimental_compile(self, *,
                             buffer_size_bytes: int = 1 << 20
                             ) -> "CompiledDAG":
        from ray_tpu.dag.compiled import CompiledDAG
        return CompiledDAG(self, buffer_size_bytes=buffer_size_bytes)

    # -- traversal --------------------------------------------------------
    def topo_sort(self) -> List["DAGNode"]:
        seen: Dict[int, DAGNode] = {}
        order: List[DAGNode] = []

        def visit(node: DAGNode):
            if node.id in seen:
                return
            seen[node.id] = node
            for up in node.upstream():
                visit(up)
            order.append(node)

        visit(self)
        return order


class InputNode(DAGNode):
    """Placeholder for the value passed at execute() time. Supports
    ``with InputNode() as inp:`` (reference usage shape)."""

    def __init__(self):
        super().__init__()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class FunctionNode(DAGNode):
    def __init__(self, remote_function, args, kwargs):
        super().__init__(args, kwargs)
        self.remote_function = remote_function

    def __repr__(self):
        return f"FunctionNode({self.remote_function._function_name})"


class ClassMethodNode(DAGNode):
    def __init__(self, actor_handle, method_name: str, args, kwargs):
        super().__init__(args, kwargs)
        self.actor_handle = actor_handle
        self.method_name = method_name

    def __repr__(self):
        return f"ClassMethodNode({self.method_name})"


class MultiOutputNode(DAGNode):
    def __init__(self, outputs: List[DAGNode]):
        super().__init__(tuple(outputs), {})
