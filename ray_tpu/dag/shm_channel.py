"""Pre-allocated shared-memory channels for compiled DAGs.

Reference capability: the accelerated-DAG mutable-object channels
(`src/ray/core_worker/experimental_mutable_object_manager.h:44`,
`python/ray/experimental/channel/shared_memory_channel.py`) — a fixed
shm buffer per DAG edge, written in place every execution, never
touching the object store or the RPC plane.

Protocol (single producer, single consumer, capacity 1 — the compiled
DAG executes in rounds, so depth-1 double-buffering is the reference's
shape too):

    header:  seq  u64 | ack  u64 | len  u64
    payload: [24, 24+capacity)

  write: wait seq == ack (previous value consumed) -> payload, len,
         then publish seq += 1
  read:  wait seq == ack + 1 -> value, then publish ack += 1

Both sides poll with spin-then-sleep backoff (the reference spins on a
seqno too); payload order is guaranteed by writing data before the seq
publish. Values are (status, cloudpickle) tuples so stage errors
propagate THROUGH the channel chain instead of deadlocking readers.
"""

from __future__ import annotations

import struct
import time
from multiprocessing import shared_memory
from typing import Any, Optional, Tuple

import cloudpickle

_HDR = struct.Struct("<QQQ")          # seq, ack, len
HEADER_SIZE = _HDR.size
DEFAULT_CAPACITY = 1 << 20            # 1 MiB per edge

_TSO_ARCHS = ("x86_64", "AMD64", "i686", "x86")
_arch_warned = False


def _check_arch() -> None:
    """The lock-free publish order is only guaranteed under x86-TSO
    (all TPU hosts). Warn once elsewhere instead of silently racing."""
    global _arch_warned
    import platform
    if _arch_warned or platform.machine() in _TSO_ARCHS:
        return
    _arch_warned = True
    import warnings
    warnings.warn(
        f"shm channels assume x86-TSO store ordering; on "
        f"{platform.machine()} a reader may observe the seq bump "
        f"before the payload bytes", RuntimeWarning, stacklevel=3)


class ChannelClosed(Exception):
    pass


class ChannelFull(Exception):
    """Value exceeds the channel's pre-allocated capacity."""


class ShmChannel:
    """One DAG edge. ``create=True`` allocates and owns the segment;
    ``create=False`` attaches by name (the worker side)."""

    def __init__(self, name: Optional[str] = None, *,
                 capacity: int = DEFAULT_CAPACITY, create: bool = False):
        _check_arch()
        if create:
            self._shm = shared_memory.SharedMemory(
                create=True, size=HEADER_SIZE + capacity)
            self._shm.buf[:HEADER_SIZE] = b"\x00" * HEADER_SIZE
        else:
            self._shm = shared_memory.SharedMemory(name=name)
        self.capacity = len(self._shm.buf) - HEADER_SIZE
        self._owner = create

    @property
    def name(self) -> str:
        return self._shm.name

    # -- header accessors -------------------------------------------------
    # Memory-model note: the seq/ack protocol publishes payload+len
    # BEFORE bumping seq (slot 0) and relies on CPython's byte-store
    # ordering plus x86-TSO for the reader to observe them in that
    # order. On weakly-ordered hosts (ARM) a reader could in principle
    # see the new seq before the payload bytes; TPU hosts are x86, so
    # this is asserted at import in _check_arch() rather than paying a
    # lock per message on the hot path.
    def _get(self, idx: int) -> int:
        return struct.unpack_from("<Q", self._shm.buf, idx * 8)[0]

    def _set(self, idx: int, value: int) -> None:
        struct.pack_into("<Q", self._shm.buf, idx * 8, value)

    # -- data plane -------------------------------------------------------
    def _wait(self, cond, stop=None,
              timeout: Optional[float] = 300.0) -> None:
        """``timeout=None`` waits forever (idle DAG loops gate on the
        stop event alone)."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        spins = 0
        while not cond():
            if stop is not None and stop.is_set():
                raise ChannelClosed("channel stopped")
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("shm channel wait timed out")
            spins += 1
            if spins < 200:
                continue              # brief spin: same-round handoff
            time.sleep(0.0005 if spins < 2000 else 0.002)

    def write(self, status: str, value: Any, *, stop=None,
              timeout: float = 300.0) -> None:
        blob = cloudpickle.dumps((status, value))
        if len(blob) > self.capacity:
            raise ChannelFull(
                f"value of {len(blob)} bytes exceeds channel capacity "
                f"{self.capacity}; recompile with a larger "
                f"buffer_size_bytes")
        self._wait(lambda: self._get(0) == self._get(1), stop=stop,
                   timeout=timeout)
        self._shm.buf[HEADER_SIZE:HEADER_SIZE + len(blob)] = blob
        self._set(2, len(blob))
        self._set(0, self._get(0) + 1)     # publish

    def read(self, *, stop=None, timeout: float = 300.0
             ) -> Tuple[str, Any]:
        self._wait(lambda: self._get(0) == self._get(1) + 1, stop=stop,
                   timeout=timeout)
        n = self._get(2)
        status, value = cloudpickle.loads(
            bytes(self._shm.buf[HEADER_SIZE:HEADER_SIZE + n]))
        self._set(1, self._get(1) + 1)     # consume
        return status, value

    # -- lifecycle --------------------------------------------------------
    def close(self) -> None:
        try:
            self._shm.close()
        except Exception:
            pass

    def unlink(self) -> None:
        if self._owner:
            try:
                self._shm.unlink()
            except Exception:
                pass
