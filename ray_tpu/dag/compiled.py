"""DAG execution: dynamic walk + compiled static schedule.

Reference: `dag/compiled_dag_node.py:809` (CompiledDAG; execute :2550) —
compile-time topological schedule, per-call execution without graph
traversal. The reference pre-allocates shm/NCCL channels; here values
flow as ObjectRefs (host plane) — accelerator-plane channels are the
SPMD ppermute programs of `ray_tpu.parallel.pipeline`.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

from ray_tpu.dag.node import (ClassMethodNode, DAGNode, FunctionNode,
                              InputNode, MultiOutputNode)


def _submit_node(node: DAGNode, resolved: Dict[int, Any]):
    """Submit one node's task with upstream results substituted."""
    def sub(a):
        return resolved[a.id] if isinstance(a, DAGNode) else a

    args = tuple(sub(a) for a in node.args)
    kwargs = {k: sub(v) for k, v in node.kwargs.items()}
    if isinstance(node, FunctionNode):
        return node.remote_function.remote(*args, **kwargs)
    if isinstance(node, ClassMethodNode):
        method = getattr(node.actor_handle, node.method_name)
        return method.remote(*args, **kwargs)
    raise TypeError(f"cannot submit {node!r}")


def _execute_dag(root: DAGNode, input_args: Tuple, input_kwargs: Dict):
    order = root.topo_sort()
    return _run_schedule(order, root, input_args)


def _run_schedule(order: List[DAGNode], root: DAGNode,
                  input_args: Tuple):
    resolved: Dict[int, Any] = {}
    for node in order:
        if isinstance(node, InputNode):
            if not input_args:
                raise ValueError("DAG has an InputNode but execute() got "
                                 "no argument")
            resolved[node.id] = input_args[0]
        elif isinstance(node, MultiOutputNode):
            resolved[node.id] = [resolved[o.id] for o in node.args]
        else:
            resolved[node.id] = _submit_node(node, resolved)
    return resolved[root.id]


class _Slot:
    """One edge's value channel for one execution (the shm-mutable-object
    role of ``experimental_mutable_object_manager.h:44`` collapsed to an
    in-process slot: the compiled data plane never touches the object
    store)."""

    __slots__ = ("_event", "_value", "_error")

    def __init__(self):
        import threading

        self._event = threading.Event()
        self._value = None
        self._error = None

    def put(self, value) -> None:
        self._value = value
        self._event.set()

    def put_error(self, err: BaseException) -> None:
        self._error = err
        self._event.set()

    def get(self, timeout: float = 300.0):
        if not self._event.wait(timeout):
            raise TimeoutError("compiled DAG channel read timed out")
        if self._error is not None:
            raise self._error
        return self._value


class CompiledDAG:
    """Pre-computed schedule with pre-bound channels.

    Reference: ``CompiledDAG`` (`compiled_dag_node.py:809`) — at compile
    time the schedule and channels are fixed; per execute() nothing goes
    through the scheduler. Here, when every compute node is a method on
    an in-process sync actor, each node becomes a *direct op* queued on
    its actor's executor thread: values flow actor→actor through
    ``_Slot`` channels (plain objects, no object store), each actor's
    executor pipelines its stage, and only the FINAL result is sealed
    into an ObjectRef for the caller. DAGs with task nodes, async
    actors, or daemon-remote actors fall back to the dynamic schedule.
    """

    def __init__(self, root: DAGNode):
        self.root = root
        self.schedule = root.topo_sort()
        # static validation at compile time (reference does channel
        # allocation + schedule checks here)
        n_inputs = sum(isinstance(n, InputNode) for n in self.schedule)
        if n_inputs > 1:
            raise ValueError("compiled DAGs support a single InputNode")
        self._teardown = False
        self._executors = self._bind_executors()

    def _bind_executors(self):
        """Channel mode iff every compute node is a sync in-process actor
        method; returns {node_id: (executor, bound_method_name)}."""
        from ray_tpu._private import worker

        rt = worker.global_runtime()
        if rt is None:
            return None
        bound = {}
        for node in self.schedule:
            if isinstance(node, (InputNode, MultiOutputNode)):
                continue
            if not isinstance(node, ClassMethodNode):
                return None         # task node: dynamic fallback
            actor_id = node.actor_handle._actor_id
            if actor_id in rt._remote_actors:
                return None         # daemon-hosted actor
            # Actor creation is async; compile blocks until the actor is
            # live (reference: experimental_compile waits on actors).
            import time as _time

            deadline = _time.monotonic() + 30.0
            executor = None
            while _time.monotonic() < deadline:
                with rt._actor_lock:
                    executor = rt._actor_executors.get(actor_id)
                if executor is not None and executor.instance is not None:
                    break
                if actor_id in rt._remote_actors:
                    return None
                _time.sleep(0.01)
            if (executor is None or executor.is_async
                    or executor.instance is None):
                return None
            instance = executor.instance
            from ray_tpu._private.worker_process import \
                _ProcessActorInstance
            if isinstance(instance, _ProcessActorInstance):
                return None         # worker-process actor: fallback
            bound[node.id] = executor
        return bound or None

    def execute(self, *args):
        if self._teardown:
            raise RuntimeError("compiled DAG was torn down")
        if self._executors is None:
            return _run_schedule(self.schedule, self.root, args)
        return self._execute_channels(args)

    def _execute_channels(self, args):
        import threading

        from ray_tpu import exceptions as exc
        from ray_tpu._private import worker
        from ray_tpu._private.ids import ObjectID
        from ray_tpu._private.object_ref import ObjectRef

        rt = worker.global_worker()
        slots = {node.id: _Slot() for node in self.schedule}

        def read(arg):
            if isinstance(arg, DAGNode):
                return slots[arg.id].get()
            if isinstance(arg, ObjectRef):
                # parity with the dynamic path: refs resolve to values
                return rt.get([arg])[0]
            return arg

        for node in self.schedule:
            if isinstance(node, InputNode):
                if not args:
                    raise ValueError("DAG has an InputNode but execute() "
                                     "got no argument")
                value = args[0]
                if isinstance(value, ObjectRef):
                    value = rt.get([value])[0]
                slots[node.id].put(value)
            elif isinstance(node, MultiOutputNode):
                continue            # gathered by the finisher
            else:
                def op(instance, node=node):
                    slot = slots[node.id]
                    try:
                        vals = [read(a) for a in node.args]
                        kw = {k: read(v) for k, v in node.kwargs.items()}
                        method = getattr(instance, node.method_name)
                        slot.put(method(*vals, **kw))
                    except BaseException as e:  # noqa: BLE001 — to slot
                        slot.put_error(e)

                def on_dead(cause, node=node):
                    slots[node.id].put_error(exc.ActorDiedError(
                        node.actor_handle._actor_id, cause))

                if not self._executors[node.id].submit_direct(
                        op, on_dead=on_dead):
                    raise RuntimeError(
                        "compiled DAG actor is dead; rebuild the DAG")

        # The caller gets a normal ObjectRef; only the FINAL value is
        # sealed into the store (reference: execute() returns a ref).
        oid = ObjectID.from_random()
        ref = ObjectRef(oid, owner_hex=rt.worker_id.hex(),
                        task_name="compiled_dag")

        def finish():
            try:
                if isinstance(self.root, MultiOutputNode):
                    value = [slots[o.id].get() for o in self.root.args]
                else:
                    value = slots[self.root.id].get()
                rt._store_value(oid, value)
            except BaseException as e:  # noqa: BLE001 — shipped to ref
                rt._store_value(oid, exc.TaskError(e, "compiled_dag"))
            rt.futures.complete(oid)

        threading.Thread(target=finish, daemon=True,
                         name="compiled-dag-finish").start()
        return ref

    def teardown(self) -> None:
        self._teardown = True
