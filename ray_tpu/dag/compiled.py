"""DAG execution: dynamic walk + compiled static schedule.

Reference: `dag/compiled_dag_node.py:809` (CompiledDAG; execute :2550) —
compile-time topological schedule, per-call execution without graph
traversal. The reference pre-allocates shm/NCCL channels; here values
flow as ObjectRefs (host plane) — accelerator-plane channels are the
SPMD ppermute programs of `ray_tpu.parallel.pipeline`.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

from ray_tpu.dag.node import (ClassMethodNode, DAGNode, FunctionNode,
                              InputNode, MultiOutputNode)


def _submit_node(node: DAGNode, resolved: Dict[int, Any]):
    """Submit one node's task with upstream results substituted."""
    def sub(a):
        return resolved[a.id] if isinstance(a, DAGNode) else a

    args = tuple(sub(a) for a in node.args)
    kwargs = {k: sub(v) for k, v in node.kwargs.items()}
    if isinstance(node, FunctionNode):
        return node.remote_function.remote(*args, **kwargs)
    if isinstance(node, ClassMethodNode):
        method = getattr(node.actor_handle, node.method_name)
        return method.remote(*args, **kwargs)
    raise TypeError(f"cannot submit {node!r}")


def _execute_dag(root: DAGNode, input_args: Tuple, input_kwargs: Dict):
    order = root.topo_sort()
    return _run_schedule(order, root, input_args)


def _run_schedule(order: List[DAGNode], root: DAGNode,
                  input_args: Tuple):
    resolved: Dict[int, Any] = {}
    for node in order:
        if isinstance(node, InputNode):
            if not input_args:
                raise ValueError("DAG has an InputNode but execute() got "
                                 "no argument")
            resolved[node.id] = input_args[0]
        elif isinstance(node, MultiOutputNode):
            resolved[node.id] = [resolved[o.id] for o in node.args]
        else:
            resolved[node.id] = _submit_node(node, resolved)
    return resolved[root.id]


class CompiledDAG:
    """Pre-computed schedule: execute() replays it without traversal."""

    def __init__(self, root: DAGNode):
        self.root = root
        self.schedule = root.topo_sort()
        # static validation at compile time (reference does channel
        # allocation + schedule checks here)
        n_inputs = sum(isinstance(n, InputNode) for n in self.schedule)
        if n_inputs > 1:
            raise ValueError("compiled DAGs support a single InputNode")
        self._teardown = False

    def execute(self, *args):
        if self._teardown:
            raise RuntimeError("compiled DAG was torn down")
        return _run_schedule(self.schedule, self.root, args)

    def teardown(self) -> None:
        self._teardown = True
