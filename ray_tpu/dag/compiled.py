"""DAG execution: dynamic walk + compiled static schedule.

Reference: `dag/compiled_dag_node.py:809` (CompiledDAG; execute :2550) —
compile-time topological schedule, per-call execution without graph
traversal. The reference pre-allocates shm/NCCL channels; here values
flow as ObjectRefs (host plane) — accelerator-plane channels are the
SPMD ppermute programs of `ray_tpu.parallel.pipeline`.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

from ray_tpu.dag.node import (ClassMethodNode, DAGNode, FunctionNode,
                              InputNode, MultiOutputNode)


def _submit_node(node: DAGNode, resolved: Dict[int, Any]):
    """Submit one node's task with upstream results substituted."""
    def sub(a):
        return resolved[a.id] if isinstance(a, DAGNode) else a

    args = tuple(sub(a) for a in node.args)
    kwargs = {k: sub(v) for k, v in node.kwargs.items()}
    if isinstance(node, FunctionNode):
        return node.remote_function.remote(*args, **kwargs)
    if isinstance(node, ClassMethodNode):
        method = getattr(node.actor_handle, node.method_name)
        return method.remote(*args, **kwargs)
    raise TypeError(f"cannot submit {node!r}")


def _execute_dag(root: DAGNode, input_args: Tuple, input_kwargs: Dict):
    order = root.topo_sort()
    return _run_schedule(order, root, input_args)


def _run_schedule(order: List[DAGNode], root: DAGNode,
                  input_args: Tuple):
    resolved: Dict[int, Any] = {}
    for node in order:
        if isinstance(node, InputNode):
            if not input_args:
                raise ValueError("DAG has an InputNode but execute() got "
                                 "no argument")
            resolved[node.id] = input_args[0]
        elif isinstance(node, MultiOutputNode):
            resolved[node.id] = [resolved[o.id] for o in node.args]
        else:
            resolved[node.id] = _submit_node(node, resolved)
    return resolved[root.id]


class _Slot:
    """One edge's value channel for one execution (the shm-mutable-object
    role of ``experimental_mutable_object_manager.h:44`` collapsed to an
    in-process slot: the compiled data plane never touches the object
    store)."""

    __slots__ = ("_event", "_value", "_error")

    def __init__(self):
        import threading

        self._event = threading.Event()
        self._value = None
        self._error = None

    def put(self, value) -> None:
        self._value = value
        self._event.set()

    def put_error(self, err: BaseException) -> None:
        self._error = err
        self._event.set()

    def get(self, timeout: float = 300.0):
        if not self._event.wait(timeout):
            raise TimeoutError("compiled DAG channel read timed out")
        if self._error is not None:
            raise self._error
        return self._value


class CompiledDAG:
    """Pre-computed schedule with pre-bound channels.

    Reference: ``CompiledDAG`` (`compiled_dag_node.py:809`) — at compile
    time the schedule and channels are fixed; per execute() nothing goes
    through the scheduler. Here, when every compute node is a method on
    an in-process sync actor, each node becomes a *direct op* queued on
    its actor's executor thread: values flow actor→actor through
    ``_Slot`` channels (plain objects, no object store), each actor's
    executor pipelines its stage, and only the FINAL result is sealed
    into an ObjectRef for the caller. DAGs with task nodes, async
    actors, or daemon-remote actors fall back to the dynamic schedule.
    """

    def __init__(self, root: DAGNode, *,
                 buffer_size_bytes: int = 1 << 20):
        self.root = root
        self.schedule = root.topo_sort()
        # static validation at compile time (reference does channel
        # allocation + schedule checks here)
        n_inputs = sum(isinstance(n, InputNode) for n in self.schedule)
        if n_inputs > 1:
            raise ValueError("compiled DAGs support a single InputNode")
        self._teardown = False
        self._buffer_size = buffer_size_bytes
        self._proc = None
        # One submit at a time: a round's input-channel writes and its
        # rounds.put must be atomic or concurrent execute() calls can
        # interleave writes across channels and mispair round outputs
        # with ObjectRefs.
        self._submit_lock = __import__("threading").Lock()
        self._executors = self._bind_executors()
        if self._executors is None:
            # cross-process mode: pre-allocated shm channels + a
            # persistent per-actor loop — zero RPCs per execute()
            self._proc = self._bind_process_channels()

    @staticmethod
    def _resolve_live_executor(rt, actor_id):
        """Wait (<=30s) for an actor's executor to be live. Actor
        creation is async; compile blocks until actors exist
        (reference: experimental_compile waits on actors)."""
        import time as _time

        deadline = _time.monotonic() + 30.0
        while _time.monotonic() < deadline:
            with rt._actor_lock:
                executor = rt._actor_executors.get(actor_id)
            if executor is not None and executor.instance is not None:
                return executor
            if actor_id in getattr(rt, "_remote_actors", {}):
                return None
            _time.sleep(0.01)
        return None

    def _bind_executors(self):
        """Channel mode iff every compute node is a sync in-process actor
        method; returns {node_id: (executor, bound_method_name)}."""
        from ray_tpu._private import worker

        rt = worker.global_runtime()
        if rt is None:
            return None
        bound = {}
        for node in self.schedule:
            if isinstance(node, (InputNode, MultiOutputNode)):
                continue
            if not isinstance(node, ClassMethodNode):
                return None         # task node: dynamic fallback
            actor_id = node.actor_handle._actor_id
            if actor_id in rt._remote_actors:
                return None         # daemon-hosted actor
            executor = self._resolve_live_executor(rt, actor_id)
            if executor is None or executor.is_async:
                return None
            instance = executor.instance
            from ray_tpu._private.cluster import RemoteActorInstance
            from ray_tpu._private.worker_process import \
                _ProcessActorInstance
            if isinstance(instance,
                          (_ProcessActorInstance, RemoteActorInstance)):
                # worker-process / daemon-hosted actor: fallback. The
                # instance check matters even with the _remote_actors
                # gate above — actor creation is async, so a compile
                # racing registration can resolve the executor first.
                return None
            bound[node.id] = executor
        return bound or None

    def _bind_process_channels(self):
        """Cross-process channel mode iff every compute node is a
        method on a DRIVER-SPAWNED process-worker actor: pre-allocate
        one shm channel per consumed edge, ship each actor ONE
        dag_start op binding its stages to channels, and let values
        flow worker->worker through shared memory from then on
        (reference: shared_memory_channel.py + _do_exec_tasks loop)."""
        from ray_tpu._private import worker
        from ray_tpu._private.worker_process import _ProcessActorInstance
        from ray_tpu.dag.shm_channel import ShmChannel

        rt = worker.global_runtime()
        if rt is None:
            return None

        # every compute node must resolve to a live process-actor client
        instances = {}
        for node in self.schedule:
            if isinstance(node, (InputNode, MultiOutputNode)):
                continue
            if not isinstance(node, ClassMethodNode):
                return None
            actor_id = node.actor_handle._actor_id
            if actor_id in getattr(rt, "_remote_actors", {}):
                return None          # daemon-hosted: no direct client
            executor = self._resolve_live_executor(rt, actor_id)
            if executor is None:
                return None
            if not isinstance(executor.instance, _ProcessActorInstance):
                return None
            instances[node.id] = (actor_id, executor.instance)
        actor_instances = {aid: inst for aid, inst in instances.values()}

        if not instances:
            return None
        # every stage must be GATED by a channel read (a node with only
        # constant args would free-run in the worker loop, executing
        # more rounds than execute() calls — a semantic break for
        # stateful actors), and constants must not be ObjectRefs (the
        # dynamic path resolves those; channels would ship raw handles)
        from ray_tpu._private.object_ref import ObjectRef as _Ref
        has_input = any(isinstance(n, InputNode) for n in self.schedule)
        if not has_input:
            return None
        for node in self.schedule:
            if isinstance(node, (InputNode, MultiOutputNode)):
                continue
            srcs = list(node.args) + list(node.kwargs.values())
            if not any(isinstance(a, DAGNode) for a in srcs):
                return None
            if any(isinstance(a, _Ref) for a in srcs):
                return None

        # one channel per CONSUMED edge (fan-out = one channel per
        # consumer; a node using the same upstream twice gets two)
        channels = {}                 # channel name -> ShmChannel (owner)
        input_feeds = []              # channels the driver writes args to
        consts: list = []
        stage_specs: Dict[Any, list] = {}   # actor_id -> [stage...]
        out_edges: Dict[int, list] = {}     # producer node id -> names

        def new_channel():
            ch = ShmChannel(create=True, capacity=self._buffer_size)
            channels[ch.name] = ch
            return ch

        def source_for(arg):
            if isinstance(arg, InputNode):
                ch = new_channel()
                input_feeds.append(ch)
                return ("chan", ch.name)
            if isinstance(arg, DAGNode):
                ch = new_channel()
                out_edges.setdefault(arg.id, []).append(ch.name)
                return ("chan", ch.name)
            consts.append(arg)
            return ("const", len(consts) - 1)

        for node in self.schedule:
            if isinstance(node, (InputNode, MultiOutputNode)):
                continue
            spec = {
                "method": node.method_name,
                "args": [source_for(a) for a in node.args],
                "kwargs": {k: source_for(v)
                           for k, v in node.kwargs.items()},
                "out": [],            # filled below once consumers known
            }
            actor_id, _ = instances[node.id]
            stage_specs.setdefault(actor_id, []).append((node.id, spec))

        # driver-read output channels (the DAG's results)
        roots = (self.root.args if isinstance(self.root, MultiOutputNode)
                 else [self.root])
        if any(not isinstance(r, ClassMethodNode) for r in roots):
            # e.g. a MultiOutputNode echoing the InputNode directly: no
            # stage would ever write that output channel — dynamic path
            for ch in channels.values():
                ch.close()
                ch.unlink()
            return None
        outputs = []
        for out_node in roots:
            ch = new_channel()
            out_edges.setdefault(out_node.id, []).append(ch.name)
            outputs.append(ch)

        for actor_id, stages in stage_specs.items():
            for node_id, spec in stages:
                spec["out"] = out_edges.get(node_id, [])

        # bind each actor's loop with ONE RPC; per-actor channel set
        # and per-actor consts (no shipping one stage's big constant to
        # every worker). A GENERATION token scopes teardown: a stale
        # CompiledDAG being GC'd must not kill a newer binding.
        import uuid

        import cloudpickle
        gen = uuid.uuid4().hex
        started = []

        def send_stop(instance):
            try:
                client = instance._client
                rid, pend = client._request({
                    "op": "dag_stop", "args_blob": cloudpickle.dumps(gen),
                    "ctx": {}, "runtime_env": None})
                client._wait_outcome(rid, pend)
            except Exception:
                pass

        try:
            for actor_id, stages in stage_specs.items():
                instance = actor_instances[actor_id]
                names = set()
                used_consts = []
                for _, spec in stages:
                    for part in (spec["args"],
                                 list(spec["kwargs"].values())):
                        for i, (kind, key) in enumerate(part):
                            if kind == "chan":
                                names.add(key)
                            else:
                                used_consts.append(key)
                    names.update(spec["out"])
                remap = {old: i for i, old in
                         enumerate(dict.fromkeys(used_consts))}

                def remap_src(src):
                    kind, key = src
                    return (kind, key if kind == "chan" else remap[key])

                actor_stages = [
                    {"method": spec["method"],
                     "args": [remap_src(s) for s in spec["args"]],
                     "kwargs": {k: remap_src(s)
                                for k, s in spec["kwargs"].items()},
                     "out": spec["out"]}
                    for _, spec in stages]
                blob = cloudpickle.dumps({
                    "channels": sorted(names),
                    "consts": [consts[old] for old in remap],
                    "stages": actor_stages,
                    "gen": gen,
                })
                client = instance._client
                rid, pend = client._request({
                    "op": "dag_start", "args_blob": blob, "ctx": {},
                    "runtime_env": None})
                outcome = client._wait_outcome(rid, pend)
                if outcome[0] not in ("ok", "ok_raw"):
                    raise RuntimeError(
                        f"dag_start failed on actor {actor_id}: "
                        f"{outcome}")
                started.append(instance)
        except Exception:
            for instance in started:   # stop loops already bound
                send_stop(instance)
            for ch in channels.values():
                ch.close()
                ch.unlink()
            raise

        proc = {"channels": channels, "inputs": input_feeds,
                "outputs": outputs, "actors": started, "gen": gen,
                "stop": send_stop}
        self._start_finisher(proc)
        return proc

    def execute(self, *args):
        if self._teardown:
            raise RuntimeError("compiled DAG was torn down")
        if self._executors is not None:
            return self._execute_channels(args)
        if self._proc is not None:
            return self._execute_process(args)
        return _run_schedule(self.schedule, self.root, args)

    def _start_finisher(self, proc) -> None:
        """ONE long-lived reader drains the output channels in round
        order — concurrent execute() calls enqueue rounds instead of
        racing multiple readers on the single-consumer channels."""
        import queue
        import threading

        from ray_tpu import exceptions as exc
        from ray_tpu._private import worker

        rounds: "queue.Queue" = queue.Queue()
        proc["rounds"] = rounds
        outputs = proc["outputs"]

        def read_output(ch):
            # short-poll reads + liveness checks: a DEAD stage worker
            # must fail the round promptly, not after a 300s channel
            # timeout
            deadline = 300
            waited = 0.0
            while True:
                try:
                    return ch.read(timeout=2.0)
                except TimeoutError:
                    waited += 2.0
                    for instance in proc["actors"]:
                        if instance._client.dead:
                            raise exc.ActorDiedError(
                                None, "compiled-DAG stage worker died")
                    if waited >= deadline:
                        raise

        def run():
            rt = worker.global_runtime()
            while True:
                item = rounds.get()
                if item is None:
                    return
                oid, multi = item
                try:
                    got = [read_output(ch) for ch in outputs]
                    err = next((v for s, v in got if s != "ok"), None)
                    if err is not None:
                        raise err
                    vals = [v for _, v in got]
                    rt._store_value(oid, vals if multi else vals[0])
                except BaseException as e:  # noqa: BLE001 — to the ref
                    rt._store_value(oid, exc.TaskError(e, "compiled_dag"))
                rt.futures.complete(oid)

        t = threading.Thread(target=run, daemon=True,
                             name="compiled-dag-finisher")
        proc["finisher"] = t
        t.start()

    def _execute_process(self, args):
        from ray_tpu._private import worker
        from ray_tpu._private.ids import ObjectID
        from ray_tpu._private.object_ref import ObjectRef

        rt = worker.global_worker()
        value = None
        if self._proc["inputs"]:
            if not args:
                raise ValueError("DAG has an InputNode but execute() "
                                 "got no argument")
            value = args[0]
            if isinstance(value, ObjectRef):
                value = rt.get([value])[0]

        oid = ObjectID.from_random()
        ref = ObjectRef(oid, owner_hex=rt.worker_id.hex(),
                        task_name="compiled_dag")
        with self._submit_lock:
            for ch in self._proc["inputs"]:
                ch.write("ok", value)
            self._proc["rounds"].put(
                (oid, isinstance(self.root, MultiOutputNode)))
        return ref

    def _execute_channels(self, args):
        import threading

        from ray_tpu import exceptions as exc
        from ray_tpu._private import worker
        from ray_tpu._private.ids import ObjectID
        from ray_tpu._private.object_ref import ObjectRef

        rt = worker.global_worker()
        slots = {node.id: _Slot() for node in self.schedule}

        def read(arg):
            if isinstance(arg, DAGNode):
                return slots[arg.id].get()
            if isinstance(arg, ObjectRef):
                # parity with the dynamic path: refs resolve to values
                return rt.get([arg])[0]
            return arg

        for node in self.schedule:
            if isinstance(node, InputNode):
                if not args:
                    raise ValueError("DAG has an InputNode but execute() "
                                     "got no argument")
                value = args[0]
                if isinstance(value, ObjectRef):
                    value = rt.get([value])[0]
                slots[node.id].put(value)
            elif isinstance(node, MultiOutputNode):
                continue            # gathered by the finisher
            else:
                def op(instance, node=node):
                    slot = slots[node.id]
                    try:
                        vals = [read(a) for a in node.args]
                        kw = {k: read(v) for k, v in node.kwargs.items()}
                        method = getattr(instance, node.method_name)
                        slot.put(method(*vals, **kw))
                    except BaseException as e:  # noqa: BLE001 — to slot
                        slot.put_error(e)

                def on_dead(cause, node=node):
                    slots[node.id].put_error(exc.ActorDiedError(
                        node.actor_handle._actor_id, cause))

                if not self._executors[node.id].submit_direct(
                        op, on_dead=on_dead):
                    raise RuntimeError(
                        "compiled DAG actor is dead; rebuild the DAG")

        # The caller gets a normal ObjectRef; only the FINAL value is
        # sealed into the store (reference: execute() returns a ref).
        oid = ObjectID.from_random()
        ref = ObjectRef(oid, owner_hex=rt.worker_id.hex(),
                        task_name="compiled_dag")

        def finish():
            try:
                if isinstance(self.root, MultiOutputNode):
                    value = [slots[o.id].get() for o in self.root.args]
                else:
                    value = slots[self.root.id].get()
                rt._store_value(oid, value)
            except BaseException as e:  # noqa: BLE001 — shipped to ref
                rt._store_value(oid, exc.TaskError(e, "compiled_dag"))
            rt.futures.complete(oid)

        threading.Thread(target=finish, daemon=True,
                         name="compiled-dag-finish").start()
        return ref

    def __del__(self):
        try:
            if not self._teardown and self._proc is not None:
                self.teardown()
        except Exception:
            pass

    def teardown(self) -> None:
        self._teardown = True
        if self._proc is not None:
            for instance in self._proc["actors"]:
                self._proc["stop"](instance)   # generation-scoped stop
            self._proc["rounds"].put(None)     # drain the finisher
            self._proc["finisher"].join(timeout=5)
            for ch in self._proc["channels"].values():
                ch.close()
                ch.unlink()
            self._proc = None
