"""ray_tpu: a TPU-native distributed AI framework.

Capability contract of Ray (tasks, actors, objects, placement groups, and the
AI-library surface) re-designed TPU-first: SPMD JAX programs over device
meshes are the unit of accelerator work; the control plane schedules them
gang-wise over hosts; Pallas kernels cover the hot ops; XLA collectives over
ICI replace NCCL.

Public core API parity: reference ``python/ray/__init__.py`` /
``python/ray/_private/worker.py`` (init :1341, get :2736, put :2890,
wait :2955, remote :3343).
"""

from __future__ import annotations

import inspect as _inspect
from typing import Any, Dict, List, Optional, Sequence, Union

from ray_tpu import exceptions
from ray_tpu._private import worker as _worker
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.runtime_context import get_runtime_context
from ray_tpu._private.task_spec import (
    NodeAffinitySchedulingStrategy,
    NodeLabelSchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)
from ray_tpu.actor import (ActorClass, ActorHandle, exit_actor, get_actor)
from ray_tpu.remote_function import ObjectRefGenerator, RemoteFunction

__version__ = "0.1.0"

__all__ = [
    "init", "shutdown", "is_initialized", "remote", "get", "put", "wait",
    "kill", "cancel", "get_actor", "get_runtime_context", "ObjectRef",
    "ObjectRefGenerator", "ActorHandle", "exit_actor", "cluster_resources",
    "available_resources", "nodes", "drain_node", "exceptions", "method",
    "NodeAffinitySchedulingStrategy", "NodeLabelSchedulingStrategy",
    "PlacementGroupSchedulingStrategy",
]


def init(num_nodes: int = 1,
         resources: Optional[Dict[str, float]] = None,
         object_store_memory: int = 2 * 1024 ** 3,
         namespace: Optional[str] = None,
         ignore_reinit_error: bool = False,
         _system_config: Optional[Dict[str, Any]] = None,
         **kwargs) -> "_worker.Runtime":
    """Start the runtime with ``num_nodes`` virtual nodes on this host
    (or join a running cluster with ``address="host:port"``).

    ``_system_config`` overrides flags from the central table
    (``ray_tpu/_private/config.py``, the ray_config_def.h role)."""
    if _worker.global_runtime() is not None:
        if ignore_reinit_error:
            return _worker.global_runtime()
        raise RuntimeError("ray_tpu.init() called twice "
                           "(use ignore_reinit_error=True to allow)")
    from ray_tpu._private.config import apply_system_config
    apply_system_config(_system_config)
    # `ray-tpu attach` exports RAY_TPU_ADDRESS so a bare init() joins
    # the attached cluster (reference: RAY_ADDRESS)
    import os as _os
    if not kwargs.get("address") and _os.environ.get("RAY_TPU_ADDRESS"):
        kwargs["address"] = _os.environ["RAY_TPU_ADDRESS"]
    return _worker.init_runtime(
        num_nodes=num_nodes, resources_per_node=resources,
        object_store_memory=object_store_memory, namespace=namespace,
        **kwargs)


def shutdown() -> None:
    _worker.shutdown_runtime()


def is_initialized() -> bool:
    return _worker.global_runtime() is not None


def _make_remote(obj, options: Dict[str, Any]):
    if _inspect.isclass(obj):
        return ActorClass(obj, options)
    if callable(obj):
        return RemoteFunction(obj, options)
    raise TypeError("@remote decorates a function or a class, "
                    f"got {type(obj).__name__}")


def remote(*args, **kwargs):
    """``@remote`` / ``@remote(**options)`` decorator for tasks and actors."""
    if len(args) == 1 and not kwargs and (callable(args[0])
                                          or _inspect.isclass(args[0])):
        return _make_remote(args[0], {})
    if args:
        raise TypeError("@remote takes only keyword options")
    return lambda obj: _make_remote(obj, kwargs)


def method(**options):
    """Per-method defaults on actor classes (e.g. num_returns)."""
    def decorator(m):
        m.__ray_tpu_method_options__ = options
        return m
    return decorator


def get(refs: Union[ObjectRef, Sequence[ObjectRef]],
        *, timeout: Optional[float] = None) -> Any:
    rt = _worker.global_worker()
    if isinstance(refs, ObjectRef):
        return rt.get([refs], timeout=timeout)[0]
    if isinstance(refs, (list, tuple)):
        bad = [r for r in refs if not isinstance(r, ObjectRef)]
        if bad:
            raise TypeError(f"get() expects ObjectRefs, got {type(bad[0])}")
        return rt.get(list(refs), timeout=timeout)
    raise TypeError(f"get() expects an ObjectRef or list, got {type(refs)}")


def put(value: Any) -> ObjectRef:
    return _worker.global_worker().put(value)


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None, fetch_local: bool = True):
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    return _worker.global_worker().wait(
        list(refs), num_returns=num_returns, timeout=timeout,
        fetch_local=fetch_local)


def kill(actor: ActorHandle, *, no_restart: bool = True) -> None:
    _worker.global_worker().kill_actor(actor._ray_actor_id,
                                       no_restart=no_restart)


def cancel(ref: ObjectRef, *, force: bool = False,
           recursive: bool = True) -> None:
    _worker.global_worker().cancel(ref, force=force, recursive=recursive)


def cluster_resources() -> Dict[str, float]:
    return _worker.global_worker().cluster_resources()


def available_resources() -> Dict[str, float]:
    return _worker.global_worker().available_resources()


def nodes() -> List[Dict[str, Any]]:
    rt = _worker.global_worker()
    out = []
    for info in rt.gcs.nodes.values():
        node = rt.get_node(info.node_id)
        out.append({
            "NodeID": info.node_id.hex(),
            "Alive": info.alive,
            "Draining": bool(node is not None
                             and getattr(node, "draining", False)),
            "Resources": dict(info.resources),
            "Labels": dict(info.labels),
        })
    return out


def drain_node(node_id: Union[str, Any],
               deadline_s: Optional[float] = None,
               reason: str = "preemption") -> bool:
    """Gracefully drain a node (planned departure: preemption notice,
    downscale, maintenance): new placements avoid it, queued tasks
    resubmit elsewhere, primary object replicas and actors migrate off
    proactively, and once its running work finishes it leaves the
    cluster with no reconstruction debt. If ``deadline_s`` (default:
    the ``drain_deadline_s`` flag) expires first, the drain escalates
    into the ordinary node-death path. Returns True if a drain started."""
    return _worker.global_worker().drain_node(node_id,
                                              deadline_s=deadline_s,
                                              reason=reason)
