"""Autoscaler v2: GCS-state-driven reconciler + instance-manager lifecycle.

Reference: ``autoscaler/v2/instance_manager/reconciler.py`` — the v2
rewrite replaces the v1 monitor's direct polling with a reconciler that
(1) consumes the autoscaler state the GCS assembles (pending resource
demand + cluster shape), (2) tracks every cloud instance through an
explicit state machine (QUEUED → REQUESTED → ALLOCATED → RAY_RUNNING →
RAY_STOPPING → TERMINATED, ``instance_manager.proto``), and (3) drives a
cloud provider toward the desired count.

TPU-first: a "node type" is a WHOLE ICI slice topology (v5e-4, v5p-8,
...) — TPU capacity is provisioned in slice units, never single chips,
so bin-packing selects the smallest slice type covering the unmet TPU
demand (plus CPU hosts for the host plane). The GKE/TPU-VM provider here
is a stub for the cloud API calls (zero-egress build): the
``RuntimeBackedTpuProvider`` materializes "instances" as runtime nodes so
the full reconciler lifecycle is exercised end to end.
"""

from __future__ import annotations

import dataclasses
import enum
import itertools
import math
import threading
import time
from typing import Any, Dict, List, Optional


class InstanceStatus(enum.Enum):
    QUEUED = "QUEUED"
    REQUESTED = "REQUESTED"
    ALLOCATED = "ALLOCATED"
    RAY_RUNNING = "RAY_RUNNING"
    RAY_STOPPING = "RAY_STOPPING"
    TERMINATING = "TERMINATING"
    TERMINATED = "TERMINATED"
    ALLOCATION_FAILED = "ALLOCATION_FAILED"


@dataclasses.dataclass
class Instance:
    instance_id: str
    node_type: str
    status: InstanceStatus = InstanceStatus.QUEUED
    cloud_instance_id: Optional[str] = None
    node: Any = None                       # runtime node once RAY_RUNNING
    history: List[str] = dataclasses.field(default_factory=list)
    updated_at: float = 0.0

    def transition(self, status: InstanceStatus) -> None:
        self.history.append(f"{self.status.value}->{status.value}")
        self.status = status
        self.updated_at = time.time()


class InstanceManager:
    """The instance table + legal transitions (instance_manager/)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instances: Dict[str, Instance] = {}
        self._counter = itertools.count()

    def create(self, node_type: str) -> Instance:
        with self._lock:
            inst = Instance(f"inst-{next(self._counter)}", node_type)
            self._instances[inst.instance_id] = inst
            return inst

    def list(self, *statuses: InstanceStatus) -> List[Instance]:
        with self._lock:
            out = list(self._instances.values())
        if statuses:
            out = [i for i in out if i.status in statuses]
        return out

    def active(self) -> List[Instance]:
        return self.list(InstanceStatus.QUEUED, InstanceStatus.REQUESTED,
                         InstanceStatus.ALLOCATED,
                         InstanceStatus.RAY_RUNNING)


class CloudProvider:
    """Cloud API seam. ``node_types`` maps a slice/host type to its
    resource shape; launch/terminate talk to the cloud."""

    node_types: Dict[str, Dict[str, float]] = {}

    def launch(self, node_type: str) -> str:
        raise NotImplementedError

    def terminate(self, cloud_instance_id: str) -> None:
        raise NotImplementedError

    def poll_allocated(self, cloud_instance_id: str) -> bool:
        """Has the cloud finished provisioning this instance?"""
        raise NotImplementedError


# Slice catalog: TPU capacity comes in whole ICI slices.
TPU_SLICE_TYPES: Dict[str, Dict[str, float]] = {
    "cpu-host": {"CPU": 16.0},
    "v5e-4": {"TPU": 4.0, "CPU": 8.0},
    "v5e-8": {"TPU": 8.0, "CPU": 16.0},
    "v5p-8": {"TPU": 8.0, "CPU": 32.0},
    "v5p-16": {"TPU": 16.0, "CPU": 64.0},
}


class GkeTpuProvider(CloudProvider):
    """GKE / TPU-VM provider STUB: the shape of the real provider (node
    pools keyed by slice topology; create/delete node-pool members via
    the cloud API) with the API calls left unimplemented — this build is
    zero-egress. Use RuntimeBackedTpuProvider to exercise the reconciler.
    """

    node_types = TPU_SLICE_TYPES

    def __init__(self, project: str = "", zone: str = "",
                 cluster: str = ""):
        self.project, self.zone, self.cluster = project, zone, cluster

    def launch(self, node_type: str) -> str:
        raise NotImplementedError(
            "GKE/TPU-VM API is unavailable in this environment; "
            "implement launch() against container.googleapis.com / "
            "tpu.googleapis.com (node pool per slice type)")

    def terminate(self, cloud_instance_id: str) -> None:
        raise NotImplementedError

    def poll_allocated(self, cloud_instance_id: str) -> bool:
        raise NotImplementedError


class RuntimeBackedTpuProvider(CloudProvider):
    """Materializes instances as runtime nodes (the v2 analogue of the
    reference's fake_multi_node provider): full lifecycle, no cloud."""

    node_types = TPU_SLICE_TYPES

    def __init__(self, runtime, provision_delay_s: float = 0.0):
        self.runtime = runtime
        self.provision_delay_s = provision_delay_s
        self._counter = itertools.count()
        self._launched: Dict[str, Dict[str, Any]] = {}

    def launch(self, node_type: str) -> str:
        cid = f"cloud-{next(self._counter)}"
        self._launched[cid] = {"node_type": node_type,
                               "at": time.time(), "node": None}
        return cid

    def poll_allocated(self, cloud_instance_id: str) -> bool:
        entry = self._launched[cloud_instance_id]
        return time.time() - entry["at"] >= self.provision_delay_s

    def materialize(self, cloud_instance_id: str):
        entry = self._launched[cloud_instance_id]
        if entry["node"] is None:
            entry["node"] = self.runtime.add_node(
                dict(self.node_types[entry["node_type"]]),
                labels={"ray_tpu.io/slice-type": entry["node_type"]},
                object_store_memory=256 * 1024 * 1024)
        return entry["node"]

    def terminate(self, cloud_instance_id: str) -> None:
        entry = self._launched.pop(cloud_instance_id, None)
        if entry and entry["node"] is not None and entry["node"].alive:
            self.runtime.remove_node(entry["node"])


class ProcessHostProvider(CloudProvider):
    """A provider that GENUINELY creates hosts: each launch spawns a
    real node-daemon OS process via the cluster launcher (subprocess on
    this machine, or SSH bootstrap with an SshProvider) which registers
    at the head; the driver's node-event subscription then surfaces it
    as a live remote node. This closes the reconciler loop end to end —
    demand -> new PROCESS -> head registration -> schedulable node
    (reference: autoscaler node_provider + NodeUpdater actually
    creating instances; `GkeTpuProvider` remains the cloud-API-shaped
    stub for zero-egress builds)."""

    node_types = TPU_SLICE_TYPES

    def __init__(self, runtime, launcher=None):
        from ray_tpu.cluster_launcher import SubprocessProvider
        self.runtime = runtime
        self.launcher = launcher or SubprocessProvider()
        self._launched: Dict[str, Dict[str, Any]] = {}

    def _head_address(self) -> str:
        backend = getattr(self.runtime, "cluster_backend", None)
        if backend is None:
            raise RuntimeError(
                "ProcessHostProvider needs a daemons-cluster runtime")
        host, port = backend.head.addr
        return f"{host}:{port}"

    def launch(self, node_type: str) -> str:
        rec = self.launcher.create_worker(
            self._head_address(),
            {"resources": dict(self.node_types[node_type])})
        self._launched[rec["node_id"]] = rec
        return rec["node_id"]

    def poll_allocated(self, cloud_instance_id: str) -> bool:
        return True      # the OS process exists the moment spawn returns

    def materialize(self, cloud_instance_id: str):
        """The node is 'running' once the daemon registered at the head
        and the driver's subscription added it; None keeps the instance
        ALLOCATED until then."""
        from ray_tpu._private.ids import NodeID
        return self.runtime.get_node(
            NodeID.from_hex(cloud_instance_id))

    def terminate(self, cloud_instance_id: str) -> None:
        rec = self._launched.pop(cloud_instance_id, None)
        if rec is not None:
            self.launcher.terminate(rec)


def gcs_autoscaler_state(runtime) -> Dict[str, Any]:
    """The cluster-state snapshot the reconciler consumes (the role of
    GcsAutoscalerStateManager): pending demand + per-node shape, derived
    from GCS-visible state rather than runtime internals."""
    demand: Dict[str, float] = {}
    max_chunk: Dict[str, float] = {}   # largest SINGLE task/bundle ask
    for node in runtime.nodes():
        with node._pending_lock:
            for k, v in node._pending_demand.items():
                if k.startswith("_pg_"):
                    k = k.split("_", 4)[-1]
                demand[k] = demand.get(k, 0.0) + v
    # per-task chunk sizes come from the queued specs, NOT the per-node
    # aggregate (10 one-chip tasks must not demand a 10-chip slice)
    with runtime._tasks_lock:
        queued = [t.spec for t in runtime._tasks.values()
                  if t.state in ("PENDING_ARGS_AVAIL",
                                 "PENDING_NODE_ASSIGNMENT")]
    for spec in queued:
        for k, v in (spec.resources or {}).items():
            if k.startswith("_pg_"):
                k = k.split("_", 4)[-1]
            max_chunk[k] = max(max_chunk.get(k, 0.0), v)
    for pg in list(getattr(runtime.pg_manager, "_pending", [])):
        for bundle in pg.bundles:
            for k, v in bundle.resources.items():
                demand[k] = demand.get(k, 0.0) + v
                max_chunk[k] = max(max_chunk.get(k, 0.0), v)
    nodes = []
    for info in runtime.gcs.alive_nodes():
        node = runtime.get_node(info.node_id)
        if node is None or not node.alive:
            continue
        with node._running_lock:
            running = len(node._running)
        nodes.append({"node_id": info.node_id, "running": running,
                      "available": node.ledger.available(),
                      "total": dict(node.ledger.total),
                      "has_actors": bool(node.actors)})
    return {"pending_demand": demand, "max_chunk_demand": max_chunk,
            "nodes": nodes}


class Reconciler:
    """One reconcile pass = sync instance states with the provider and
    the GCS view, then close the gap between desired and actual."""

    def __init__(self, runtime, provider: CloudProvider, *,
                 max_instances: int = 16, idle_timeout_s: float = 5.0,
                 drain_deadline_s: float = 5.0):
        self.runtime = runtime
        self.provider = provider
        self.instance_manager = InstanceManager()
        self.max_instances = max_instances
        self.idle_timeout_s = idle_timeout_s
        self.drain_deadline_s = drain_deadline_s
        self._idle_since: Dict[str, float] = {}
        self.stats = {"reconciles": 0, "launched": 0, "terminated": 0,
                      "drained": 0}

    # -- helpers ----------------------------------------------------------
    def _pick_node_type(self, unmet: Dict[str, float],
                        max_chunk: Dict[str, float]) -> Optional[str]:
        """Smallest slice type that could host the LARGEST single
        task/bundle demand for each unmet resource (TPU comes in whole
        slices; a type smaller than the biggest bundle would launch
        nodes the bundle can never fit on)."""
        best = None
        for node_type, shape in self.provider.node_types.items():
            if not all(shape.get(k, 0.0) >= max(max_chunk.get(k, 0.0),
                                                1e-9)
                       for k in unmet):
                continue
            size = sum(shape.values())
            if best is None or size < best[0]:
                best = (size, node_type)
        return best[1] if best else None

    # -- the pass ---------------------------------------------------------
    def reconcile(self) -> None:
        self.stats["reconciles"] += 1
        im = self.instance_manager

        # 1. advance lifecycle: QUEUED -> REQUESTED
        for inst in im.list(InstanceStatus.QUEUED):
            try:
                inst.cloud_instance_id = self.provider.launch(
                    inst.node_type)
                inst.transition(InstanceStatus.REQUESTED)
                self.stats["launched"] += 1
            except Exception:
                inst.transition(InstanceStatus.ALLOCATION_FAILED)

        # 2. REQUESTED -> ALLOCATED (cloud finished provisioning)
        for inst in im.list(InstanceStatus.REQUESTED):
            try:
                if self.provider.poll_allocated(inst.cloud_instance_id):
                    inst.transition(InstanceStatus.ALLOCATED)
            except Exception:
                inst.transition(InstanceStatus.ALLOCATION_FAILED)

        # 3. ALLOCATED -> RAY_RUNNING (node joined the cluster)
        for inst in im.list(InstanceStatus.ALLOCATED):
            materialize = getattr(self.provider, "materialize", None)
            if materialize is not None:
                inst.node = materialize(inst.cloud_instance_id)
            if inst.node is not None and inst.node.alive:
                inst.transition(InstanceStatus.RAY_RUNNING)

        # 4. desired-state gap from the GCS snapshot
        state = gcs_autoscaler_state(self.runtime)
        demand = state["pending_demand"]
        avail: Dict[str, float] = {}
        for node in state["nodes"]:
            for k, v in node["available"].items():
                if not k.startswith("_pg_"):
                    avail[k] = avail.get(k, 0.0) + v
        unmet = {k: v - avail.get(k, 0.0) for k, v in demand.items()
                 if v > avail.get(k, 0.0) + 1e-9}
        pending_supply = im.list(InstanceStatus.QUEUED,
                                 InstanceStatus.REQUESTED,
                                 InstanceStatus.ALLOCATED)
        if unmet and not pending_supply \
                and len(im.active()) < self.max_instances:
            node_type = self._pick_node_type(
                unmet, state.get("max_chunk_demand", {}))
            if node_type is not None:
                shape = self.provider.node_types[node_type]
                count = max(math.ceil(v / shape[k])
                            for k, v in unmet.items()
                            for k2 in [k] if shape.get(k, 0.0) > 0)
                count = min(count,
                            self.max_instances - len(im.active()))
                for _ in range(max(1, count)):
                    im.create(node_type)

        # 5. drain idle RAY_RUNNING instances
        now = time.time()
        if not unmet:
            for inst in im.list(InstanceStatus.RAY_RUNNING):
                node = inst.node
                idle = (node is not None and node.alive
                        and not node.actors
                        and not self._node_busy(node))
                if idle:
                    since = self._idle_since.setdefault(
                        inst.instance_id, now)
                    if now - since >= self.idle_timeout_s:
                        inst.transition(InstanceStatus.RAY_STOPPING)
                else:
                    self._idle_since.pop(inst.instance_id, None)

        # 6. RAY_STOPPING: graceful drain first, then TERMINATED. The
        # drain migrates any leftover primary object replicas off the
        # idle node BEFORE it disappears — a downscale must never pay
        # lineage reconstruction for data that was sitting on a node we
        # chose to remove. The drain's own deadline escalation bounds
        # how long an instance can linger here.
        for inst in im.list(InstanceStatus.RAY_STOPPING):
            node = inst.node
            still_in = (node is not None
                        and self.runtime.get_node(node.node_id)
                        is not None)
            if still_in and node.alive:
                if self.runtime.begin_node_drain(
                        node, self.drain_deadline_s, "idle downscale"):
                    self.stats["drained"] += 1
                continue        # re-check next pass: drain in flight
            inst.transition(InstanceStatus.TERMINATING)
            try:
                self.provider.terminate(inst.cloud_instance_id)
            except Exception:
                pass
            self.stats["terminated"] += 1
            inst.transition(InstanceStatus.TERMINATED)
            self._idle_since.pop(inst.instance_id, None)

    @staticmethod
    def _node_busy(node) -> bool:
        with node._running_lock:
            if node._running:
                return True
        with node._pending_lock:
            return bool(node._pending_demand)

    # -- loop -------------------------------------------------------------
    def start(self, interval_s: float = 0.5) -> threading.Event:
        stop = threading.Event()

        def loop():
            while not stop.wait(interval_s):
                try:
                    self.reconcile()
                except Exception:
                    pass

        threading.Thread(target=loop, daemon=True,
                         name="autoscaler-v2").start()
        return stop
