"""Search space primitives (reference: `python/ray/tune/search/sample.py`
+ `tune/search/variant_generator.py` grid/resolved-vars machinery)."""

from __future__ import annotations

import math
import random
from typing import Any, Callable, Dict, List, Sequence


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, lo: float, hi: float):
        self.lo, self.hi = lo, hi

    def sample(self, rng):
        return rng.uniform(self.lo, self.hi)


class LogUniform(Domain):
    def __init__(self, lo: float, hi: float):
        self.lo, self.hi = lo, hi

    def sample(self, rng):
        return math.exp(rng.uniform(math.log(self.lo), math.log(self.hi)))


class RandInt(Domain):
    def __init__(self, lo: int, hi: int):
        self.lo, self.hi = lo, hi

    def sample(self, rng):
        return rng.randrange(self.lo, self.hi)


class Choice(Domain):
    def __init__(self, options: Sequence[Any]):
        self.options = list(options)

    def sample(self, rng):
        return rng.choice(self.options)


class GridSearch:
    def __init__(self, values: Sequence[Any]):
        self.values = list(values)


class SampleFrom(Domain):
    def __init__(self, fn: Callable[[Dict], Any]):
        self.fn = fn

    def sample(self, rng):
        return self.fn({})


def uniform(lo: float, hi: float) -> Uniform:
    return Uniform(lo, hi)


def loguniform(lo: float, hi: float) -> LogUniform:
    return LogUniform(lo, hi)


def randint(lo: int, hi: int) -> RandInt:
    return RandInt(lo, hi)


def choice(options: Sequence[Any]) -> Choice:
    return Choice(options)


def grid_search(values: Sequence[Any]) -> GridSearch:
    return GridSearch(values)


def sample_from(fn: Callable) -> SampleFrom:
    return SampleFrom(fn)


def generate_variants(space: Dict[str, Any], num_samples: int,
                      seed: int = 0) -> List[Dict[str, Any]]:
    """Expand grid axes (cross product) × num_samples random draws of the
    stochastic axes (reference: BasicVariantGenerator semantics)."""
    rng = random.Random(seed)
    grids = {k: v.values for k, v in space.items()
             if isinstance(v, GridSearch)}
    grid_combos: List[Dict[str, Any]] = [{}]
    for key, values in grids.items():
        grid_combos = [dict(c, **{key: v}) for c in grid_combos
                       for v in values]
    out = []
    for _ in range(num_samples):
        for combo in grid_combos:
            cfg = {}
            for k, v in space.items():
                if isinstance(v, GridSearch):
                    cfg[k] = combo[k]
                elif isinstance(v, Domain):
                    cfg[k] = v.sample(rng)
                else:
                    cfg[k] = v
            out.append(cfg)
    return out
