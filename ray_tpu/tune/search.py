"""Search algorithms: random/grid variants, quasi-random, and TPE.

Reference: ``python/ray/tune/search/`` — the Searcher interface
(`suggest`/`on_trial_complete`) with pluggable backends (optuna,
hyperopt, bohb…). None of those libraries exist in this image, so the
backends are NATIVE implementations of the same algorithms:

- :class:`BasicVariantGenerator` — grid/random (the default path).
- :class:`HaltonSearcher` — deterministic low-discrepancy (quasi-random)
  sweeps; better space coverage than iid sampling at small budgets.
- :class:`TPESearcher` — Tree-structured Parzen Estimator (the algorithm
  behind hyperopt): after a random startup phase, observations split
  into good/bad by quantile; candidates are drawn from a KDE over the
  good set and ranked by the density ratio l(x)/g(x).

All searchers speak the Domain vocabulary of
:mod:`ray_tpu.tune.search_space` (Uniform/LogUniform/RandInt/Choice).
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.tune.search_space import (Choice, Domain, GridSearch,
                                       LogUniform, RandInt, Uniform)


class Searcher:
    """suggest() yields configs; on_trial_complete() feeds results back."""

    def set_search_space(self, space: Dict[str, Any]) -> None:
        self.space = space

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str,
                          metric_value: Optional[float]) -> None:
        pass


class BasicVariantGenerator(Searcher):
    """iid random sampling (grid handled by the default variant path)."""

    def __init__(self, seed: int = 0):
        self._rng = random.Random(seed)

    def suggest(self, trial_id: str) -> Dict[str, Any]:
        out = {}
        for key, dom in self.space.items():
            if isinstance(dom, Domain):
                out[key] = dom.sample(self._rng)
            elif isinstance(dom, GridSearch):
                out[key] = self._rng.choice(dom.values)
            else:
                out[key] = dom
        return out


def _halton(index: int, base: int) -> float:
    """Halton low-discrepancy point in (0, 1)."""
    f, r = 1.0, 0.0
    i = index + 1
    while i > 0:
        f /= base
        r += f * (i % base)
        i //= base
    return r


_PRIMES = [2, 3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37, 41, 43]


class HaltonSearcher(Searcher):
    """Deterministic quasi-random sweep: dimension d uses the Halton
    sequence in base prime[d]."""

    def __init__(self):
        self._count = 0

    def _from_unit(self, dom, u: float, index: int):
        if isinstance(dom, Uniform):
            return dom.lo + u * (dom.hi - dom.lo)
        if isinstance(dom, LogUniform):
            return math.exp(math.log(dom.lo)
                            + u * (math.log(dom.hi) - math.log(dom.lo)))
        if isinstance(dom, RandInt):
            return min(dom.lo + int(u * (dom.hi - dom.lo)), dom.hi - 1)
        if isinstance(dom, Choice):
            return dom.options[index % len(dom.options)]
        if isinstance(dom, GridSearch):
            return dom.values[index % len(dom.values)]
        return dom

    def suggest(self, trial_id: str) -> Dict[str, Any]:
        i = self._count
        self._count += 1
        out = {}
        for d, (key, dom) in enumerate(sorted(self.space.items())):
            u = _halton(i, _PRIMES[d % len(_PRIMES)])
            out[key] = self._from_unit(dom, u, i)
        return out


class TPESearcher(Searcher):
    """Tree-structured Parzen Estimator (hyperopt's algorithm), native.

    minimize mode is handled by the caller passing scores where LOWER is
    better (the Tuner normalizes max-mode by negating)."""

    def __init__(self, n_startup: int = 8, gamma: float = 0.25,
                 n_candidates: int = 24, seed: int = 0):
        self.n_startup = n_startup
        self.gamma = gamma
        self.n_candidates = n_candidates
        self._rng = random.Random(seed)
        self._observed: List[Tuple[Dict[str, Any], float]] = []
        self._pending: Dict[str, Dict[str, Any]] = {}

    # -- observation -------------------------------------------------------
    def on_trial_complete(self, trial_id: str,
                          metric_value: Optional[float]) -> None:
        config = self._pending.pop(trial_id, None)
        if config is not None and metric_value is not None \
                and math.isfinite(metric_value):
            self._observed.append((config, float(metric_value)))

    def _model_observations(self) -> List[Tuple[Dict[str, Any], float]]:
        """The observation pool the Parzen model fits; BOHB narrows this
        to the most-informative fidelity."""
        return self._observed

    # -- numeric helpers ---------------------------------------------------
    @staticmethod
    def _to_unit(dom, value) -> Optional[float]:
        try:
            if isinstance(dom, Uniform):
                return (value - dom.lo) / max(dom.hi - dom.lo, 1e-12)
            if isinstance(dom, LogUniform):
                return ((math.log(value) - math.log(dom.lo))
                        / max(math.log(dom.hi) - math.log(dom.lo), 1e-12))
            if isinstance(dom, RandInt):
                return (value - dom.lo) / max(dom.hi - dom.lo, 1e-12)
        except (TypeError, ValueError):
            return None
        return None

    def _from_unit(self, dom, u: float):
        u = min(max(u, 0.0), 1.0)
        if isinstance(dom, Uniform):
            return dom.lo + u * (dom.hi - dom.lo)
        if isinstance(dom, LogUniform):
            return math.exp(math.log(dom.lo)
                            + u * (math.log(dom.hi) - math.log(dom.lo)))
        if isinstance(dom, RandInt):
            return min(dom.lo + int(round(u * (dom.hi - dom.lo))),
                       dom.hi - 1)
        return None

    @staticmethod
    def _kde_logpdf(x: float, points: List[float], bw: float) -> float:
        if not points:
            return 0.0
        acc = 0.0
        for p in points:
            acc += math.exp(-0.5 * ((x - p) / bw) ** 2)
        return math.log(max(acc / (len(points) * bw), 1e-12))

    # -- suggestion --------------------------------------------------------
    def _random_config(self) -> Dict[str, Any]:
        return {key: (dom.sample(self._rng)
                      if isinstance(dom, Domain)
                      else (self._rng.choice(dom.values)
                            if isinstance(dom, GridSearch) else dom))
                for key, dom in self.space.items()}

    def suggest(self, trial_id: str) -> Dict[str, Any]:
        observed = self._model_observations()
        if len(observed) < self.n_startup:
            config = self._random_config()
            self._pending[trial_id] = config
            return config

        ranked = sorted(observed, key=lambda cv: cv[1])
        n_good = max(1, int(self.gamma * len(ranked)))
        good, bad = ranked[:n_good], ranked[n_good:]

        config: Dict[str, Any] = {}
        for key, dom in self.space.items():
            if isinstance(dom, (Uniform, LogUniform, RandInt)):
                g_pts = [u for cfg, _ in good
                         if (u := self._to_unit(dom, cfg.get(key)))
                         is not None]
                b_pts = [u for cfg, _ in bad
                         if (u := self._to_unit(dom, cfg.get(key)))
                         is not None]
                bw = max(1.0 / max(len(g_pts), 1) ** 0.5 * 0.4, 0.05)
                best_u, best_score = self._rng.random(), -1e18
                for _ in range(self.n_candidates):
                    src = self._rng.choice(g_pts) if g_pts \
                        else self._rng.random()
                    u = min(max(self._rng.gauss(src, bw), 0.0), 1.0)
                    score = (self._kde_logpdf(u, g_pts, bw)
                             - self._kde_logpdf(u, b_pts, bw))
                    if score > best_score:
                        best_u, best_score = u, score
                config[key] = self._from_unit(dom, best_u)
            elif isinstance(dom, (Choice, GridSearch)):
                options = (dom.options if isinstance(dom, Choice)
                           else dom.values)
                weights = []
                for opt in options:
                    g_n = sum(1 for cfg, _ in good if cfg.get(key) == opt)
                    b_n = sum(1 for cfg, _ in bad if cfg.get(key) == opt)
                    weights.append((g_n + 0.5) / (b_n + 0.5))
                total = sum(weights)
                r = self._rng.random() * total
                for opt, w in zip(options, weights):
                    r -= w
                    if r <= 0:
                        config[key] = opt
                        break
                else:
                    config[key] = options[-1]
            elif isinstance(dom, Domain):
                config[key] = dom.sample(self._rng)
            else:
                config[key] = dom
        self._pending[trial_id] = config
        return config


class BOHBSearcher(TPESearcher):
    """BOHB's model-based component (reference:
    ``python/ray/tune/search/bohb/bohb_search.py`` wrapping HpBandSter):
    a TPE whose observation pool is MULTI-FIDELITY — intermediate
    results at each rung budget feed per-budget pools, and the Parzen
    model fits the largest budget that has enough observations. Pair it
    with the ASHA scheduler (the async-hyperband role) for full BOHB
    behavior: the scheduler culls, this searcher proposes.

    The trial controller calls :meth:`on_trial_result` for every
    ``tune.report`` (budget = the scheduler's time_attr value).
    """

    def __init__(self, n_startup: int = 8, gamma: float = 0.25,
                 n_candidates: int = 24, seed: int = 0):
        super().__init__(n_startup=n_startup, gamma=gamma,
                         n_candidates=n_candidates, seed=seed)
        # budget -> {trial_id: (config, latest value at that budget)}
        self._by_budget: Dict[int, Dict[str, Tuple[Dict[str, Any],
                                                   float]]] = {}
        # trial_id -> config, kept past completion: the controller can
        # drain a trial's intermediate reports AFTER its final result
        # (poll/finalize ordering), and those rung observations must
        # still land in the per-budget pools
        self._configs: Dict[str, Dict[str, Any]] = {}

    def suggest(self, trial_id: str) -> Dict[str, Any]:
        config = super().suggest(trial_id)
        self._configs[trial_id] = config
        return config

    def on_trial_result(self, trial_id: str, budget: Any,
                        metric_value: Optional[float]) -> None:
        config = (self._pending.get(trial_id)
                  or self._configs.get(trial_id))
        if (config is None or metric_value is None
                or not math.isfinite(metric_value)):
            return
        try:
            b = int(budget)
        except (TypeError, ValueError):
            return
        self._by_budget.setdefault(b, {})[trial_id] = (
            config, float(metric_value))

    def on_trial_complete(self, trial_id: str,
                          metric_value: Optional[float]) -> None:
        config = self._pending.pop(trial_id, None)
        if config is not None and metric_value is not None \
                and math.isfinite(metric_value):
            self._observed.append((config, float(metric_value)))

    def _model_observations(self) -> List[Tuple[Dict[str, Any], float]]:
        # BOHB rule: fit on the LARGEST budget with enough points —
        # high-fidelity signal dominates when available, low-fidelity
        # rungs bootstrap the model early
        for b in sorted(self._by_budget, reverse=True):
            pool = self._by_budget[b]
            if len(pool) >= self.n_startup:
                return list(pool.values())
        if self._observed:
            return self._observed
        # fall back to the richest partial pool to leave startup ASAP
        best: List[Tuple[Dict[str, Any], float]] = []
        for pool in self._by_budget.values():
            if len(pool) > len(best):
                best = list(pool.values())
        return best
