"""Trial schedulers (reference: `tune/schedulers/` — FIFO, ASHA
`async_hyperband.py`, PBT `pbt.py`)."""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class TrialScheduler:
    def on_result(self, trial, result: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_complete(self, trial) -> None:
        pass


class FIFOScheduler(TrialScheduler):
    pass


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA: asynchronous successive halving. At each rung (time_attr
    hitting a milestone), a trial is stopped unless it's in the top 1/rf of
    completed results at that rung (reference:
    `tune/schedulers/async_hyperband.py` — the async variant never waits
    for a full rung)."""

    def __init__(self, *, metric: str = "loss", mode: str = "min",
                 time_attr: str = "training_iteration",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 4):
        assert mode in ("min", "max")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.max_t = max_t
        self.grace = grace_period
        self.rf = reduction_factor
        # rung milestone -> list of recorded metric values
        self.rungs: Dict[int, List[float]] = {}
        milestones = []
        t = grace_period
        while t < max_t:
            milestones.append(t)
            t *= reduction_factor
        self.milestones = milestones

    def on_result(self, trial, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr)
        val = result.get(self.metric)
        if t is None or val is None:
            return CONTINUE
        val = float(val) if self.mode == "min" else -float(val)
        for m in self.milestones:
            if t == m:
                recorded = self.rungs.setdefault(m, [])
                recorded.append(val)
                k = max(1, len(recorded) // self.rf)
                cutoff = sorted(recorded)[k - 1]
                if val > cutoff:
                    return STOP
        if t >= self.max_t:
            return STOP
        return CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT: at each perturbation interval, bottom-quantile trials copy the
    config (+ checkpoint state, via re-seeding config) of a top-quantile
    trial and perturb hyperparams (reference: `tune/schedulers/pbt.py`)."""

    def __init__(self, *, metric: str = "score", mode: str = "max",
                 time_attr: str = "training_iteration",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25, seed: int = 0):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.rng = random.Random(seed)
        self.latest: Dict[Any, Dict] = {}   # trial -> last result

    def on_result(self, trial, result: Dict[str, Any]) -> str:
        self.latest[trial] = result
        t = result.get(self.time_attr, 0)
        if t and t % self.interval == 0:
            self._maybe_exploit(trial, result)
        return CONTINUE

    def _score(self, r):
        v = float(r.get(self.metric, -math.inf))
        return v if self.mode == "max" else -v

    def _maybe_exploit(self, trial, result) -> None:
        if len(self.latest) < 2:
            return
        ranked = sorted(self.latest.items(),
                        key=lambda kv: self._score(kv[1]), reverse=True)
        n = len(ranked)
        k = max(1, int(n * self.quantile))
        bottom = [t for t, _ in ranked[-k:]]
        top = [t for t, _ in ranked[:k]]
        if trial in bottom and top:
            donor = self.rng.choice(top)
            trial.config = dict(donor.config)
            self._perturb(trial.config)
            trial.pbt_exploited = True

    def _perturb(self, config: Dict[str, Any]) -> None:
        from ray_tpu.tune.search_space import Domain
        for key, spec in self.mutations.items():
            if key not in config:
                continue
            if isinstance(spec, list):
                config[key] = self.rng.choice(spec)
            elif isinstance(spec, Domain):
                config[key] = spec.sample(self.rng)
            elif callable(spec):
                config[key] = spec()
            else:
                factor = self.rng.choice([0.8, 1.2])
                config[key] = config[key] * factor
