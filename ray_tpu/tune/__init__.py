"""ray_tpu.tune — hyperparameter search / experiment execution.

Reference: Ray Tune (`python/ray/tune`, SURVEY.md §2.2): Tuner → trials →
searchers + schedulers (ASHA/PBT) → trainable actors, with intermediate
reporting and checkpoint plumbing shared with Train.
"""

from ray_tpu.tune.schedulers import (AsyncHyperBandScheduler, FIFOScheduler,
                                     PopulationBasedTraining, TrialScheduler)
from ray_tpu.tune.search import (BasicVariantGenerator, BOHBSearcher,
                                 HaltonSearcher, Searcher, TPESearcher)
from ray_tpu.tune.search_space import (choice, grid_search, loguniform,
                                       randint, sample_from, uniform)
from ray_tpu.tune.tuner import (ResultGrid, Trial, TuneConfig, Tuner, report,
                                with_parameters)

__all__ = [
    "Tuner", "TuneConfig", "ResultGrid", "Trial", "report",
    "with_parameters",
    "uniform", "loguniform", "randint", "choice", "grid_search",
    "sample_from",
    "FIFOScheduler", "AsyncHyperBandScheduler", "PopulationBasedTraining",
    "TrialScheduler",
    "Searcher", "BasicVariantGenerator", "HaltonSearcher", "TPESearcher",
    "BOHBSearcher",
]
