"""Tuner + trial controller (reference: `tune/tuner.py`, `tune/tune.py`,
`tune/execution/tune_controller.py:68,666`).

Trials run as gang of actors polled by the controller event loop; the
scheduler (FIFO/ASHA/PBT) acts on every intermediate `tune.report`.

Experiment persistence / restore (reference: ``Tuner.restore`` +
experiment-state snapshots): with a ``run_config`` the controller
snapshots every trial's (config, status, results, checkpoint) to
``<storage>/<name>/tuner_state.pkl`` after each event-loop step;
``Tuner.restore(path, trainable)`` rebuilds the experiment — finished
trials keep their results, unfinished ones re-run from their last
reported checkpoint — so a killed experiment resumes with the trial
count conserved.
"""

from __future__ import annotations

import dataclasses
import itertools
import math
import os
import time
import traceback
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.session import TrainContext, _set_context
from ray_tpu.tune.schedulers import (CONTINUE, STOP, FIFOScheduler,
                                     TrialScheduler)
from ray_tpu.tune.search_space import generate_variants


@dataclasses.dataclass
class TuneConfig:
    metric: str = "loss"
    mode: str = "min"
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    scheduler: Optional[TrialScheduler] = None
    # Searcher (tune/search.py: BasicVariantGenerator, HaltonSearcher,
    # TPESearcher); None = grid/random variant generation.
    search_alg: Optional[Any] = None
    seed: int = 0


class Trial:
    _ids = itertools.count()

    def __init__(self, config: Dict[str, Any]):
        self.id = f"trial_{next(Trial._ids):05d}"
        self.config = config
        self.status = "PENDING"
        self.results: List[Dict[str, Any]] = []
        self.error: Optional[str] = None
        self.actor = None
        self.run_ref = None
        self.pbt_exploited = False
        self.checkpoint: Optional[Checkpoint] = None

    @property
    def last_result(self) -> Dict[str, Any]:
        return self.results[-1] if self.results else {}

    def snapshot(self) -> Dict[str, Any]:
        return {"id": self.id, "config": self.config,
                "status": self.status, "results": list(self.results),
                "error": self.error, "checkpoint": self.checkpoint,
                "search_id": getattr(self, "search_id", None)}

    @staticmethod
    def from_snapshot(d: Dict[str, Any]) -> "Trial":
        t = Trial(d["config"])
        t.id = d["id"]
        t.error = d["error"]
        t.checkpoint = d.get("checkpoint")
        if d.get("search_id") is not None:
            t.search_id = d["search_id"]
        # Anything not finished re-runs (a RUNNING trial died with the
        # experiment process).
        if d["status"] in ("TERMINATED", "STOPPED", "ERROR"):
            t.status = d["status"]
            t.results = list(d["results"])
        else:
            # Re-running from the last checkpoint re-reports those steps:
            # keep the checkpoint, drop the partial results so they are
            # not double-counted in the resumed run.
            t.status = "PENDING"
            t.results = []
        return t


class _TrialActor:
    """Runs one trial's trainable; buffers intermediate reports."""

    def __init__(self):
        self._buffer: List[Dict] = []
        self._stop = None

    def run(self, fn: Callable, config: Dict[str, Any],
            checkpoint: Optional[Checkpoint] = None) -> Optional[Dict]:
        ctx = TrainContext(world_rank=0, world_size=1,
                           experiment_name="tune",
                           latest_checkpoint=checkpoint)
        ctx._report_cb = lambda e: self._buffer.append(e)
        self._stop = ctx._stop_event
        _set_context(ctx)
        try:
            out = fn(config)
            if isinstance(out, dict):
                self._buffer.append({"metrics": out, "checkpoint": None,
                                     "rank": 0})
            return out
        finally:
            _set_context(None)

    def poll(self) -> List[Dict]:
        drained, self._buffer = self._buffer, []
        return drained

    def stop(self) -> None:
        if self._stop is not None:
            self._stop.set()

    def ping(self) -> bool:
        return True


@dataclasses.dataclass
class ResultGrid:
    trials: List[Trial]
    metric: str
    mode: str

    def get_best_result(self) -> "TrialResult":
        def score(t: Trial) -> float:
            v = t.last_result.get(self.metric)
            if v is None:
                return -math.inf
            return float(v) if self.mode == "max" else -float(v)
        best = max(self.trials, key=score)
        return TrialResult(best)

    def __iter__(self):
        return (TrialResult(t) for t in self.trials)

    def __len__(self):
        return len(self.trials)

    @property
    def errors(self) -> List[str]:
        return [t.error for t in self.trials if t.error]


@dataclasses.dataclass
class TrialResult:
    trial: Trial

    @property
    def metrics(self) -> Dict[str, Any]:
        return self.trial.last_result

    @property
    def config(self) -> Dict[str, Any]:
        return self.trial.config

    @property
    def error(self) -> Optional[str]:
        return self.trial.error


class Tuner:
    def __init__(self, trainable: Callable, *,
                 param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config=None):
        # Train-on-Tune (reference: base_trainer.py:692 wraps a Trainer as
        # a one-trial Tune trainable): a JaxTrainer becomes a trainable
        # whose config overrides train_loop_config per trial.
        from ray_tpu.train.trainer import JaxTrainer
        if isinstance(trainable, JaxTrainer):
            trainable = _trainer_as_trainable(trainable)
            if param_space and "train_loop_config" in param_space:
                param_space = dict(param_space["train_loop_config"])
        self.trainable = trainable
        self.param_space = param_space or {}
        self.cfg = tune_config or TuneConfig()
        self.run_config = run_config
        self._restored_trials: Optional[List[Trial]] = None

    # -- experiment persistence (reference: Tuner.restore) --------------
    @property
    def experiment_path(self) -> Optional[str]:
        if self.run_config is None:
            return None
        return self.run_config.resolved_storage_path()

    def _state_file(self) -> Optional[str]:
        path = self.experiment_path
        return os.path.join(path, "tuner_state.pkl") if path else None

    def _save_state(self, trials: List[Trial]) -> None:
        state_file = self._state_file()
        if state_file is None:
            return
        # only snapshot when something actually changed (a long event
        # loop otherwise rewrites identical state every ~0.1s tick)
        sig = tuple((t.id, t.status, len(t.results)) for t in trials)
        if sig == getattr(self, "_last_sig", None):
            return
        self._last_sig = sig
        import cloudpickle

        os.makedirs(os.path.dirname(state_file), exist_ok=True)
        try:
            searcher_blob = cloudpickle.dumps(self.cfg.search_alg)
        except Exception:
            searcher_blob = None
        blob = cloudpickle.dumps({
            "metric": self.cfg.metric, "mode": self.cfg.mode,
            "num_samples": self.cfg.num_samples,
            "searcher": searcher_blob,
            "trials": [t.snapshot() for t in trials]})
        tmp = state_file + ".tmp"
        with open(tmp, "wb") as f:   # atomic: a crash never half-writes
            f.write(blob)
        os.replace(tmp, state_file)

    @classmethod
    def restore(cls, path: str, trainable: Callable) -> "Tuner":
        """Rebuild a killed experiment from its state snapshots. Finished
        trials keep their results; unfinished ones re-run from their last
        reported checkpoint. ``trainable`` must be the same callable the
        experiment was built with (functions don't round-trip through the
        snapshot, same as the reference's restore contract)."""
        import cloudpickle

        from ray_tpu.train.config import RunConfig

        state_file = os.path.join(path, "tuner_state.pkl")
        with open(state_file, "rb") as f:
            state = cloudpickle.loads(f.read())
        searcher = None
        if state.get("searcher"):
            try:
                # the pickled searcher carries its observations, so an
                # adaptive search (TPE) resumes where it left off
                searcher = cloudpickle.loads(state["searcher"])
            except Exception:
                searcher = None
        base, name = os.path.dirname(path), os.path.basename(path)
        tuner = cls(trainable,
                    tune_config=TuneConfig(metric=state["metric"],
                                           mode=state["mode"],
                                           num_samples=state["num_samples"],
                                           search_alg=searcher),
                    run_config=RunConfig(name=name, storage_path=base))
        tuner._restored_trials = [Trial.from_snapshot(s)
                                  for s in state["trials"]]
        return tuner

    @staticmethod
    def can_restore(path: str) -> bool:
        return os.path.exists(os.path.join(path, "tuner_state.pkl"))

    def fit(self) -> ResultGrid:
        from ray_tpu.train.callbacks import invoke as _cb
        cbs = (self.run_config.callbacks
               if self.run_config is not None else [])
        _cb(cbs, "on_run_start",
            (self.run_config.name if self.run_config else None)
            or "tune_run", dict(self.param_space))
        scheduler = self.cfg.scheduler or FIFOScheduler()
        searcher = self.cfg.search_alg
        if self._restored_trials is not None:
            trials = self._restored_trials
            # searcher experiments: trials that were never created before
            # the kill still owe their samples (trial count conserved)
            to_create = (max(0, self.cfg.num_samples - len(trials))
                         if searcher is not None else 0)
        elif searcher is not None:
            searcher.set_search_space(self.param_space)
            trials: List[Trial] = []
            to_create = self.cfg.num_samples
        else:
            variants = generate_variants(
                self.param_space, self.cfg.num_samples, self.cfg.seed)
            trials = [Trial(v) for v in variants]
            to_create = 0
        limit = self.cfg.max_concurrent_trials or max(
            1, int(ray_tpu.cluster_resources().get("CPU", 4)))
        actor_cls = ray_tpu.remote(_TrialActor)

        pending = [t for t in trials if t.status == "PENDING"]
        running: List[Trial] = []
        while pending or running or to_create > 0:
            # searcher-driven trials are created lazily as slots free, so
            # adaptive searchers (TPE) see completed results first
            while to_create > 0 and len(running) + len(pending) < limit:
                trial_id = f"trial-{self.cfg.num_samples - to_create}"
                trial = Trial(searcher.suggest(trial_id))
                trial.search_id = trial_id
                trials.append(trial)
                pending.append(trial)
                to_create -= 1
            while pending and len(running) < limit:
                trial = pending.pop(0)
                trial.actor = actor_cls.options(max_concurrency=2).remote()
                trial.run_ref = trial.actor.run.remote(
                    self.trainable, trial.config, trial.checkpoint)
                trial.status = "RUNNING"
                running.append(trial)

            # Drain intermediate reports; let the scheduler stop trials.
            for trial in list(running):
                try:
                    entries = ray_tpu.get(trial.actor.poll.remote(),
                                          timeout=30)
                except Exception:
                    entries = []
                for entry in entries:
                    self._consume_entry(trial, entry, cbs)
                    # multi-fidelity searchers (BOHB) ingest every
                    # intermediate report at its budget (= the
                    # scheduler's time_attr value)
                    on_res = getattr(searcher, "on_trial_result", None)
                    if on_res is not None:
                        metrics = entry["metrics"]
                        value = metrics.get(self.cfg.metric)
                        if value is not None:
                            if self.cfg.mode == "max":
                                value = -float(value)
                            on_res(getattr(trial, "search_id", ""),
                                   metrics.get(
                                       getattr(scheduler, "time_attr",
                                               "training_iteration")),
                                   value)
                    if scheduler.on_result(trial, entry["metrics"]) == STOP:
                        trial.actor.stop.remote()
                        trial.status = "STOPPED"

            done, _ = ray_tpu.wait([t.run_ref for t in running],
                                   num_returns=len(running), timeout=0.1)
            done_set = set(done)
            for trial in list(running):
                if trial.run_ref in done_set:
                    self._finalize(trial, scheduler, cbs)
                    running.remove(trial)
                    if searcher is not None:
                        value = trial.last_result.get(self.cfg.metric)
                        if value is not None and self.cfg.mode == "max":
                            value = -float(value)
                        searcher.on_trial_complete(
                            getattr(trial, "search_id", ""), value)
            self._save_state(trials)  # crash-resume snapshot per step
        self._save_state(trials)
        grid = ResultGrid(trials=trials, metric=self.cfg.metric,
                          mode=self.cfg.mode)
        _cb(cbs, "on_run_end", grid)
        return grid

    @staticmethod
    def _consume_entry(trial: Trial, entry: dict, cbs) -> None:
        """Per-report handling shared by the live event loop and the
        finalize drain: record metrics, fire logger callbacks, advance
        the trial's checkpoint pointer to the latest reported one."""
        from ray_tpu.train.callbacks import invoke as _cb

        trial.results.append(entry["metrics"])
        _cb(cbs, "on_report", entry["metrics"],
            len(trial.results), trial_id=trial.id)
        if entry.get("checkpoint") is not None:
            trial.checkpoint = entry["checkpoint"]

    def _finalize(self, trial: Trial, scheduler: TrialScheduler,
                  cbs=()) -> None:
        try:
            ray_tpu.get(trial.run_ref)
            if trial.status != "STOPPED":
                trial.status = "TERMINATED"
        except Exception as e:
            msg = repr(e)
            if "StopIteration" in msg or trial.status == "STOPPED":
                trial.status = "STOPPED"
            else:
                trial.status = "ERROR"
                trial.error = msg
        # drain any last reports; a timed-out get under host load must
        # not silently lose the trial's final metrics — retry the SAME
        # poll ref (a fresh poll.remote() would find an already-drained
        # buffer: the first poll still executes server-side)
        try:
            poll_ref = trial.actor.poll.remote()
        except Exception:
            poll_ref = None
        if poll_ref is not None:
            for attempt in range(2):
                try:
                    for entry in ray_tpu.get(poll_ref, timeout=30):
                        self._consume_entry(trial, entry, cbs)
                    break
                except Exception:
                    if attempt == 1:
                        break
                    time.sleep(0.5)
        scheduler.on_trial_complete(trial)
        try:
            ray_tpu.kill(trial.actor)
        except Exception:
            pass


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    """`tune.report` — alias of the train session report."""
    from ray_tpu.train.session import report as _report
    _report(metrics, checkpoint)


def _trainer_as_trainable(trainer) -> Callable:
    import copy

    def trainable(config: Dict[str, Any]):
        t = copy.copy(trainer)
        merged = dict(trainer.train_loop_config or {})
        merged.update(config)
        t.train_loop_config = merged
        result = t.fit()
        if result.error:
            raise RuntimeError(result.error)
        return dict(result.metrics)

    return trainable


def with_parameters(fn: Callable, **params) -> Callable:
    def wrapped(config):
        return fn(config, **params)
    return wrapped
