"""Job manager: per-job supervisor actors running shell entrypoints.

Reference: `dashboard/modules/job/job_manager.py:60,133` (supervisor
actor per job, subprocess entrypoint, status/logs); SDK shape of
`dashboard/modules/job/sdk.py` JobSubmissionClient.
"""

from __future__ import annotations

import dataclasses
import os
import subprocess
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

import ray_tpu


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"


@dataclasses.dataclass
class JobInfo:
    job_id: str
    entrypoint: str
    status: str
    returncode: Optional[int] = None
    start_time: Optional[float] = None
    end_time: Optional[float] = None
    metadata: Optional[Dict[str, str]] = None


class _JobSupervisor:
    """Actor: runs one job entrypoint as a subprocess and tails it."""

    def __init__(self, job_id: str, entrypoint: str,
                 runtime_env: Optional[Dict] = None,
                 metadata: Optional[Dict] = None):
        self.info = JobInfo(job_id=job_id, entrypoint=entrypoint,
                            status=JobStatus.PENDING, metadata=metadata)
        self._logs: List[str] = []
        self._proc: Optional[subprocess.Popen] = None
        self._lock = threading.Lock()
        env = dict(os.environ)
        for k, v in (runtime_env or {}).get("env_vars", {}).items():
            env[k] = str(v)
        self._env = env
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        self.info.status = JobStatus.RUNNING
        from ray_tpu._private.export_events import emit_export
        emit_export("JOB", job_id=self.info.job_id, state="RUNNING",
                    entrypoint=self.info.entrypoint)
        self.info.start_time = time.time()
        try:
            self._proc = subprocess.Popen(
                self.info.entrypoint, shell=True, env=self._env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True)
            for line in self._proc.stdout:
                with self._lock:
                    self._logs.append(line)
            rc = self._proc.wait()
            self.info.returncode = rc
            if self.info.status != JobStatus.STOPPED:
                from ray_tpu._private.export_events import emit_export
                emit_export("JOB", job_id=self.info.job_id,
                            state="SUCCEEDED" if rc == 0 else "FAILED")
                self.info.status = (JobStatus.SUCCEEDED if rc == 0
                                    else JobStatus.FAILED)
        except Exception as e:
            with self._lock:
                self._logs.append(f"supervisor error: {e!r}\n")
            self.info.status = JobStatus.FAILED
        finally:
            self.info.end_time = time.time()

    def status(self) -> JobInfo:
        return self.info

    def logs(self) -> str:
        with self._lock:
            return "".join(self._logs)

    def stop(self) -> bool:
        if self._proc is not None and self._proc.poll() is None:
            self.info.status = JobStatus.STOPPED
            self._proc.terminate()
            return True
        return False


class JobSubmissionClient:
    """In-cluster job SDK (HTTP indirection of the reference elided —
    the dashboard exposes the same data over REST)."""

    def __init__(self):
        self._supervisors: Dict[str, Any] = {}

    def submit_job(self, *, entrypoint: str,
                   runtime_env: Optional[Dict] = None,
                   metadata: Optional[Dict] = None,
                   submission_id: Optional[str] = None) -> str:
        job_id = submission_id or f"raysubmit_{uuid.uuid4().hex[:12]}"
        # the supervisor actor is spawned inside the job's tenancy
        # scope: the actor-creation spec and every task the entrypoint
        # fans out inherit this job_id, so fair-share accounting and
        # /api/jobs attribute the whole job tree to its tenant
        from ray_tpu.tenancy import job_context
        sup_cls = ray_tpu.remote(_JobSupervisor)
        with job_context(job_id):
            sup = sup_cls.options(max_concurrency=4).remote(
                job_id, entrypoint, runtime_env, metadata)
        self._supervisors[job_id] = sup
        return job_id

    def get_job_status(self, job_id: str) -> str:
        return ray_tpu.get(
            self._supervisors[job_id].status.remote()).status

    def get_job_info(self, job_id: str) -> JobInfo:
        return ray_tpu.get(self._supervisors[job_id].status.remote())

    def get_job_logs(self, job_id: str) -> str:
        return ray_tpu.get(self._supervisors[job_id].logs.remote())

    def stop_job(self, job_id: str) -> bool:
        return ray_tpu.get(self._supervisors[job_id].stop.remote())

    def list_jobs(self) -> List[JobInfo]:
        return [ray_tpu.get(s.status.remote())
                for s in self._supervisors.values()]

    def wait_until_finished(self, job_id: str, timeout: float = 60.0,
                            poll_s: float = 0.2) -> str:
        deadline = time.time() + timeout
        while time.time() < deadline:
            st = self.get_job_status(job_id)
            if st in (JobStatus.SUCCEEDED, JobStatus.FAILED,
                      JobStatus.STOPPED):
                return st
            time.sleep(poll_s)
        raise TimeoutError(f"job {job_id} still {st} after {timeout}s")
