"""Job submission (reference: `dashboard/modules/job/job_manager.py:60` —
JobManager spawning a per-job supervisor actor that runs the entrypoint
as a subprocess, with status + log retrieval, SDK + CLI)."""

from ray_tpu.job.manager import (JobInfo, JobStatus, JobSubmissionClient)

__all__ = ["JobSubmissionClient", "JobStatus", "JobInfo"]
