"""Runtime environments (reference: `python/ray/runtime_env/runtime_env.py`
+ `_private/runtime_env/` plugins — pip/uv/conda/working_dir/py_modules/
container materialized by a per-node agent).

In this single-image runtime the meaningful fields are ``env_vars``
(applied around execution), ``working_dir``/``py_modules`` (paths put on
sys.path), and validation of the full reference schema. Package
materialization (pip/conda/container) requires per-process workers and
network; those fields validate and no-op with a warning (the environment
forbids installs — see repo guidelines).
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
import warnings
from typing import Any, Dict, List, Optional

_KNOWN_FIELDS = {
    "env_vars", "working_dir", "py_modules", "pip", "uv", "conda",
    "container", "image_uri", "excludes", "config",
}

_env_lock = threading.RLock()


class RuntimeEnv(dict):
    """Validated runtime-env dict (reference: RuntimeEnv class)."""

    def __init__(self, **kwargs):
        for key in kwargs:
            if key not in _KNOWN_FIELDS:
                raise ValueError(
                    f"unknown runtime_env field {key!r} "
                    f"(known: {sorted(_KNOWN_FIELDS)})")
        env_vars = kwargs.get("env_vars")
        if env_vars is not None:
            if not isinstance(env_vars, dict) or not all(
                    isinstance(k, str) and isinstance(v, str)
                    for k, v in env_vars.items()):
                raise TypeError("env_vars must be Dict[str, str]")
        super().__init__(**kwargs)


@contextlib.contextmanager
def apply_runtime_env(runtime_env: Optional[Dict[str, Any]]):
    """Apply env_vars/py_modules for the duration of a task execution.

    Process-global env mutation is serialized under a lock; the reference
    applies env at worker-process start (`worker_pool.h` runtime-env hash
    keying) — virtual in-process workers approximate it per-task.
    """
    if not runtime_env:
        yield
        return
    if any(runtime_env.get(k) for k in
           ("conda", "container", "image_uri")):
        warnings.warn(
            "runtime_env materialization for conda/container is a "
            "no-op in the single-image runtime (pip and uv ARE "
            "materialized — see _private/runtime_env_pip.py)",
            stacklevel=2)
    env_vars: Dict[str, str] = runtime_env.get("env_vars") or {}

    def _local(p: str) -> str:
        # pkg:// URIs (packaged working_dir/py_modules) materialize from
        # the content-addressed table / node cache
        if isinstance(p, str) and p.startswith("pkg://"):
            from ray_tpu._private.runtime_env_packaging import \
                resolve_local
            return resolve_local(p)
        return os.path.abspath(p)

    paths: List[str] = []
    wd = runtime_env.get("working_dir")
    if wd:
        paths.append(_local(wd))
    for mod in runtime_env.get("py_modules") or []:
        paths.append(_local(mod))
    pkgs = runtime_env.get("pip") or runtime_env.get("uv")
    if pkgs:
        # materialized package env = an import path (same interpreter;
        # the reference swaps worker interpreters instead — pip.py/
        # uv.py agents). uv specs are the same package list and
        # materialize through the same installer.
        from ray_tpu._private.runtime_env_pip import materialize_pip
        paths.append(materialize_pip(pkgs))

    with _env_lock:
        saved = {k: os.environ.get(k) for k in env_vars}
        os.environ.update(env_vars)
        added = [p for p in paths if p not in sys.path]
        for p in added:
            sys.path.insert(0, p)
    try:
        yield
    finally:
        with _env_lock:
            for k, old in saved.items():
                if old is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = old
            for p in added:
                try:
                    sys.path.remove(p)
                except ValueError:
                    pass
