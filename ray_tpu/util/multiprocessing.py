"""multiprocessing.Pool shim over tasks (reference:
`python/ray/util/multiprocessing/pool.py` — drop-in Pool running on the
cluster)."""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu


class AsyncResult:
    def __init__(self, refs: List, single: bool):
        self._refs = refs
        self._single = single

    def get(self, timeout: Optional[float] = None):
        vals = ray_tpu.get(self._refs, timeout=timeout)
        return vals[0] if self._single else vals

    def wait(self, timeout: Optional[float] = None) -> None:
        ray_tpu.wait(self._refs, num_returns=len(self._refs),
                     timeout=timeout)

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                                timeout=0)
        return len(ready) == len(self._refs)


class Pool:
    """Subset of multiprocessing.Pool: map/starmap/imap/apply (+_async)."""

    def __init__(self, processes: Optional[int] = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self._size = processes or int(
            ray_tpu.cluster_resources().get("CPU", 4))
        self._closed = False

    def _task(self, func: Callable):
        return ray_tpu.remote(func)

    def apply(self, func, args=(), kwds=None):
        return self.apply_async(func, args, kwds).get()

    def apply_async(self, func, args=(), kwds=None) -> AsyncResult:
        ref = self._task(func).remote(*args, **(kwds or {}))
        return AsyncResult([ref], single=True)

    def map(self, func, iterable: Iterable, chunksize: Optional[int] = None
            ) -> List[Any]:
        return self.map_async(func, iterable, chunksize).get()

    def map_async(self, func, iterable: Iterable,
                  chunksize: Optional[int] = None) -> AsyncResult:
        f = self._task(func)
        return AsyncResult([f.remote(x) for x in iterable], single=False)

    def starmap(self, func, iterable: Iterable) -> List[Any]:
        f = self._task(func)
        return ray_tpu.get([f.remote(*args) for args in iterable])

    def imap(self, func, iterable: Iterable, chunksize: int = 1):
        f = self._task(func)
        refs = [f.remote(x) for x in iterable]
        for ref in refs:
            yield ray_tpu.get(ref)

    def imap_unordered(self, func, iterable: Iterable, chunksize: int = 1):
        f = self._task(func)
        pending = [f.remote(x) for x in iterable]
        while pending:
            done, pending = ray_tpu.wait(pending, num_returns=1)
            yield ray_tpu.get(done[0])

    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        self._closed = True

    def join(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()
