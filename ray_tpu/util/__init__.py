"""Distributed-computing utilities (reference: python/ray/util)."""

from ray_tpu.util.placement_group import (
    PlacementGroup,
    placement_group,
    placement_group_table,
    remove_placement_group,
)

__all__ = [
    "PlacementGroup", "placement_group", "remove_placement_group",
    "placement_group_table", "ActorPool", "Queue",
]


def __getattr__(name):
    if name == "ActorPool":
        from ray_tpu.util.actor_pool import ActorPool
        return ActorPool
    if name == "Queue":
        from ray_tpu.util.queue import Queue
        return Queue
    raise AttributeError(name)
