"""joblib backend (reference: `python/ray/util/joblib/` —
`register_ray()` lets sklearn-style `Parallel(backend="ray")` fan out
over the cluster)."""

from __future__ import annotations

from joblib._parallel_backends import ThreadingBackend
from joblib.parallel import register_parallel_backend

import ray_tpu


class RayTpuBackend(ThreadingBackend):
    """Each joblib batch executes as a cluster task."""

    supports_timeout = True

    def configure(self, n_jobs=1, parallel=None, **kwargs):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self._fn = ray_tpu.remote(_run_batch)
        return super().configure(n_jobs=n_jobs, parallel=parallel,
                                 **kwargs)

    def apply_async(self, func, callback=None):
        ref = self._fn.remote(func)

        class _Future:
            def get(self, timeout=None):
                result = ray_tpu.get(ref, timeout=timeout)
                if callback:
                    callback(result)
                return result
        return _Future()

    def effective_n_jobs(self, n_jobs):
        if n_jobs == -1 or n_jobs is None:
            return max(1, int(ray_tpu.cluster_resources().get("CPU", 1)))
        return super().effective_n_jobs(n_jobs)


def _run_batch(batch):
    return batch()


def register_ray() -> None:
    register_parallel_backend("ray_tpu", RayTpuBackend)
