"""Actor pool utility (reference: python/ray/util/actor_pool.py)."""

from __future__ import annotations

from typing import Any, Callable, Iterable, List, Optional

import ray_tpu


class ActorPool:
    """Round-robins work over a fixed set of actors."""

    def __init__(self, actors: List[Any]):
        self._idle = list(actors)
        self._future_to_actor = {}
        self._index_to_future = {}
        self._next_task_index = 0
        self._next_return_index = 0

    def map(self, fn: Callable, values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def submit(self, fn: Callable, value: Any) -> None:
        if not self._idle:
            # Wait for any in-flight result to free an actor.
            refs = list(self._future_to_actor)
            ready, _ = ray_tpu.wait(refs, num_returns=1)
            self._return_actor_of(ready[0])
        actor = self._idle.pop()
        ref = fn(actor, value)
        self._future_to_actor[ref] = actor
        self._index_to_future[self._next_task_index] = ref
        self._next_task_index += 1

    def has_next(self) -> bool:
        return bool(self._index_to_future)

    def get_next(self, timeout: Optional[float] = None) -> Any:
        if not self.has_next():
            raise StopIteration("no pending results")
        # Wait before consuming the index so a timeout is retryable.
        ref = self._index_to_future[self._next_return_index]
        if timeout is not None:
            ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=timeout)
            if not ready:
                raise TimeoutError("next result not ready within timeout")
        del self._index_to_future[self._next_return_index]
        self._next_return_index += 1
        value = ray_tpu.get(ref)
        self._return_actor_of(ref)
        return value

    def get_next_unordered(self, timeout: Optional[float] = None) -> Any:
        if not self.has_next():
            raise StopIteration("no pending results")
        refs = list(self._index_to_future.values())
        ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("no result within timeout")
        ref = ready[0]
        for idx, r in list(self._index_to_future.items()):
            if r == ref:
                del self._index_to_future[idx]
                break
        value = ray_tpu.get(ref)
        self._return_actor_of(ref)
        return value

    def _return_actor_of(self, ref) -> None:
        actor = self._future_to_actor.pop(ref, None)
        if actor is not None:
            self._idle.append(actor)

    def push(self, actor: Any) -> None:
        self._idle.append(actor)

    def pop_idle(self) -> Optional[Any]:
        return self._idle.pop() if self._idle else None
