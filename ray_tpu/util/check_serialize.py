"""Serializability inspector (reference: `python/ray/util/check_serialize.py`
— walks closures/attributes to locate the leaf that fails to pickle)."""

from __future__ import annotations

import inspect
from typing import Any, List, Set, Tuple

import cloudpickle


class FailureTuple:
    def __init__(self, obj: Any, name: str, parent: Any):
        self.obj = obj
        self.name = name
        self.parent = parent

    def __repr__(self):
        return f"FailureTuple(obj={self.name!r}, parent={self.parent!r})"


def _check(obj: Any, name: str, parent: Any, failures: List[FailureTuple],
           seen: Set[int], depth: int) -> bool:
    try:
        cloudpickle.dumps(obj)
        return True
    except Exception:
        pass
    if id(obj) in seen or depth > 4:
        return False
    seen.add(id(obj))
    found_leaf = False
    # descend into closures
    if inspect.isfunction(obj) and obj.__closure__:
        for var, cell in zip(obj.__code__.co_freevars, obj.__closure__):
            try:
                inner = cell.cell_contents
            except ValueError:
                continue
            if not _check(inner, var, name, failures, seen, depth + 1):
                found_leaf = True
    # descend into attributes / dict values
    attrs = {}
    if hasattr(obj, "__dict__") and isinstance(obj.__dict__, dict):
        attrs = obj.__dict__
    elif isinstance(obj, dict):
        attrs = obj
    for key, val in list(attrs.items())[:64]:
        try:
            cloudpickle.dumps(val)
        except Exception:
            if not _check(val, str(key), name, failures, seen, depth + 1):
                found_leaf = True
    if not found_leaf:
        failures.append(FailureTuple(obj, name, parent))
    return False


def inspect_serializability(obj: Any, name: str = "obj"
                            ) -> Tuple[bool, List[FailureTuple]]:
    """Returns (is_serializable, failure_leaves)."""
    failures: List[FailureTuple] = []
    ok = _check(obj, name, None, failures, set(), 0)
    return ok, failures
