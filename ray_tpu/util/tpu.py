"""TPU pod helpers — the reference's import path (`ray.util.tpu` +
`util/accelerators/tpu.py:14-33`): pod identity, per-host worker index,
chip detection, and visibility control, re-exported from the
accelerator-management layer (`_private/accelerators.py`) plus the ICI
topology model (`parallel/topology.py`)."""

from ray_tpu._private.accelerators import (detect_tpu_chips,
                                           get_accelerator_type,
                                           get_pod_name, get_worker_id,
                                           set_visible_chips)
from ray_tpu.parallel.topology import TpuTopology

__all__ = ["detect_tpu_chips", "get_accelerator_type", "get_pod_name",
           "get_worker_id", "set_visible_chips", "TpuTopology"]
