"""Cluster profiling: stack sampler (py-spy role) + tracemalloc.

Reference: ``dashboard/modules/reporter/profile_manager.py:82`` shells
out to py-spy (CPU flamegraph) and memray (heap). Neither tool is in
this image, so both capabilities are in-process and stdlib-only:

- :func:`sample_cpu_profile` — a sampling profiler over
  ``sys._current_frames()``: every thread's stack is sampled on an
  interval for a duration and aggregated into collapsed-stack lines
  (the flamegraph.pl / speedscope input format), with per-thread totals.
  Unlike cProfile it sees ALL threads and adds no per-call overhead.
- :func:`memory_snapshot` — tracemalloc top allocations (started lazily
  on first use), the memray-lite view.

Cluster-wide layer (docs/observability.md "Profiling & contention"):

- :class:`ContinuousSampler` — an opt-in low-rate daemon thread
  (``profiling_hz`` knob, default off) aggregating every thread's stack
  into CUMULATIVE collapsed-stack counters. Cumulative + monotonic by
  design: pruning folds excess stacks into a ``<pruned>`` bucket, so a
  snapshot always supersedes every earlier one and the transport can
  use replace semantics (a dropped flush is healed by the next send —
  the same retry discipline as ``trace.flush``).
- :func:`ingest_profile` / :func:`node_profile` — the node-local store:
  workers piggyback their profile records on result frames (next to
  spans); the host ingests them here and the daemon heartbeat ships
  ``node_profile()`` to the head.
- :func:`burst_record` — on-demand high-rate burst in record form, the
  ``ray-tpu profile`` / ``profile_burst`` RPC payload.
- :func:`merged_collapsed` / :func:`speedscope_document` — render a set
  of per-process records as one collapsed-stack text or one speedscope
  JSON document with a lane per process (mirroring
  ``merged_chrome_trace``).
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import Counter
from typing import Any, Dict, List, Optional


def _collapse(frame, thread_name: str) -> str:
    stack: List[str] = []
    f = frame
    while f is not None:
        code = f.f_code
        stack.append(f"{code.co_filename.rsplit('/', 1)[-1]}:"
                     f"{code.co_name}")
        f = f.f_back
    stack.reverse()
    return thread_name + ";" + ";".join(stack)


def sample_cpu_profile(duration_s: float = 5.0,
                       interval_s: float = 0.005,
                       top: int = 60) -> Dict:
    """Sample every thread's stack for ``duration_s``; returns
    {"collapsed": [...], "top": [...], "samples": N}."""
    counts: Counter = Counter()
    names = {t.ident: t.name for t in threading.enumerate()}
    samples = 0
    deadline = time.monotonic() + duration_s
    me = threading.get_ident()
    while time.monotonic() < deadline:
        for ident, frame in sys._current_frames().items():
            if ident == me:
                continue
            counts[_collapse(frame,
                             names.get(ident, f"thread-{ident}"))] += 1
        samples += 1
        names = {t.ident: t.name for t in threading.enumerate()}
        time.sleep(interval_s)

    collapsed = [f"{stack} {n}"
                 for stack, n in counts.most_common()]
    # leaf-frame hot spots; pct is of ALL thread-samples (a stack is
    # recorded per thread per tick, so the denominator is the total
    # number of recorded stacks, not ticks)
    leaf: Counter = Counter()
    for stack, n in counts.items():
        leaf[stack.rsplit(";", 1)[-1]] += n
    total = max(sum(counts.values()), 1)
    return {
        "samples": samples,
        "thread_samples": total,
        "collapsed": collapsed[:1000],
        "top": [{"frame": fr, "samples": n,
                 "pct": round(100.0 * n / total, 1)}
                for fr, n in leaf.most_common(top)],
    }


_tracemalloc_started = False


def memory_snapshot(top: int = 40,
                    group_by: str = "lineno") -> Dict:
    """tracemalloc top allocation sites (starts tracing on first call —
    earlier allocations are invisible until then, like attaching
    memray)."""
    global _tracemalloc_started
    import tracemalloc

    if not tracemalloc.is_tracing():
        tracemalloc.start(16)
        _tracemalloc_started = True
        return {"started": True,
                "note": "tracemalloc just started; call again after the "
                        "workload runs to see allocations"}
    snap = tracemalloc.take_snapshot()
    stats = snap.statistics(group_by)
    total = sum(s.size for s in stats)
    return {
        "total_traced_bytes": total,
        "top": [{
            "site": str(s.traceback[0]) if s.traceback else "?",
            "size_bytes": s.size,
            "count": s.count,
        } for s in stats[:top]],
    }


def stop_memory_tracing() -> None:
    import tracemalloc

    if tracemalloc.is_tracing():
        tracemalloc.stop()


# ---------------------------------------------------------------------------
# continuous sampling (cluster-wide layer)
# ---------------------------------------------------------------------------

# Collapsed-stack cap per record. Pruning keeps the TOP stacks and folds
# the tail's weight into one synthetic "<pruned>" stack so totals stay
# monotonic (replace-semantics transport depends on it).
MAX_STACKS = 2000
PRUNED_STACK = "<pruned>"


def _sample_once(counts: Counter, skip_ident: int) -> int:
    """One tick over ``sys._current_frames()`` into ``counts``; returns
    the number of thread stacks recorded."""
    names = {t.ident: t.name for t in threading.enumerate()}
    n = 0
    for ident, frame in sys._current_frames().items():
        if ident == skip_ident:
            continue
        counts[_collapse(frame, names.get(ident, f"thread-{ident}"))] += 1
        n += 1
    return n


def _prune(counts: Counter) -> None:
    if len(counts) <= MAX_STACKS:
        return
    keep = counts.most_common(MAX_STACKS - 1)
    folded = sum(counts.values()) - sum(n for _, n in keep)
    counts.clear()
    counts.update(dict(keep))
    counts[PRUNED_STACK] += folded


class ContinuousSampler:
    """Low-rate background stack sampler with cumulative counters.

    ``snapshot()`` is safe from any thread and always returns a record
    that supersedes every earlier one (counts only grow; see
    :data:`PRUNED_STACK`)."""

    def __init__(self, proc: str, hz: float):
        self.proc = proc
        self.hz = float(hz)
        self._lock = threading.Lock()
        #: guarded by self._lock
        self._counts: Counter = Counter()
        #: guarded by self._lock
        self._samples = 0
        self._started = time.time()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ContinuousSampler":
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"profiler-{self.proc}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=1.0)

    def _run(self) -> None:
        me = threading.get_ident()
        interval = 1.0 / max(self.hz, 0.1)
        local: Counter = Counter()
        while not self._stop.wait(interval):
            local.clear()
            n = _sample_once(local, me)
            with self._lock:
                self._samples += 1
                self._counts.update(local)
                if n:
                    _prune(self._counts)

    def snapshot(self) -> Optional[Dict[str, Any]]:
        """Cumulative record, or None before the first non-empty tick."""
        with self._lock:
            if not self._counts:
                return None
            counts = dict(self._counts)
            samples = self._samples
        return {"proc": self.proc, "pid": os.getpid(),
                "mode": "continuous", "hz": self.hz,
                "samples": samples, "since": self._started,
                "counts": counts}


_SAMPLER_LOCK = threading.Lock()
#: guarded by _SAMPLER_LOCK (the process-wide continuous sampler slot)
_SAMPLER: Optional[ContinuousSampler] = None


def start_process_sampler(proc: str,
                          hz: Optional[float] = None
                          ) -> Optional[ContinuousSampler]:
    """Start (or return) this process's continuous sampler. ``hz=None``
    reads the ``profiling_hz`` config knob; <= 0 leaves sampling off."""
    global _SAMPLER
    if hz is None:
        try:
            from ray_tpu._private.config import cfg
            hz = float(cfg().profiling_hz)
        except Exception:
            hz = 0.0
    if hz <= 0:
        return None
    with _SAMPLER_LOCK:
        if _SAMPLER is not None:
            return _SAMPLER
        _SAMPLER = ContinuousSampler(proc, hz).start()
        return _SAMPLER


def maybe_start_from_config(proc: str) -> Optional[ContinuousSampler]:
    """Config-gated start; never raises (runtime boot path)."""
    try:
        return start_process_sampler(proc, hz=None)
    except Exception:
        return None


def stop_process_sampler() -> None:
    global _SAMPLER
    with _SAMPLER_LOCK:
        s, _SAMPLER = _SAMPLER, None
    if s is not None:
        s.stop()


def process_profile() -> Optional[Dict[str, Any]]:
    """This process's cumulative continuous-sampler record (or None)."""
    with _SAMPLER_LOCK:
        s = _SAMPLER
    return s.snapshot() if s is not None else None


# Records pushed from child processes (workers piggyback them on result
# frames the way spans ride; the host _read_loop ingests here). Keyed by
# proc name; a later record replaces the earlier one (cumulative).
_REMOTE_LOCK = threading.Lock()
#: guarded by _REMOTE_LOCK
_REMOTE: Dict[str, Dict[str, Any]] = {}


def ingest_profile(record: Any) -> None:
    """Store a child process's profile record (tolerant: bad payloads
    are dropped, never raised — this sits on the result hot path)."""
    if not isinstance(record, dict) or not record.get("proc"):
        return
    if not isinstance(record.get("counts"), dict):
        return
    with _REMOTE_LOCK:
        _REMOTE[str(record["proc"])] = record


def remote_profiles() -> List[Dict[str, Any]]:
    with _REMOTE_LOCK:
        return list(_REMOTE.values())


def node_profile() -> Optional[Dict[str, Any]]:
    """Everything this process knows: its own continuous record plus
    ingested child records — the daemon's heartbeat payload. None when
    there is nothing to ship (keeps heartbeats lean with profiling
    off)."""
    procs: List[Dict[str, Any]] = []
    own = process_profile()
    if own is not None:
        procs.append(own)
    procs.extend(remote_profiles())
    if not procs:
        return None
    return {"procs": procs, "ts": time.time()}


def burst_record(proc: str, duration_s: float = 2.0,
                 hz: float = 100.0) -> Dict[str, Any]:
    """On-demand burst in record form (same shape as a continuous
    snapshot) — the ``profile_burst`` RPC / ``ray-tpu profile``
    payload. Runs inline in the calling thread."""
    counts: Counter = Counter()
    me = threading.get_ident()
    interval = 1.0 / max(hz, 1.0)
    samples = 0
    deadline = time.monotonic() + max(duration_s, interval)
    while time.monotonic() < deadline:
        _sample_once(counts, me)
        samples += 1
        _prune(counts)
        time.sleep(interval)
    return {"proc": proc, "pid": os.getpid(), "mode": "burst",
            "hz": hz, "samples": samples, "wall_s": duration_s,
            "counts": dict(counts)}


# ---------------------------------------------------------------------------
# rendering: merged collapsed text + speedscope JSON (lane per process)
# ---------------------------------------------------------------------------

def merged_collapsed(records: List[Dict[str, Any]]) -> str:
    """flamegraph.pl input over many process records: each line is
    ``proc;thread;frame;... count``, heaviest first."""
    lines: List[str] = []
    for rec in records:
        proc = rec.get("proc", "?")
        counts = rec.get("counts") or {}
        for stack, n in sorted(counts.items(),
                               key=lambda kv: (-kv[1], kv[0])):
            lines.append(f"{proc};{stack} {n}")
    return "\n".join(lines)


def speedscope_document(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """One speedscope file, one "sampled" profile lane per process
    record (mirroring merged_chrome_trace's one-lane-per-process
    layout). Weights are sample counts (unit "none")."""
    frames: List[Dict[str, str]] = []
    index: Dict[str, int] = {}

    def frame_idx(name: str) -> int:
        i = index.get(name)
        if i is None:
            i = index[name] = len(frames)
            frames.append({"name": name})
        return i

    profiles: List[Dict[str, Any]] = []
    for rec in records:
        samples: List[List[int]] = []
        weights: List[int] = []
        counts = rec.get("counts") or {}
        for stack, n in sorted(counts.items(),
                               key=lambda kv: (-kv[1], kv[0])):
            samples.append([frame_idx(tok)
                            for tok in stack.split(";") if tok])
            weights.append(int(n))
        total = sum(weights)
        profiles.append({
            "type": "sampled",
            "name": f"{rec.get('proc', '?')} "
                    f"({rec.get('mode', '?')}, pid {rec.get('pid', 0)})",
            "unit": "none",
            "startValue": 0,
            "endValue": total,
            "samples": samples,
            "weights": weights,
        })
    return {
        "$schema": "https://www.speedscope.app/file-format-schema.json",
        "shared": {"frames": frames},
        "profiles": profiles,
        "name": "ray_tpu cluster profile",
        "exporter": "ray_tpu.util.profiling",
    }
