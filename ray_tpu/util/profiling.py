"""On-demand profiling: stack sampler (py-spy role) + tracemalloc.

Reference: ``dashboard/modules/reporter/profile_manager.py:82`` shells
out to py-spy (CPU flamegraph) and memray (heap). Neither tool is in
this image, so both capabilities are in-process and stdlib-only:

- :func:`sample_cpu_profile` — a sampling profiler over
  ``sys._current_frames()``: every thread's stack is sampled on an
  interval for a duration and aggregated into collapsed-stack lines
  (the flamegraph.pl / speedscope input format), with per-thread totals.
  Unlike cProfile it sees ALL threads and adds no per-call overhead.
- :func:`memory_snapshot` — tracemalloc top allocations (started lazily
  on first use), the memray-lite view.
"""

from __future__ import annotations

import sys
import threading
import time
from collections import Counter
from typing import Dict, List, Optional


def _collapse(frame, thread_name: str) -> str:
    stack: List[str] = []
    f = frame
    while f is not None:
        code = f.f_code
        stack.append(f"{code.co_filename.rsplit('/', 1)[-1]}:"
                     f"{code.co_name}")
        f = f.f_back
    stack.reverse()
    return thread_name + ";" + ";".join(stack)


def sample_cpu_profile(duration_s: float = 5.0,
                       interval_s: float = 0.005,
                       top: int = 60) -> Dict:
    """Sample every thread's stack for ``duration_s``; returns
    {"collapsed": [...], "top": [...], "samples": N}."""
    counts: Counter = Counter()
    names = {t.ident: t.name for t in threading.enumerate()}
    samples = 0
    deadline = time.monotonic() + duration_s
    me = threading.get_ident()
    while time.monotonic() < deadline:
        for ident, frame in sys._current_frames().items():
            if ident == me:
                continue
            counts[_collapse(frame,
                             names.get(ident, f"thread-{ident}"))] += 1
        samples += 1
        names = {t.ident: t.name for t in threading.enumerate()}
        time.sleep(interval_s)

    collapsed = [f"{stack} {n}"
                 for stack, n in counts.most_common()]
    # leaf-frame hot spots; pct is of ALL thread-samples (a stack is
    # recorded per thread per tick, so the denominator is the total
    # number of recorded stacks, not ticks)
    leaf: Counter = Counter()
    for stack, n in counts.items():
        leaf[stack.rsplit(";", 1)[-1]] += n
    total = max(sum(counts.values()), 1)
    return {
        "samples": samples,
        "thread_samples": total,
        "collapsed": collapsed[:1000],
        "top": [{"frame": fr, "samples": n,
                 "pct": round(100.0 * n / total, 1)}
                for fr, n in leaf.most_common(top)],
    }


_tracemalloc_started = False


def memory_snapshot(top: int = 40,
                    group_by: str = "lineno") -> Dict:
    """tracemalloc top allocation sites (starts tracing on first call —
    earlier allocations are invisible until then, like attaching
    memray)."""
    global _tracemalloc_started
    import tracemalloc

    if not tracemalloc.is_tracing():
        tracemalloc.start(16)
        _tracemalloc_started = True
        return {"started": True,
                "note": "tracemalloc just started; call again after the "
                        "workload runs to see allocations"}
    snap = tracemalloc.take_snapshot()
    stats = snap.statistics(group_by)
    total = sum(s.size for s in stats)
    return {
        "total_traced_bytes": total,
        "top": [{
            "site": str(s.traceback[0]) if s.traceback else "?",
            "size_bytes": s.size,
            "count": s.count,
        } for s in stats[:top]],
    }


def stop_memory_tracing() -> None:
    import tracemalloc

    if tracemalloc.is_tracing():
        tracemalloc.stop()
