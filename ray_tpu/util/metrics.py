"""User + system metrics (reference: `python/ray/util/metrics.py`
Counter/Gauge/Histogram over the C++ OpenCensus registry,
`_private/metrics_agent.py` Prometheus exposition)."""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

_REGISTRY: Dict[str, "Metric"] = {}
_REG_LOCK = threading.Lock()


def _labels_key(labels: Optional[Dict[str, str]]) -> Tuple:
    return tuple(sorted((labels or {}).items()))


class Metric:
    kind = "untyped"

    def __new__(cls, name: str, *args, **kwargs):
        # get-or-create by name: re-declaring a metric (the natural
        # pattern inside tasks — Counter("x").inc() per call) must
        # return the LIVE instance, not a fresh zeroed one. A replace
        # here silently reset values, so a worker reusing a process
        # reported only its first flush's deltas.
        with _REG_LOCK:
            existing = _REGISTRY.get(name)
            if existing is not None and type(existing) is cls:
                return existing
        return super().__new__(cls)

    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = ()):
        if getattr(self, "_initialized", False):
            return                      # live instance from __new__
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._values: Dict[Tuple, float] = {}
        self._lock = threading.Lock()
        self._initialized = True
        with _REG_LOCK:
            _REGISTRY[name] = self

    def _reattach(self) -> None:
        # clear_registry() (test isolation, process reuse) empties the
        # name->metric table, but module-level metric HOLDERS (tenancy
        # gauges, wire counters) keep writing to the orphaned instance —
        # which then never appears in prometheus_text() again. Re-attach
        # on write so a live metric always reaches the exposition; a
        # cleared metric nobody writes again stays gone. The unlocked
        # membership probe is safe: dict get is atomic, and a lost race
        # just means one extra locked setdefault.
        if _REGISTRY.get(self.name) is not self:
            with _REG_LOCK:
                _REGISTRY.setdefault(self.name, self)

    def _set(self, key: Tuple, value: float) -> None:
        self._reattach()
        with self._lock:
            self._values[key] = value

    def _add(self, key: Tuple, delta: float) -> None:
        self._reattach()
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + delta

    def samples(self) -> List[Tuple[Tuple, float]]:
        with self._lock:
            return list(self._values.items())


class Counter(Metric):
    kind = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        self._add(_labels_key(tags), value)


class Gauge(Metric):
    kind = "gauge"

    def set(self, value: float,
            tags: Optional[Dict[str, str]] = None) -> None:
        self._set(_labels_key(tags), value)

    def remove(self, tags: Optional[Dict[str, str]] = None) -> None:
        """Drop one label series (e.g. a downscaled replica slot) so
        the exposition stops reporting its last value forever."""
        with self._lock:
            self._values.pop(_labels_key(tags), None)


class Histogram(Metric):
    kind = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Sequence[float] = (0.01, 0.1, 1, 10, 100),
                 tag_keys: Sequence[str] = ()):
        if getattr(self, "_initialized", False):
            return                      # live instance from __new__
        super().__init__(name, description, tag_keys)
        self.boundaries = tuple(boundaries)
        self._counts: Dict[Tuple, List[int]] = {}
        self._sums: Dict[Tuple, float] = {}
        self._totals: Dict[Tuple, int] = {}

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        self._reattach()
        key = _labels_key(tags)
        with self._lock:
            counts = self._counts.setdefault(
                key, [0] * (len(self.boundaries) + 1))
            i = 0
            while i < len(self.boundaries) and value > self.boundaries[i]:
                i += 1
            counts[i] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1


# ---------------------------------------------------------------------------
# cross-process flow: pool workers drain deltas after each task; the
# driver merges them so user metrics from ANY process surface on the
# one Prometheus endpoint (reference: workers -> agent -> exporter)
# ---------------------------------------------------------------------------

_FLUSH_STATE: Dict[str, Dict] = {}
# one drainer at a time per process (concurrent task threads would read
# the same snapshot and double-count), and one merger at a time on the
# receiving side (check-then-create on first sight of a metric)
_FLUSH_LOCK = threading.Lock()


def drain_deltas() -> List[Dict]:
    """Changes since the last drain, as plain picklable entries.
    Counters/histograms ship DELTAS (mergeable across workers); gauges
    ship absolute values (last writer wins)."""
    with _FLUSH_LOCK:
        return _drain_deltas_locked()


def _drain_deltas_locked() -> List[Dict]:
    out: List[Dict] = []
    for name, m in registry().items():
        if m.kind == "histogram":
            prev = _FLUSH_STATE.get(name, {})
            hist = {}
            with m._lock:
                for key, counts in m._counts.items():
                    p = prev.get(key, ([0] * len(counts), 0.0, 0))
                    dc = [c - pc for c, pc in zip(counts, p[0])]
                    ds = m._sums.get(key, 0.0) - p[1]
                    dt = m._totals.get(key, 0) - p[2]
                    if dt:
                        hist[key] = (dc, ds, dt)
                _FLUSH_STATE[name] = {
                    key: (list(c), m._sums.get(key, 0.0),
                          m._totals.get(key, 0))
                    for key, c in m._counts.items()}
            if hist:
                out.append({"name": name, "kind": "histogram",
                            "description": m.description,
                            "tag_keys": m.tag_keys,
                            "boundaries": m.boundaries,
                            "hist": hist})
            continue
        prev = _FLUSH_STATE.get(name, {})
        cur = dict(m.samples())
        if m.kind == "counter":
            samples = [(k, v - prev.get(k, 0.0)) for k, v in cur.items()
                       if v != prev.get(k, 0.0)]
        else:
            samples = [(k, v) for k, v in cur.items()
                       if v != prev.get(k)]
        _FLUSH_STATE[name] = cur
        if samples:
            out.append({"name": name, "kind": m.kind,
                        "description": m.description,
                        "tag_keys": m.tag_keys, "samples": samples})
    return out


def merge_deltas(entries: List[Dict]) -> None:
    """Apply another process's drained deltas to this registry."""
    with _FLUSH_LOCK:                 # serialize check-then-create
        _merge_deltas_locked(entries)


def _merge_deltas_locked(entries: List[Dict]) -> None:
    for e in entries:
        with _REG_LOCK:
            m = _REGISTRY.get(e["name"])
        if m is None:
            if e["kind"] == "counter":
                m = Counter(e["name"], e["description"],
                            tag_keys=e.get("tag_keys", ()))
            elif e["kind"] == "gauge":
                m = Gauge(e["name"], e["description"],
                          tag_keys=e.get("tag_keys", ()))
            elif e["kind"] == "histogram":
                m = Histogram(e["name"], e["description"],
                              boundaries=e.get("boundaries",
                                               (0.01, 0.1, 1, 10, 100)),
                              tag_keys=e.get("tag_keys", ()))
            else:
                continue
        if e["kind"] == "histogram":
            if tuple(e.get("boundaries", ())) != tuple(m.boundaries):
                import warnings
                warnings.warn(
                    f"histogram {e['name']!r}: incoming boundaries "
                    f"{e.get('boundaries')} != registered "
                    f"{m.boundaries}; dropping this batch (a truncated "
                    f"merge would corrupt the exposition)",
                    stacklevel=2)
                continue
            with m._lock:
                for key, (dc, ds, dt) in e["hist"].items():
                    counts = m._counts.setdefault(
                        key, [0] * (len(m.boundaries) + 1))
                    for i, d in enumerate(dc[:len(counts)]):
                        counts[i] += d
                    m._sums[key] = m._sums.get(key, 0.0) + ds
                    m._totals[key] = m._totals.get(key, 0) + dt
        elif e["kind"] == "counter":
            for key, v in e["samples"]:
                m._add(key, v)
        else:
            for key, v in e["samples"]:
                m._set(key, v)


def registry() -> Dict[str, Metric]:
    with _REG_LOCK:
        return dict(_REGISTRY)


def clear_registry() -> None:
    with _FLUSH_LOCK:
        with _REG_LOCK:
            _REGISTRY.clear()
        # a metric re-created with the same name must not drain against
        # stale baselines (negative counter deltas break monotonicity)
        _FLUSH_STATE.clear()


def _esc_label(value: Any) -> str:
    """Escape a label VALUE per the Prometheus exposition spec: backslash,
    double-quote, and newline must be escaped or the scrape corrupts
    (e.g. a task name containing ``"`` used to break parsing)."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _esc_help(text: str) -> str:
    """HELP text escaping per the spec: backslash and newline only."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_labels(key: Tuple) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{_esc_label(v)}"' for k, v in key)
    return "{" + inner + "}"


# ---------------------------------------------------------------------------
# queue-dwell gauges (observability for the control-plane hot loops:
# node dispatch, daemon reply pump, rpc server lane). Like the rpc wire
# counters, updates are PLAIN dict stores — single writer per queue
# name, last-value-wins gauge semantics, so a rare lost store under a
# race is acceptable and the hot path pays no lock.
# ---------------------------------------------------------------------------

_QUEUE_DWELL: Dict[str, float] = {}


def note_queue_dwell(queue: str, seconds: float) -> None:
    """Record how long the most recent item sat queued before service
    (``ray_tpu_queue_dwell_seconds{queue}``)."""
    _QUEUE_DWELL[queue] = seconds


def queue_dwell_entries() -> List[Dict]:
    """Dwell gauges in the export_snapshot wire-entry format."""
    if not _QUEUE_DWELL:
        return []
    return [{
        "name": "ray_tpu_queue_dwell_seconds", "kind": "gauge",
        "description": "seconds the most recently serviced item waited "
                       "in a control-plane queue",
        "samples": [[[["queue", q]], v]
                    for q, v in sorted(_QUEUE_DWELL.items())],
    }]


# ---------------------------------------------------------------------------
# cluster federation (reference: per-process OpenCensus registries merged
# into ONE Prometheus view by the metrics agent). Each process exports a
# wire-plain snapshot of its registry; daemons ship theirs to the head on
# heartbeats; the driver's dashboard renders local + federated snapshots
# with a node_id label per source.
# ---------------------------------------------------------------------------

def export_snapshot() -> List[Dict]:
    """Absolute (idempotent) snapshot of every registered metric as
    msgpack-plain entries — keys serialized as [[k, v], ...] pair lists.
    Re-sending a snapshot replaces the previous one at the receiver, so
    nothing double-counts (unlike deltas)."""
    out: List[Dict] = []
    for name, m in registry().items():
        if m.kind == "histogram":
            with m._lock:
                hist = [[[list(p) for p in key], list(counts),
                         m._sums.get(key, 0.0), m._totals.get(key, 0)]
                        for key, counts in m._counts.items()]
            if hist:
                out.append({"name": name, "kind": "histogram",
                            "description": m.description,
                            "boundaries": list(m.boundaries),
                            "hist": hist})
            continue
        samples = [[[list(p) for p in key], v] for key, v in m.samples()]
        if samples:
            out.append({"name": name, "kind": m.kind,
                        "description": m.description,
                        "samples": samples})
    try:    # wire/RPC counters live outside the registry (hot path)
        from ray_tpu._private import rpc as _rpc
        out.extend(_rpc.wire_metric_entries())
    except Exception:
        pass
    try:    # lock wait/hold meters (lock_sanitizer's metering mode)
        from ray_tpu._private import lock_sanitizer as _ls
        out.extend(_ls.lock_metric_entries())
    except Exception:
        pass
    out.extend(queue_dwell_entries())
    return out


def _inject(key, extra: Dict[str, str]) -> Tuple:
    """Label key (pair list or tuple) + per-source labels (a source's own
    label of the same name wins)."""
    pairs = {str(k): v for k, v in key}
    for k, v in (extra or {}).items():
        pairs.setdefault(k, v)
    return tuple(sorted(pairs.items()))


def render_prometheus(parts: List[Tuple[Dict[str, str], List[Dict]]]
                      ) -> str:
    """Render one exposition from many process snapshots: one HELP/TYPE
    block per metric name, every sample labeled with its source's extra
    labels (``node_id`` for federated daemons)."""
    merged: Dict[str, Dict[str, Any]] = {}
    for extra, entries in parts:
        for e in entries or []:
            slot = merged.setdefault(e["name"], {
                "kind": e["kind"], "description": e.get("description", ""),
                "boundaries": tuple(e.get("boundaries", ())),
                "scalars": [], "hists": []})
            if e["kind"] != slot["kind"]:
                continue        # conflicting registration: first wins
            if e["kind"] == "histogram":
                if tuple(e.get("boundaries", ())) != slot["boundaries"]:
                    continue    # a truncated merge would corrupt buckets
                for key, counts, hsum, total in e.get("hist", []):
                    slot["hists"].append(
                        (_inject(key, extra), counts, hsum, total))
            else:
                for key, value in e.get("samples", []):
                    slot["scalars"].append((_inject(key, extra), value))
    lines: List[str] = []
    for name in sorted(merged):
        slot = merged[name]
        lines.append(f"# HELP {name} {_esc_help(slot['description'])}")
        lines.append(f"# TYPE {name} {slot['kind']}")
        if slot["kind"] == "histogram":
            for key, counts, hsum, total in slot["hists"]:
                cum = 0
                for bound, c in zip(slot["boundaries"], counts):
                    cum += c
                    lk = _inject(key, {"le": str(bound)})
                    lines.append(f"{name}_bucket{_fmt_labels(lk)} {cum}")
                lk = _inject(key, {"le": "+Inf"})
                lines.append(f"{name}_bucket{_fmt_labels(lk)} {total}")
                lines.append(f"{name}_sum{_fmt_labels(key)} {hsum}")
                lines.append(f"{name}_count{_fmt_labels(key)} {total}")
        else:
            for key, value in slot["scalars"]:
                lines.append(f"{name}{_fmt_labels(key)} {value}")
    return "\n".join(lines)


def _system_stats_lines() -> List[str]:
    lines: List[str] = []
    try:
        from ray_tpu._private import worker as _worker
        rt = _worker.global_runtime()
        if rt is not None:
            for k, v in rt.stats.items():
                lines.append(f"# TYPE ray_tpu_{k} counter")
                lines.append(f"ray_tpu_{k} {v}")
            lines.append("# TYPE ray_tpu_nodes_alive gauge")
            lines.append(
                f"ray_tpu_nodes_alive "
                f"{sum(1 for n in rt.nodes() if n.alive)}")
    except Exception:
        pass
    return lines


def _federated_parts() -> List[Tuple[Dict[str, str], List[Dict]]]:
    """Per-node metric snapshots the daemons shipped to the head with
    their heartbeats (empty outside the daemon topology)."""
    parts: List[Tuple[Dict[str, str], List[Dict]]] = []
    try:
        from ray_tpu._private import worker as _worker
        rt = _worker.global_runtime()
        backend = getattr(rt, "cluster_backend", None)
        head = getattr(backend, "head", None)
        if head is not None:
            for node_id, snap in head.metrics_get().items():
                parts.append(({"node_id": node_id}, snap))
    except Exception:
        pass
    return parts


def prometheus_text() -> str:
    """Prometheus exposition for THIS process's registry, plus the
    runtime's system stats as gauges."""
    lines = [render_prometheus([({}, export_snapshot())])]
    lines.extend(_system_stats_lines())
    return "\n".join(line for line in lines if line) + "\n"


def cluster_prometheus_text() -> str:
    """CLUSTER-WIDE exposition: this process's registry merged with every
    daemon's federated snapshot (``node_id``-labeled). Served by the
    dashboard's ``/metrics``; identical to :func:`prometheus_text` in the
    in-process topology."""
    parts = [({}, export_snapshot())] + _federated_parts()
    lines = [render_prometheus(parts)]
    lines.extend(_system_stats_lines())
    return "\n".join(line for line in lines if line) + "\n"


def cluster_metrics_json() -> Dict[str, Any]:
    """Structured (JSON) view of the cluster-wide metric samples — the
    dashboard's ``/api/metrics``."""
    rows: List[Dict[str, Any]] = []
    for extra, entries in [({}, export_snapshot())] + _federated_parts():
        for e in entries or []:
            if e["kind"] == "histogram":
                for key, counts, hsum, total in e.get("hist", []):
                    rows.append({
                        "name": e["name"], "kind": "histogram",
                        "labels": dict(_inject(key, extra)),
                        "sum": hsum, "count": total,
                        # one label per count INCLUDING the overflow
                        # bucket (counts has len(boundaries)+1 cells)
                        "buckets": dict(zip(
                            [str(b) for b in e.get("boundaries", ())]
                            + ["+Inf"],
                            counts))})
            else:
                for key, value in e.get("samples", []):
                    rows.append({"name": e["name"], "kind": e["kind"],
                                 "labels": dict(_inject(key, extra)),
                                 "value": value})
    return {"metrics": rows}
