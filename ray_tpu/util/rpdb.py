"""Distributed debugger: socket-backed pdb sessions in remote workers.

Reference capability: `python/ray/util/rpdb.py:282` (RemotePdb +
``ray debug``). A task anywhere in the cluster calls
``ray_tpu.util.rpdb.set_trace()`` (or crashes with post-mortem enabled
via ``RAY_TPU_POST_MORTEM=1``): the worker opens a TCP-backed pdb,
ADVERTISES (host, port, task context) in the cluster KV, and blocks
until a client attaches. ``ray-tpu debug`` (scripts/cli.py) lists the
active sessions and bridges the operator's terminal to one; programmatic
attachment uses :func:`connect` below (what the CLI and tests use).

Design notes: the pdb reads/writes a socket makefile, so the worker
needs no tty; sessions self-deregister when the debugger detaches
(continue/quit or client disconnect). The KV namespace is
``rtpu:debug:*`` — the same cluster KV every node can reach.
"""

from __future__ import annotations

import contextlib
import json
import os
import pdb
import socket
import sys
import threading
import time
from typing import Any, Dict, List, Optional

_NS = "rtpu:debug:"


def _kv():
    """Best-effort cluster KV handle (head in daemons mode, gcs local)."""
    from ray_tpu._private import worker

    rt = worker.global_runtime()
    if rt is None:
        return None
    backend = getattr(rt, "cluster_backend", None)
    head = getattr(backend, "head", None)
    if head is not None:
        return head
    return getattr(rt, "gcs", None)


class _SessionRegistry:
    """Worker-side helper: advertise/retract one debug session."""

    def __init__(self, meta: Dict[str, Any]):
        self.key = f"{_NS}{meta['host']}:{meta['port']}".encode()
        self.meta = meta

    def register(self) -> None:
        kv = _kv()
        if kv is not None:
            try:
                kv.kv_put(self.key, json.dumps(self.meta).encode())
            except Exception:
                pass

    def retract(self) -> None:
        kv = _kv()
        if kv is not None:
            try:
                kv.kv_del(self.key)
            except Exception:
                pass


def sessions_from_kv(kv) -> List[Dict[str, Any]]:
    """Advertised sessions from any KV handle (head client or gcs)."""
    out = []
    try:
        for key in kv.kv_keys(_NS.encode()):
            blob = kv.kv_get(key)
            if blob:
                out.append(json.loads(blob))
    except Exception:
        pass
    return sorted(out, key=lambda m: m.get("started_at", 0))


def active_sessions() -> List[Dict[str, Any]]:
    """All advertised debugger sessions (for ``ray-tpu debug``)."""
    kv = _kv()
    if kv is None:
        return []
    return sessions_from_kv(kv)


def _bind_and_advertise() -> tuple:
    """(bind_host, advertise_host). SECURITY: a pdb session is arbitrary
    code execution, so the DEFAULT binds loopback only (matching the
    reference rpdb). Cross-node attachment is an explicit opt-in —
    RAY_TPU_DEBUGGER_EXTERNAL=1 — which binds all interfaces and
    advertises a routable address."""
    if os.environ.get("RAY_TPU_DEBUGGER_EXTERNAL") == "1":
        advertise = "127.0.0.1"
        try:
            host = socket.gethostbyname(socket.gethostname())
            if host and not host.startswith("127."):
                advertise = host
        except OSError:
            pass
        return "0.0.0.0", advertise
    return "127.0.0.1", "127.0.0.1"


class _RemotePdb(pdb.Pdb):
    """pdb over one accepted TCP connection (no tty needed). Cleanup
    (registry retract + socket close) hangs off the continue/quit
    commands because ``set_trace`` must be the session's LAST statement
    — anything after it would be the first thing the tracer stops in."""

    def __init__(self, conn: socket.socket):
        self._conn = conn
        self._io = conn.makefile("rw", buffering=1)
        super().__init__(stdin=self._io, stdout=self._io)
        self.use_rawinput = False
        self.prompt = "(rpdb) "
        self._registry: Optional[_SessionRegistry] = None

    def close(self) -> None:
        if self._registry is not None:
            self._registry.retract()
            self._registry = None
        try:
            self._io.close()
        except Exception:
            pass
        try:
            self._conn.close()
        except Exception:
            pass

    def do_continue(self, arg):
        out = super().do_continue(arg)
        self.close()
        return out

    do_c = do_cont = do_continue

    def do_quit(self, arg):
        try:
            return super().do_quit(arg)
        finally:
            self.close()

    do_q = do_exit = do_quit

    def do_EOF(self, arg):
        # client hung up (Ctrl-D / dropped connection): detach cleanly
        # like quit, never leave the session advertised
        try:
            return super().do_EOF(arg)
        finally:
            self.close()


def _open_session(banner: str) -> Optional[_RemotePdb]:
    """Listen, advertise, block for one client; None if disabled."""
    if os.environ.get("RAY_TPU_DEBUGGER_DISABLED") == "1":
        return None
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    bind_host, host = _bind_and_advertise()
    # SECURITY: an externally-reachable pdb port is arbitrary code
    # execution, so the opt-in bind requires a shared token before the
    # session starts. The token rides the cluster KV (cluster-internal)
    # so `ray-tpu debug` sends it automatically; a bare network peer
    # that can reach the port cannot produce it.
    token = None
    if bind_host != "127.0.0.1":
        token = os.environ.get("RAY_TPU_DEBUGGER_TOKEN")
        if not token:
            import secrets
            token = secrets.token_hex(16)
    srv.bind((bind_host, 0))
    srv.listen(1)
    _, port = srv.getsockname()
    from ray_tpu._private import runtime_context
    try:
        ctx = runtime_context.get_runtime_context()
        task_id = getattr(ctx, "task_id", None)
        task_id = task_id.hex() if task_id is not None else None
    except Exception:
        task_id = None
    meta = {
        "host": host, "port": port, "pid": os.getpid(),
        "task_id": task_id, "banner": banner,
        "started_at": time.time(),
    }
    if token is not None:
        meta["token"] = token
    reg = _SessionRegistry(meta)
    reg.register()
    # pool workers have no runtime handle for the KV: the stderr line
    # still reaches the operator via worker-log forwarding
    print(f"[rpdb] {banner}; attach with: ray-tpu debug {host}:{port}",
          file=sys.stderr, flush=True)
    timeout = float(os.environ.get("RAY_TPU_DEBUGGER_TIMEOUT_S", "600"))
    deadline = time.monotonic() + timeout
    conn = None
    try:
        # keep accepting until a client authenticates: one bad/probing
        # connection (port scanner, stale token) must NOT tear the
        # session down — that would be a trivial remote DoS of the
        # breakpoint and silently skip it
        while time.monotonic() < deadline:
            srv.settimeout(max(0.1, deadline - time.monotonic()))
            try:
                cand, _ = srv.accept()
            except socket.timeout:
                break
            if token is None or _check_token(cand, token, timeout):
                conn = cand
                break
            try:
                cand.close()
            except Exception:
                pass
    finally:
        try:
            srv.close()
        except Exception:
            pass
    if conn is None:
        reg.retract()
        return None
    dbg = _RemotePdb(conn)
    dbg._registry = reg
    dbg._io.write(banner + "\n")
    return dbg


def _check_token(conn: socket.socket, token: str,
                 timeout: float) -> bool:
    """First client line must equal the session token (constant-time
    compare). Wrong or missing token: drop the connection without
    starting pdb."""
    import hmac
    try:
        conn.settimeout(min(timeout, 30.0))
        buf = b""
        while b"\n" not in buf and len(buf) < 256:
            chunk = conn.recv(64)
            if not chunk:
                return False
            buf += chunk
        line = buf.split(b"\n", 1)[0].strip().decode(errors="replace")
        ok = hmac.compare_digest(line, token)
        conn.settimeout(None)
        return ok
    except Exception:
        return False


def set_trace(frame=None) -> None:
    """Breakpoint: block this worker until a debugger client attaches
    (reference ``ray.util.pdb.set_trace``). No-op when
    RAY_TPU_DEBUGGER_DISABLED=1 or no client attaches in time."""
    dbg = _open_session(f"breakpoint in pid {os.getpid()}")
    if dbg is None:
        return
    # LAST statement on purpose: the tracer stops at the next executed
    # line, which must be the caller's — cleanup happens in the
    # debugger's continue/quit hooks
    dbg.set_trace(frame or sys._getframe().f_back)


def post_mortem(exc: Optional[BaseException] = None) -> None:
    """Debug a crashed task's traceback in place (reference
    ``ray.util.rpdb._post_mortem``)."""
    exc = exc or sys.exception()
    if exc is None or exc.__traceback__ is None:
        return
    dbg = _open_session(
        f"post-mortem in pid {os.getpid()}: {type(exc).__name__}: {exc}")
    if dbg is None:
        return
    try:
        dbg.interaction(None, exc.__traceback__)
    finally:
        dbg.close()


def post_mortem_enabled() -> bool:
    return os.environ.get("RAY_TPU_POST_MORTEM") == "1"


@contextlib.contextmanager
def post_mortem_on_error():
    """THE task-execution hook (used by both the in-process and the
    pooled-worker paths): on a task exception with post-mortem enabled,
    hold the crashed frame open for an operator, then re-raise the
    ORIGINAL error. Must run INSIDE apply_runtime_env so per-task
    env_vars={"RAY_TPU_POST_MORTEM": "1"} works; a debugger-side
    failure must never mask the user's exception."""
    try:
        yield
    except BaseException as e:  # noqa: BLE001 — re-raised below
        try:
            if post_mortem_enabled():
                post_mortem(e)
        except Exception:
            pass
        raise


# ---------------------------------------------------------------------------
# client side
# ---------------------------------------------------------------------------

def connect(host: str, port: int, *, commands: Optional[List[str]] = None,
            timeout: float = 30.0, token: Optional[str] = None) -> str:
    """Attach to a session. With ``commands`` (tests/automation): send
    each line, return the full transcript. Without: bridge this
    process's stdin/stdout to the session until it closes (the
    ``ray-tpu debug`` interactive path). ``token`` authenticates to an
    externally-bound session (falls back to RAY_TPU_DEBUGGER_TOKEN)."""
    sock = socket.create_connection((host, port), timeout=timeout)
    token = token or os.environ.get("RAY_TPU_DEBUGGER_TOKEN")
    if token:
        sock.sendall(token.encode() + b"\n")
    if commands is None:
        # interactive: the timeout applies to CONNECTING only — an
        # operator reading code at the prompt must not be disconnected
        sock.settimeout(None)
        return _bridge_tty(sock)
    sock.settimeout(timeout)
    transcript = []
    io = sock.makefile("rw", buffering=1)
    try:
        for cmd in commands:
            # read until the prompt, then issue the next command
            transcript.append(_read_until(io, "(rpdb) "))
            io.write(cmd + "\n")
            io.flush()
        transcript.append(_drain(sock, io))
    finally:
        try:
            sock.close()
        except Exception:
            pass
    return "".join(transcript)


def _read_until(io, marker: str) -> str:
    buf = []
    while True:
        ch = io.read(1)
        if not ch:
            return "".join(buf)
        buf.append(ch)
        if "".join(buf[-len(marker):]) == marker:
            return "".join(buf)


def _drain(sock, io) -> str:
    sock.settimeout(1.0)
    buf = []
    try:
        while True:
            ch = io.read(1)
            if not ch:
                break
            buf.append(ch)
    except Exception:
        pass
    return "".join(buf)


def _bridge_tty(sock: socket.socket) -> str:
    """Interactive bridge: stdin -> socket, socket -> stdout."""
    io = sock.makefile("rw", buffering=1)
    stop = threading.Event()

    def pump_out():
        try:
            while not stop.is_set():
                ch = io.read(1)
                if not ch:
                    break
                sys.stdout.write(ch)
                sys.stdout.flush()
        except Exception:
            pass
        stop.set()

    t = threading.Thread(target=pump_out, daemon=True)
    t.start()
    try:
        while not stop.is_set():
            line = sys.stdin.readline()
            if not line:
                break
            io.write(line)
            io.flush()
    finally:
        stop.set()
        try:
            sock.close()
        except Exception:
            pass
    return ""
