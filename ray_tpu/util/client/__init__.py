"""Ray-Client-equivalent: remote driver over a socket.

Reference: `python/ray/util/client/` (`ray://` mode — a thin client
proxies API calls over gRPC to a server running inside the cluster,
`server/server.py:96`). Here the wire is a length-prefixed cloudpickle
protocol over TCP; the API proxy covers put/get/wait/remote
functions/actors/kill/cluster_resources.
"""

from ray_tpu.util.client.server import ClientServer, serve_cluster
from ray_tpu.util.client.client import ClusterClient, connect

__all__ = ["ClientServer", "serve_cluster", "ClusterClient", "connect"]
