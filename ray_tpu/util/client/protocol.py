"""Length-prefixed cloudpickle framing shared by client + server."""

from __future__ import annotations

import socket
import struct
from typing import Any

import cloudpickle

# ONE recv implementation for every wire layer (recv_into + memoryview,
# no per-chunk copies); raises ConnectionError on EOF like the local
# helper it replaced.
from ray_tpu._private.rpc import SEND_CONCAT_MAX
from ray_tpu._private.rpc import recv_exact as _recv_exact

_HDR = struct.Struct("!Q")
MAX_FRAME = 1 << 34


def send_msg(sock: socket.socket, obj: Any) -> None:
    from ray_tpu._private.device_objects import wire_dumps
    payload = wire_dumps(obj)   # sharding-preserving jax wire format
    if len(payload) <= SEND_CONCAT_MAX:
        sock.sendall(_HDR.pack(len(payload)) + payload)
    else:   # big tensors: skip the header+payload concat copy
        sock.sendall(_HDR.pack(len(payload)))
        sock.sendall(payload)


def recv_msg(sock: socket.socket) -> Any:
    hdr = _recv_exact(sock, _HDR.size)
    (length,) = _HDR.unpack(hdr)
    if length > MAX_FRAME:
        raise ValueError(f"frame too large: {length}")
    return cloudpickle.loads(_recv_exact(sock, length))
