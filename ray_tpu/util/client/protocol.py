"""Length-prefixed cloudpickle framing shared by client + server."""

from __future__ import annotations

import socket
import struct
from typing import Any

import cloudpickle

_HDR = struct.Struct("!Q")
MAX_FRAME = 1 << 34


def send_msg(sock: socket.socket, obj: Any) -> None:
    from ray_tpu._private.device_objects import wire_dumps
    payload = wire_dumps(obj)   # sharding-preserving jax wire format
    sock.sendall(_HDR.pack(len(payload)) + payload)


def recv_msg(sock: socket.socket) -> Any:
    hdr = _recv_exact(sock, _HDR.size)
    (length,) = _HDR.unpack(hdr)
    if length > MAX_FRAME:
        raise ValueError(f"frame too large: {length}")
    return cloudpickle.loads(_recv_exact(sock, length))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)
