"""Thin client: the remote-driver side of `ray://` mode.

Reference: `python/ray/util/client/api.py` + `worker.py` (ClientAPI
mirroring the core API; ClientObjectRef/ClientActorHandle proxies).
"""

from __future__ import annotations

import socket
import threading
import uuid
from typing import Any, Dict, List, Optional, Sequence, Union

from ray_tpu.util.client.protocol import recv_msg, send_msg


class ClientObjectRef:
    def __init__(self, client: "ClusterClient", ref_id: str):
        self._client = client
        self.ref_id = ref_id

    def __repr__(self):
        return f"ClientObjectRef({self.ref_id[:12]})"


class ClientActorMethod:
    def __init__(self, client, actor_id: str, name: str):
        self._client = client
        self._actor_id = actor_id
        self._name = name

    def remote(self, *args, **kwargs) -> ClientObjectRef:
        return self._client._actor_call(self._actor_id, self._name, args,
                                        kwargs)


class ClientActorHandle:
    def __init__(self, client, actor_id: str):
        self._client = client
        self._actor_id = actor_id

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return ClientActorMethod(self._client, self._actor_id, name)


class ClientRemoteFunction:
    def __init__(self, client, func, options: Optional[Dict] = None):
        self._client = client
        self._func = func
        self._func_id = uuid.uuid4().hex
        self._options = options
        self._registered = False

    def options(self, **opts) -> "ClientRemoteFunction":
        out = ClientRemoteFunction(self._client, self._func, opts)
        out._func_id = self._func_id
        out._registered = self._registered
        return out

    def remote(self, *args, **kwargs) -> ClientObjectRef:
        if not self._registered:
            self._client._call("register_function",
                               func_id=self._func_id, func=self._func)
            self._registered = True
        rid = self._client._call(
            "task", func_id=self._func_id,
            args=self._client._wrap_args(args), kwargs=kwargs,
            options=self._options)
        return ClientObjectRef(self._client, rid)


class ClientActorClass:
    def __init__(self, client, cls, options: Optional[Dict] = None):
        self._client = client
        self._cls = cls
        self._options = options

    def options(self, **opts) -> "ClientActorClass":
        return ClientActorClass(self._client, self._cls, opts)

    def remote(self, *args, **kwargs) -> ClientActorHandle:
        aid = self._client._call(
            "create_actor", cls=self._cls,
            args=self._client._wrap_args(args), kwargs=kwargs,
            options=self._options)
        return ClientActorHandle(self._client, aid)


class ClusterClient:
    """Mirrors the core API over the wire."""

    def __init__(self, address: str):
        host, _, port = address.partition(":")
        self._sock = socket.create_connection((host, int(port)), timeout=60)
        self._lock = threading.Lock()
        assert self._call("ping") == "pong"

    # -- plumbing --------------------------------------------------------
    def _call(self, op: str, **kwargs) -> Any:
        with self._lock:
            send_msg(self._sock, {"op": op, **kwargs})
            resp = recv_msg(self._sock)
        if not resp["ok"]:
            raise RuntimeError(
                f"server error: {resp['error']}\n{resp['traceback']}")
        return resp["result"]

    def _wrap_args(self, args):
        out = []
        for a in args:
            if isinstance(a, ClientObjectRef):
                out.append({"__client_ref__": True, "ref_id": a.ref_id})
            else:
                out.append(a)
        return out

    def _actor_call(self, actor_id, method, args, kwargs):
        rid = self._call("actor_call", actor_id=actor_id, method=method,
                         args=self._wrap_args(args), kwargs=kwargs)
        return ClientObjectRef(self, rid)

    # -- API -------------------------------------------------------------
    def put(self, value: Any) -> ClientObjectRef:
        return ClientObjectRef(self, self._call("put", value=value))

    def get(self, refs: Union[ClientObjectRef, Sequence[ClientObjectRef]],
            *, timeout: Optional[float] = None):
        single = isinstance(refs, ClientObjectRef)
        ref_list = [refs] if single else list(refs)
        values = self._call("get", ref_ids=[r.ref_id for r in ref_list],
                            timeout=timeout)
        return values[0] if single else values

    def wait(self, refs: Sequence[ClientObjectRef], *, num_returns: int = 1,
             timeout: Optional[float] = None):
        ready_ids, rest_ids = self._call(
            "wait", ref_ids=[r.ref_id for r in refs],
            num_returns=num_returns, timeout=timeout)
        by_id = {r.ref_id: r for r in refs}
        return ([by_id[i] for i in ready_ids],
                [by_id[i] for i in rest_ids])

    def remote(self, func_or_class):
        import inspect
        if inspect.isclass(func_or_class):
            return ClientActorClass(self, func_or_class)
        return ClientRemoteFunction(self, func_or_class)

    def kill(self, actor: ClientActorHandle) -> None:
        self._call("kill_actor", actor_id=actor._actor_id)

    def cluster_resources(self) -> Dict[str, float]:
        return self._call("cluster_resources")

    def available_resources(self) -> Dict[str, float]:
        return self._call("available_resources")

    def release(self, refs: List[ClientObjectRef]) -> None:
        self._call("release", ref_ids=[r.ref_id for r in refs])

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def connect(address: str) -> ClusterClient:
    """`ray_tpu.util.client.connect("host:port")` — remote-driver mode."""
    return ClusterClient(address)
