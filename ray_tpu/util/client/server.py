"""Client server: runs inside the cluster, executes proxied API calls.

Reference: `util/client/server/server.py:96` (RayletServicer — the gRPC
servicer holding server-side refs on behalf of remote drivers).
"""

from __future__ import annotations

import socket
import threading
import traceback
import uuid
from typing import Any, Dict, Optional, Tuple

import ray_tpu
from ray_tpu.util.client.protocol import recv_msg, send_msg


class ClientServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.address: Tuple[str, int] = self._sock.getsockname()
        # server-side handle tables (the server owns refs for the client)
        self._refs: Dict[str, Any] = {}
        self._actors: Dict[str, Any] = {}
        self._funcs: Dict[str, Any] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="client-server").start()

    # -- wire loop -------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while True:
                req = recv_msg(conn)
                try:
                    result = self._handle(req)
                    send_msg(conn, {"ok": True, "result": result})
                except Exception as e:
                    send_msg(conn, {
                        "ok": False, "error": repr(e),
                        "traceback": traceback.format_exc()})
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    # -- ops -------------------------------------------------------------
    def _track_ref(self, ref) -> str:
        rid = uuid.uuid4().hex
        with self._lock:
            self._refs[rid] = ref
        return rid

    def _handle(self, req: Dict) -> Any:
        op = req["op"]
        if op == "put":
            return self._track_ref(ray_tpu.put(req["value"]))
        if op == "get":
            refs = [self._refs[r] for r in req["ref_ids"]]
            values = ray_tpu.get(refs, timeout=req.get("timeout"))
            return values
        if op == "wait":
            refs = [self._refs[r] for r in req["ref_ids"]]
            ready, not_ready = ray_tpu.wait(
                refs, num_returns=req["num_returns"],
                timeout=req.get("timeout"))
            id_of = {id(v): k for k, v in self._refs.items()}
            return ([id_of[id(r)] for r in ready],
                    [id_of[id(r)] for r in not_ready])
        if op == "register_function":
            self._funcs[req["func_id"]] = ray_tpu.remote(req["func"])
            return True
        if op == "task":
            fn = self._funcs[req["func_id"]]
            if req.get("options"):
                fn = fn.options(**req["options"])
            args = self._unwrap_args(req["args"])
            ref = fn.remote(*args, **req.get("kwargs", {}))
            return self._track_ref(ref)
        if op == "create_actor":
            cls = ray_tpu.remote(req["cls"])
            if req.get("options"):
                cls = cls.options(**req["options"])
            args = self._unwrap_args(req["args"])
            handle = cls.remote(*args, **req.get("kwargs", {}))
            aid = uuid.uuid4().hex
            self._actors[aid] = handle
            return aid
        if op == "actor_call":
            handle = self._actors[req["actor_id"]]
            method = getattr(handle, req["method"])
            args = self._unwrap_args(req["args"])
            return self._track_ref(method.remote(*args,
                                                 **req.get("kwargs", {})))
        if op == "kill_actor":
            ray_tpu.kill(self._actors.pop(req["actor_id"]))
            return True
        if op == "release":
            with self._lock:
                for rid in req["ref_ids"]:
                    self._refs.pop(rid, None)
            return True
        if op == "cluster_resources":
            return ray_tpu.cluster_resources()
        if op == "available_resources":
            return ray_tpu.available_resources()
        if op == "ping":
            return "pong"
        raise ValueError(f"unknown op {op!r}")

    def _unwrap_args(self, args):
        out = []
        for a in args:
            if isinstance(a, dict) and a.get("__client_ref__"):
                out.append(self._refs[a["ref_id"]])
            else:
                out.append(a)
        return out

    def shutdown(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


def serve_cluster(host: str = "127.0.0.1", port: int = 0,
                  num_nodes: int = 1) -> ClientServer:
    """Boot a runtime (if needed) and serve it to remote drivers."""
    if not ray_tpu.is_initialized():
        ray_tpu.init(num_nodes=num_nodes)
    return ClientServer(host, port)
