"""Opt-in tracing (reference: `python/ray/util/tracing/tracing_helper.py`
— OpenTelemetry spans around task/actor invocation+execution, lazily
enabled). Spans here go to an in-memory exporter with the OTel span shape
(name, start/end ns, attributes, parent), convertible to chrome trace.
"""

from __future__ import annotations

import contextlib
import itertools
import threading
import time
from typing import Any, Dict, List, Optional

_enabled = False
_spans: List[Dict[str, Any]] = []
_lock = threading.Lock()
_current = threading.local()
_ids = itertools.count(1)


def enable_tracing() -> None:
    global _enabled
    _enabled = True


def disable_tracing() -> None:
    global _enabled
    _enabled = False


def is_enabled() -> bool:
    return _enabled


def get_spans() -> List[Dict[str, Any]]:
    with _lock:
        return list(_spans)


def clear_spans() -> None:
    with _lock:
        _spans.clear()


@contextlib.contextmanager
def span(name: str, **attributes):
    """Record one span (no-op when tracing is disabled)."""
    if not _enabled:
        yield None
        return
    sid = next(_ids)
    parent = getattr(_current, "span_id", None)
    _current.span_id = sid
    start = time.time_ns()
    try:
        yield sid
    finally:
        _current.span_id = parent
        with _lock:
            _spans.append({
                "name": name, "span_id": sid, "parent_id": parent,
                "start_ns": start, "end_ns": time.time_ns(),
                "attributes": attributes})


def chrome_trace() -> List[Dict[str, Any]]:
    out = []
    for s in get_spans():
        out.append({"name": s["name"], "ph": "X", "cat": "trace",
                    "ts": s["start_ns"] / 1000,
                    "dur": max((s["end_ns"] - s["start_ns"]) / 1000, 1),
                    "pid": "trace", "tid": str(s["parent_id"] or 0),
                    "args": s["attributes"]})
    return out
