"""Actor-backed distributed queue (reference: python/ray/util/queue.py)."""

from __future__ import annotations

import asyncio
from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


@ray_tpu.remote(max_concurrency=64)
class _QueueActor:
    def __init__(self, maxsize: int):
        self.q = asyncio.Queue(maxsize=maxsize)

    async def put(self, item, timeout=None):
        try:
            await asyncio.wait_for(self.q.put(item), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def get(self, timeout=None):
        try:
            return True, await asyncio.wait_for(self.q.get(), timeout)
        except asyncio.TimeoutError:
            return False, None

    def put_nowait(self, item):
        try:
            self.q.put_nowait(item)
            return True
        except asyncio.QueueFull:
            return False

    def get_nowait(self):
        try:
            return True, self.q.get_nowait()
        except asyncio.QueueEmpty:
            return False, None

    async def get_item(self):
        return await self.q.get()

    def qsize(self):
        return self.q.qsize()

    def empty(self):
        return self.q.empty()

    def full(self):
        return self.q.full()


class Queue:
    """Multi-producer multi-consumer queue shared across tasks/actors."""

    def __init__(self, maxsize: int = 0,
                 actor_options: Optional[dict] = None):
        self.maxsize = maxsize
        opts = actor_options or {}
        self.actor = (_QueueActor.options(**opts).remote(maxsize)
                      if opts else _QueueActor.remote(maxsize))

    def put(self, item: Any, block: bool = True,
            timeout: Optional[float] = None) -> None:
        if not block:
            if not ray_tpu.get(self.actor.put_nowait.remote(item)):
                raise Full("queue is full")
            return
        if not ray_tpu.get(self.actor.put.remote(item, timeout)):
            raise Full("queue is full (timeout)")

    def get(self, block: bool = True,
            timeout: Optional[float] = None) -> Any:
        if not block:
            ok, item = ray_tpu.get(self.actor.get_nowait.remote())
            if not ok:
                raise Empty("queue is empty")
            return item
        ok, item = ray_tpu.get(self.actor.get.remote(timeout))
        if not ok:
            raise Empty("queue is empty (timeout)")
        return item

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return ray_tpu.get(self.actor.empty.remote())

    def full(self) -> bool:
        return ray_tpu.get(self.actor.full.remote())

    def put_async(self, item: Any):
        return self.actor.put.remote(item)

    def get_async(self):
        """Ref resolving to the item itself (same contract as get())."""
        return self.actor.get_item.remote()

    def shutdown(self) -> None:
        ray_tpu.kill(self.actor)
