"""Scheduling strategies — the reference's import path
(`ray.util.scheduling_strategies`) re-exporting the canonical classes
from the task-spec module (where the scheduler consumes them)."""

from ray_tpu._private.task_spec import (NodeAffinitySchedulingStrategy,
                                        NodeLabelSchedulingStrategy,
                                        PlacementGroupSchedulingStrategy)

__all__ = ["NodeAffinitySchedulingStrategy",
           "NodeLabelSchedulingStrategy",
           "PlacementGroupSchedulingStrategy"]
