"""Placement groups: gang scheduling of resource bundles.

Parity contract (reference ``python/ray/util/placement_group.py`` +
``src/ray/raylet/scheduling/policy/bundle_scheduling_policy.h`` +
``src/ray/gcs/gcs_server/gcs_placement_group_mgr.cc``): a placement group
reserves a list of resource bundles across the cluster atomically, with
PACK / SPREAD / STRICT_PACK / STRICT_SPREAD strategies; tasks and actors are
then scheduled into bundle reservations via
``PlacementGroupSchedulingStrategy``.

Mechanism: each placed bundle converts node capacity into bundle-scoped
resources (``_pg_<id>_<index>_<name>``) on the node's ledger — the analogue of
the reference's ``CPU_group_<pgid>`` formatted resources — and PG-scheduled
tasks have their demands rewritten onto those scoped names, so bundle
accounting rides the existing ledger/dispatch machinery.

TPU-first: bundles that request ``TPU`` chips are placed on as-few hosts as
possible even under SPREAD-of-bundles, because a mesh over ICI requires
chip contiguity; the ICI-topology-aware sub-slice allocator lives in
:mod:`ray_tpu.parallel.topology` and is consulted when a topology is present.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, TYPE_CHECKING

from ray_tpu import exceptions as exc
from ray_tpu._private.ids import NodeID, ObjectID, PlacementGroupID

if TYPE_CHECKING:
    from ray_tpu._private.node import Node

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


def _scoped(pg_id: PlacementGroupID, index: int, resource: str) -> str:
    return f"_pg_{pg_id.hex()[:16]}_{index}_{resource}"


@dataclass
class Bundle:
    index: int
    resources: Dict[str, float]
    node_id: Optional[NodeID] = None
    # ICI coords this bundle's TPU chips claimed (topology-aware path)
    tpu_chips: Optional[List[tuple]] = None

    def scoped_resources(self, pg_id: PlacementGroupID) -> Dict[str, float]:
        return {_scoped(pg_id, self.index, k): v
                for k, v in self.resources.items()}


class PlacementGroup:
    """Handle to a (possibly still-placing) placement group."""

    def __init__(self, pg_id: PlacementGroupID, bundles: List[Bundle],
                 strategy: str, name: str = ""):
        self.id = pg_id
        self.bundles = bundles
        self.strategy = strategy
        self.name = name
        self.state = "PENDING"
        # the ICI-contiguous sub-slice this group claimed (TPU bundles
        # under a declared topology); freed on remove/node-death
        self.subslice = None
        self._ready_event = threading.Event()
        self._ready_ref: Optional[ObjectID] = None
        self._failure: Optional[str] = None

    def __getstate__(self):
        """PGs are serializable handles (they cross task/worker
        boundaries); the local wait-machinery is rebuilt on unpickle."""
        d = dict(self.__dict__)
        d["_ready_event"] = None
        d["_ready_ref"] = None
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self._ready_event = threading.Event()
        if self.state in ("CREATED", "REMOVED"):
            self._ready_event.set()

    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        return [dict(b.resources) for b in self.bundles]

    def bundle_nodes(self) -> List[NodeID]:
        return [b.node_id for b in self.bundles]

    def is_ready(self) -> bool:
        return self.state == "CREATED"

    def ready(self):
        """ObjectRef that resolves when the group is placed (awaitable)."""
        from ray_tpu._private import worker
        from ray_tpu._private.object_ref import ObjectRef

        rt = worker.global_worker()
        if not hasattr(rt, "futures"):
            # Worker-process handle: the owning runtime lives host-side;
            # ask it for (and cache) the ready ref.
            if self._ready_ref is None:
                self._ready_ref = rt.pg_manager.ready_ref(self.id).id
            return ObjectRef(self._ready_ref, task_name="pg.ready")
        if self._ready_ref is None:
            self._ready_ref = ObjectID.from_random()
            rt.futures.register(self._ready_ref)

            def on_ready():
                self._ready_event.wait()
                if self.state == "CREATED":
                    rt._store_value(self._ready_ref, self)
                else:
                    rt._store_value(self._ready_ref, exc.TaskError(
                        exc.PlacementGroupUnschedulableError(
                            self._failure or "placement group removed"),
                        "placement_group.ready"))
                rt.futures.complete(self._ready_ref)

            threading.Thread(target=on_ready, daemon=True).start()
        return ObjectRef(self._ready_ref, task_name="pg.ready")

    def wait(self, timeout_seconds: float = 30) -> bool:
        if self._ready_event.is_set():
            return self.is_ready()
        from ray_tpu._private import worker
        rt = worker.global_worker()
        mgr = getattr(rt, "pg_manager", None)
        if mgr is None or mgr.get(self.id) is self:
            # Owning runtime: the manager flips our event directly.
            self._ready_event.wait(timeout_seconds)
            return self.is_ready()
        # Worker-process handle: poll the owner for state.
        import time as _time
        deadline = _time.monotonic() + timeout_seconds
        while True:
            cur = mgr.get(self.id)
            if cur is None:
                return False
            self.state = cur.state
            self.bundles = cur.bundles  # pick up node assignments
            if cur.state == "CREATED":
                self._ready_event.set()
                return True
            if cur.state == "REMOVED" or _time.monotonic() >= deadline:
                return self.is_ready()
            _time.sleep(0.02)

    def __repr__(self):
        return (f"PlacementGroup({self.id.hex()[:12]}, "
                f"{self.strategy}, {self.state}, "
                f"{len(self.bundles)} bundles)")


class PlacementGroupManager:
    """Places bundles onto nodes, retries pending groups, repairs on loss."""

    def __init__(self, runtime):
        self._rt = runtime
        self._lock = threading.Lock()
        self._pending: List[PlacementGroup] = []
        self._groups: Dict[PlacementGroupID, PlacementGroup] = {}
        self._wake = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="pg-manager")
        self._thread.start()
        runtime.gcs.pubsub.subscribe("node", lambda msg: self._wake.set())

    def create(self, bundles: List[Dict[str, float]], strategy: str,
               name: str = "") -> PlacementGroup:
        if strategy not in VALID_STRATEGIES:
            raise ValueError(f"invalid strategy {strategy!r}; "
                             f"one of {VALID_STRATEGIES}")
        if not bundles:
            raise ValueError("placement group needs at least one bundle")
        for b in bundles:
            if not b or any(v < 0 for v in b.values()):
                raise ValueError(f"invalid bundle {b!r}")
        topo = getattr(self._rt, "tpu_topology", None)
        if topo is not None:
            cap = topo.topology.chips_per_host
            for b in bundles:
                t = b.get("TPU", 0)
                if t != int(t):
                    raise ValueError(
                        f"fractional TPU bundle {b!r}: chips are whole "
                        "torus nodes under a declared topology")
                if t > cap:
                    raise ValueError(
                        f"bundle {b!r} wants {int(t)} chips but hosts of "
                        f"{topo.topology!r} have {cap}; a bundle is one "
                        "node's reservation — split it across bundles")
        pg = PlacementGroup(
            PlacementGroupID.from_random(),
            [Bundle(i, dict(b)) for i, b in enumerate(bundles)],
            strategy, name)
        with self._lock:
            self._groups[pg.id] = pg
            self._pending.append(pg)
        self._rt.gcs.placement_groups[pg.id] = pg
        self._wake.set()
        return pg

    def _release_bundles(self, pg: PlacementGroup) -> None:
        """Return every placed bundle's reservation to its node."""
        for b in pg.bundles:
            node = self._rt.get_node(b.node_id) if b.node_id else None
            if node is not None and node.alive:
                node.ledger.remove_total(b.scoped_resources(pg.id))
                node.ledger.release(b.resources)
                daemon = getattr(node, "daemon", None)
                if daemon is not None:
                    daemon.cancel_bundle(pg.id.hex(), b.index)
            b.node_id = None
        self._free_subslice(pg)

    def _free_subslice(self, pg: PlacementGroup) -> None:
        topo = getattr(self._rt, "tpu_topology", None)
        if pg.subslice is not None and topo is not None:
            topo.free(pg.subslice)
        pg.subslice = None
        for b in pg.bundles:
            b.tpu_chips = None

    def remove(self, pg: PlacementGroup) -> None:
        with self._lock:
            if pg.state == "REMOVED":
                return
            was_created = pg.state == "CREATED"
            pg.state = "REMOVED"
            if pg in self._pending:
                self._pending.remove(pg)
        if was_created:
            self._release_bundles(pg)
        pg._ready_event.set()

    def get(self, pg_id: PlacementGroupID) -> Optional[PlacementGroup]:
        with self._lock:
            return self._groups.get(pg_id)

    def table(self) -> Dict[str, Dict]:
        with self._lock:
            return {pg.id.hex(): {
                "name": pg.name, "strategy": pg.strategy, "state": pg.state,
                "bundles": {b.index: dict(b.resources) for b in pg.bundles},
                "bundle_nodes": [b.node_id.hex() if b.node_id else None
                                 for b in pg.bundles],
                **({"subslice": {"origin": sub.origin,
                                 "shape": sub.shape},
                    "bundle_chips": [b.tpu_chips for b in pg.bundles]}
                   # snapshot: _free_subslice nulls the field lock-free
                   if (sub := pg.subslice) is not None else {}),
            } for pg in self._groups.values()}

    def on_node_death(self, node_id: NodeID) -> None:
        """Re-place bundles that lived on a dead node."""
        topo = getattr(self._rt, "tpu_topology", None)
        if topo is not None:
            # the dead host's chips return to the pool; a replacement
            # node binds to the freed host index on next placement
            topo.unbind_node(node_id)
        with self._lock:
            for pg in self._groups.values():
                if pg.state != "CREATED":
                    continue
                if any(b.node_id == node_id for b in pg.bundles):
                    # Tear down surviving bundle reservations; re-place all.
                    for b in pg.bundles:
                        if b.node_id is not None and b.node_id != node_id:
                            node = self._rt.get_node(b.node_id)
                            if node is not None and node.alive:
                                node.ledger.remove_total(
                                    b.scoped_resources(pg.id))
                                node.ledger.release(b.resources)
                        b.node_id = None
                    self._free_subslice(pg)
                    pg.state = "RESCHEDULING"
                    # Not ready again until re-placed: waiters must block.
                    pg._ready_event.clear()
                    self._pending.append(pg)
        self._wake.set()

    # -- placement ---------------------------------------------------------
    def _loop(self) -> None:
        while True:
            self._wake.wait(1.0)
            self._wake.clear()
            with self._lock:
                pending = list(self._pending)
            for pg in pending:
                if self._try_place(pg):
                    with self._lock:
                        if pg.state == "REMOVED":
                            # Lost the race with remove(): undo reservation.
                            self._release_bundles(pg)
                            continue
                        if pg in self._pending:
                            self._pending.remove(pg)
                        pg.state = "CREATED"
                    pg._ready_event.set()

    def _try_place(self, pg: PlacementGroup) -> bool:
        # Draining nodes accept no new bundles (their capacity is on the
        # way out); schedulable_nodes falls back to them only when
        # nothing else is alive.
        nodes = self._rt.schedulable_nodes()
        if not nodes:
            return False
        assignment = self._assign(pg, nodes)
        if assignment is None:
            return False
        acquired: List[tuple] = []
        ok = True
        for bundle, node in assignment:
            if not node.ledger.try_acquire(bundle.resources):
                ok = False
                break
            # Daemon-backed node: phase-1 PREPARE on the wire (reference:
            # node_manager.proto PrepareBundleResources 2PC).
            daemon = getattr(node, "daemon", None)
            if daemon is not None and not daemon.prepare_bundle(
                    pg.id.hex(), bundle.index, dict(bundle.resources)):
                node.ledger.release(bundle.resources)
                ok = False
                break
            acquired.append((bundle, node))
        if not ok:  # roll back the partial reservation (2PC abort)
            for bundle, node in acquired:
                node.ledger.release(bundle.resources)
                daemon = getattr(node, "daemon", None)
                if daemon is not None:
                    daemon.cancel_bundle(pg.id.hex(), bundle.index)
            self._free_subslice(pg)
            return False
        for bundle, node in acquired:
            node.ledger.add_total(bundle.scoped_resources(pg.id))
            bundle.node_id = node.node_id
            daemon = getattr(node, "daemon", None)
            if daemon is not None:
                daemon.commit_bundle(pg.id.hex(), bundle.index)
        return True

    def _assign(self, pg: PlacementGroup,
                nodes: List["Node"]) -> Optional[List[tuple]]:
        """Map bundles to nodes per strategy using *available* capacity."""
        topo = getattr(self._rt, "tpu_topology", None)
        if topo is not None and any(
                b.resources.get("TPU", 0) > 0 for b in pg.bundles):
            return self._assign_tpu(pg, nodes, topo)
        avail = {n.node_id: n.effective_available() for n in nodes}

        def fits(node, bundle) -> bool:
            a = avail[node.node_id]
            return all(a.get(k, 0.0) >= v - 1e-9
                       for k, v in bundle.resources.items())

        def charge(node, bundle) -> None:
            a = avail[node.node_id]
            for k, v in bundle.resources.items():
                a[k] = a.get(k, 0.0) - v

        out: List[tuple] = []
        strategy = pg.strategy
        if strategy in ("PACK", "STRICT_PACK"):
            # (TPU bundles took the topology path above when declared)
            # Greedy: fewest nodes; STRICT_PACK demands exactly one node.
            ordered = sorted(
                nodes, key=lambda n: -sum(avail[n.node_id].values()))
            for bundle in pg.bundles:
                placed = False
                # Prefer nodes already used (pack).
                used = [n for n, _ in
                        ((n, None) for n in ordered
                         if any(x[1] is n for x in out))]
                for node in used + ordered:
                    if fits(node, bundle):
                        charge(node, bundle)
                        out.append((bundle, node))
                        placed = True
                        break
                if not placed:
                    return None
            if strategy == "STRICT_PACK":
                if len({id(n) for _, n in out}) != 1:
                    return None
            return out
        # SPREAD / STRICT_SPREAD: round-robin across distinct nodes.
        ordered = sorted(nodes, key=lambda n: -sum(avail[n.node_id].values()))
        used_nodes: List = []
        for bundle in pg.bundles:
            placed = False
            candidates = ([n for n in ordered if n not in used_nodes]
                          + ([] if strategy == "STRICT_SPREAD"
                             else used_nodes))
            for node in candidates:
                if fits(node, bundle):
                    charge(node, bundle)
                    out.append((bundle, node))
                    used_nodes.append(node)
                    placed = True
                    break
            if not placed:
                return None
        return out

    def _assign_tpu(self, pg: PlacementGroup, nodes: List["Node"],
                    topo) -> Optional[List[tuple]]:
        """ICI-topology path (bundle_scheduling_policy.h role, TPU-first):
        the group's TPU chips claim ONE axis-aligned contiguous sub-slice
        of the torus; bundles land on the sub-slice's hosts, so the
        gang's collectives ride ICI. The claim is recorded on the PG
        (``pg.subslice`` + per-bundle chip coords) and released on
        remove / node death / 2PC abort."""
        chips = [int(b.resources.get("TPU", 0)) for b in pg.bundles]
        total = sum(chips)
        # bind TPU-capable nodes to torus hosts (first-seen, stable)
        tpu_nodes = [n for n in nodes
                     if n.ledger.total.get("TPU", 0) > 0]
        topo.bind_nodes([n.node_id for n in tpu_nodes])
        node_by_id = {n.node_id: n for n in tpu_nodes}
        host_node = {h: topo.node_of_host(h)
                     for h in range(topo.topology.num_hosts)}
        avail = {n.node_id: n.effective_available() for n in nodes}
        strategy = pg.strategy

        tpu_items = [(b, c) for b, c in zip(pg.bundles, chips) if c > 0]
        cpu_items = [b for b, c in zip(pg.bundles, chips) if c == 0]

        def try_pack(cand) -> Optional[List[tuple]]:
            """Greedy bundle->host packing for one candidate box
            (largest bundles first keeps per-host fragments down).
            Chip-less bundles place by the generic strategy semantics on
            ANY node — they must not be forced onto (or burn) sub-slice
            hosts. Returns [(bundle, node, chip_coords)] or None."""
            remaining = topo.chips_by_host(cand)
            trial = {nid: dict(a) for nid, a in avail.items()}
            packed: List[tuple] = []
            used_hosts: set = set()
            used_nodes: List = []

            def fits(node, bundle) -> bool:
                a = trial[node.node_id]
                return all(a.get(k, 0.0) >= v - 1e-9
                           for k, v in bundle.resources.items())

            def charge(node, bundle) -> None:
                a = trial[node.node_id]
                for k, v in bundle.resources.items():
                    a[k] = a.get(k, 0.0) - v

            for bundle, c in sorted(tpu_items, key=lambda t: -t[1]):
                hosts = sorted(remaining)
                if strategy in ("SPREAD", "STRICT_SPREAD"):
                    # spread across hosts: fresh hosts first (STRICT:
                    # fresh hosts only)
                    order = [h for h in hosts if h not in used_hosts]
                    if strategy == "SPREAD":
                        order += [h for h in hosts if h in used_hosts]
                else:
                    order = hosts
                for h in order:
                    if len(remaining[h]) < c:
                        continue
                    node = node_by_id.get(host_node.get(h))
                    if (node is None or not node.alive
                            or not fits(node, bundle)):
                        continue
                    charge(node, bundle)
                    taken = [remaining[h].pop(0) for _ in range(c)]
                    used_hosts.add(h)
                    if node not in used_nodes:
                        used_nodes.append(node)
                    packed.append((bundle, node, taken))
                    break
                else:
                    return None
            for bundle in cpu_items:
                if strategy == "STRICT_PACK":
                    cands = used_nodes[:1] or list(nodes)
                elif strategy == "STRICT_SPREAD":
                    cands = [n for n in nodes if n not in used_nodes]
                elif strategy == "SPREAD":
                    cands = ([n for n in nodes if n not in used_nodes]
                             + used_nodes)
                else:  # PACK
                    cands = (used_nodes
                             + [n for n in nodes if n not in used_nodes])
                for node in cands:
                    if not node.alive or not fits(node, bundle):
                        continue
                    charge(node, bundle)
                    if node not in used_nodes:
                        used_nodes.append(node)
                    packed.append((bundle, node, []))
                    break
                else:
                    return None
            return packed

        plan: Dict[str, List[tuple]] = {}

        def accept(cand) -> bool:
            p = try_pack(cand)
            if p is None:
                return False
            plan["packed"] = p
            return True

        # STRICT_PACK = one node = the box must fit one host's block
        sub = topo.allocate(total,
                            max_hosts=1 if strategy == "STRICT_PACK"
                            else None,
                            accept=accept)
        if sub is None:
            return None      # slice full/fragmented: stay pending
        out: List[tuple] = []
        for bundle, node, taken in plan["packed"]:
            bundle.tpu_chips = taken or None
            out.append((bundle, node))
        pg.subslice = sub
        return out


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def placement_group(bundles: List[Dict[str, float]],
                    strategy: str = "PACK",
                    name: str = "") -> PlacementGroup:
    """Create a placement group (async; use .ready()/.wait())."""
    from ray_tpu._private import worker
    rt = worker.global_worker()
    pg = rt.pg_manager.create(bundles, strategy, name)
    from ray_tpu._private.export_events import emit_export
    emit_export("PLACEMENT_GROUP", pg_id=pg.id.hex(), state="CREATED",
                strategy=strategy, bundles=bundles)
    return pg


def remove_placement_group(pg: PlacementGroup) -> None:
    from ray_tpu._private import worker
    worker.global_worker().pg_manager.remove(pg)
    from ray_tpu._private.export_events import emit_export
    emit_export("PLACEMENT_GROUP", pg_id=pg.id.hex(), state="REMOVED")


def placement_group_table() -> Dict[str, Dict]:
    from ray_tpu._private import worker
    return worker.global_worker().pg_manager.table()


def get_current_placement_group() -> Optional[PlacementGroup]:
    from ray_tpu._private import runtime_context, worker
    rt = worker.global_worker()
    ctx = runtime_context._ctx.get()
    pg_id = getattr(ctx, "placement_group_id", None) if ctx else None
    return rt.pg_manager.get(pg_id) if pg_id else None
