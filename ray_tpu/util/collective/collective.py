"""Host-level collective communication between tasks/actors.

Parity contract (reference ``python/ray/util/collective/collective.py:150,
187,295-660``): named groups with world_size/rank, allreduce / allgather /
reducescatter / broadcast / send / recv / barrier.

TPU-first split (SURVEY.md §5.8): collectives **inside jitted code** are XLA
collectives over ICI — use :mod:`ray_tpu.parallel` meshes and ``psum`` /
``all_gather`` / ``ppermute``; nothing to build there. This module is the
*host-level* plane the reference backs with NCCL/gloo: orchestration-grade
collectives between processes/actors, here backed by a rendezvous actor
(the analogue of the reference's NCCLUniqueID exchange through the internal
KV store, ``nccl_collective_group.py:29``) that matches ops by sequence
number and performs the reduction host-side.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu

_local = threading.local()
_actor_groups: Dict[str, Dict[str, "GroupState"]] = {}
_actor_groups_lock = threading.Lock()


def _group_states() -> Dict[str, "GroupState"]:
    """Group registry for the calling context.

    Actors run __init__ and methods on different threads, so their groups
    are keyed by actor id; driver/task code falls back to thread-local.
    """
    from ray_tpu._private import runtime_context
    ctx = runtime_context._ctx.get()
    actor_id = ctx.actor_id.hex() if (ctx and ctx.actor_id) else None
    if actor_id is not None:
        with _actor_groups_lock:
            return _actor_groups.setdefault(actor_id, {})
    if not hasattr(_local, "groups"):
        _local.groups = {}
    return _local.groups


class GroupState:
    def __init__(self, name: str, world_size: int, rank: int, coordinator):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.coordinator = coordinator
        self.seq = 0
        self.p2p_seq: Dict[tuple, int] = {}

    def next_seq(self) -> int:
        self.seq += 1
        return self.seq

    def next_p2p_seq(self, src: int, dst: int) -> int:
        key = (src, dst)
        self.p2p_seq[key] = self.p2p_seq.get(key, 0) + 1
        return self.p2p_seq[key]


@ray_tpu.remote(max_concurrency=256)
class _Coordinator:
    """Matches collective ops across ranks and computes reductions."""

    def __init__(self, world_size: int):
        import asyncio
        self.world_size = world_size
        self.ops: Dict = {}
        self.lock = asyncio.Lock()

    async def _slot(self, key):
        import asyncio
        async with self.lock:
            slot = self.ops.get(key)
            if slot is None:
                slot = self.ops[key] = {
                    "parts": {}, "event": asyncio.Event(), "result": None}
            return slot

    async def contribute(self, op: str, seq: int, rank: int, data):
        """Generic all-to-one-to-all: returns the op result for this rank."""
        import asyncio
        key = (op, seq)
        slot = await self._slot(key)
        slot["parts"][rank] = data
        if len(slot["parts"]) == self.world_size:
            slot["result"] = self._compute(op, slot["parts"])
            slot["event"].set()
        await slot["event"].wait()
        result = slot["result"]
        async with self.lock:
            slot.setdefault("consumed", 0)
            slot["consumed"] += 1
            if slot["consumed"] == self.world_size:
                self.ops.pop(key, None)
        if op.startswith(("reducescatter", "allgather_scatter")):
            return result[rank]
        return result

    def _compute(self, op: str, parts: Dict[int, Any]):
        ordered = [parts[r] for r in sorted(parts)]
        if op.startswith("allreduce"):
            reduce_op = op.split(":", 1)[1]
            return _reduce(ordered, reduce_op)
        if op.startswith("allgather"):
            return list(ordered)
        if op.startswith("reducescatter"):
            reduce_op = op.split(":", 1)[1]
            reduced = _reduce(ordered, reduce_op)
            return np.array_split(np.asarray(reduced), len(ordered))
        if op.startswith("broadcast"):
            src = int(op.split(":", 1)[1])
            return parts[src]
        if op.startswith("barrier"):
            return True
        raise ValueError(f"unknown collective op {op!r}")

    async def p2p_put(self, seq: int, dst: int, data):
        import asyncio
        key = ("p2p", seq, dst)
        slot = await self._slot(key)
        slot["result"] = data
        slot["event"].set()
        return True

    async def p2p_get(self, seq: int, dst: int):
        key = ("p2p", seq, dst)
        slot = await self._slot(key)
        await slot["event"].wait()
        result = slot["result"]
        async with self.lock:
            self.ops.pop(key, None)
        return result


def _reduce(arrays: List[Any], op: str):
    acc = np.asarray(arrays[0]).copy()
    for a in arrays[1:]:
        a = np.asarray(a)
        if op == "sum":
            acc = acc + a
        elif op == "product":
            acc = acc * a
        elif op == "min":
            acc = np.minimum(acc, a)
        elif op == "max":
            acc = np.maximum(acc, a)
        else:
            raise ValueError(f"unknown reduce op {op!r}")
    return acc


# ---------------------------------------------------------------------------
# public API (shape-parity with ray.util.collective)
# ---------------------------------------------------------------------------

def init_collective_group(world_size: int, rank: int,
                          backend: str = "host",
                          group_name: str = "default") -> None:
    """Join a named collective group from the calling task/actor."""
    if backend not in ("host", "gloo", "xla"):
        raise ValueError(f"unknown backend {backend!r}")
    if not (0 <= rank < world_size):
        raise ValueError(f"rank {rank} out of range for world {world_size}")
    coordinator = _Coordinator.options(
        name=f"_collective_{group_name}", get_if_exists=True,
        lifetime="detached").remote(world_size)
    _group_states()[group_name] = GroupState(group_name, world_size, rank,
                                             coordinator)


def create_collective_group(actors: List, world_size: int, ranks: List[int],
                            backend: str = "host",
                            group_name: str = "default") -> None:
    """Declare a group for a set of actors (driver-side convenience).

    Each actor must still call ``init_collective_group`` (same contract as
    the reference's declarative path).
    """
    refs = [a._init_collective.remote(world_size, r, backend, group_name)
            for a, r in zip(actors, ranks)]
    ray_tpu.get(refs)


def destroy_collective_group(group_name: str = "default") -> None:
    state = _group_states().pop(group_name, None)
    if state is not None and state.rank == 0:
        try:
            ray_tpu.kill(state.coordinator)
        except Exception:
            pass


def _state(group_name: str) -> GroupState:
    state = _group_states().get(group_name)
    if state is None:
        raise RuntimeError(
            f"collective group {group_name!r} is not initialized in this "
            f"task/actor; call init_collective_group first")
    return state


def get_rank(group_name: str = "default") -> int:
    return _state(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _state(group_name).world_size


def allreduce(tensor, op: str = "sum", group_name: str = "default"):
    s = _state(group_name)
    return ray_tpu.get(s.coordinator.contribute.remote(
        f"allreduce:{op}", s.next_seq(), s.rank, tensor))


def allgather(tensor, group_name: str = "default") -> List:
    s = _state(group_name)
    return ray_tpu.get(s.coordinator.contribute.remote(
        "allgather", s.next_seq(), s.rank, tensor))


def reducescatter(tensor, op: str = "sum", group_name: str = "default"):
    s = _state(group_name)
    return ray_tpu.get(s.coordinator.contribute.remote(
        f"reducescatter:{op}", s.next_seq(), s.rank, tensor))


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    s = _state(group_name)
    return ray_tpu.get(s.coordinator.contribute.remote(
        f"broadcast:{src_rank}", s.next_seq(), s.rank, tensor))


def barrier(group_name: str = "default") -> None:
    s = _state(group_name)
    ray_tpu.get(s.coordinator.contribute.remote(
        "barrier", s.next_seq(), s.rank, None))


def send(tensor, dst_rank: int, group_name: str = "default") -> None:
    """Point-to-point send; matched with the peer's recv by a per-(src,dst)
    channel sequence (parity: reference collective.py:567-660)."""
    s = _state(group_name)
    seq = s.next_p2p_seq(s.rank, dst_rank)
    ray_tpu.get(s.coordinator.p2p_put.remote(
        (s.rank, dst_rank, seq), dst_rank, tensor))


def recv(src_rank: int, group_name: str = "default"):
    s = _state(group_name)
    seq = s.next_p2p_seq(src_rank, s.rank)
    return ray_tpu.get(s.coordinator.p2p_get.remote(
        (src_rank, s.rank, seq), s.rank))
