"""State API (reference: `python/ray/util/state/api.py` + `state_cli.py`
— programmatic cluster introspection over GCS/dashboard)."""

from ray_tpu.util.state.api import (list_actors, list_nodes, list_objects,
                                    list_placement_groups, list_tasks,
                                    summarize_tasks, timeline)

__all__ = ["list_tasks", "list_actors", "list_objects", "list_nodes",
           "list_placement_groups", "summarize_tasks", "timeline"]
