"""State API (reference: `python/ray/util/state/api.py` + `state_cli.py`
— programmatic cluster introspection over GCS/dashboard)."""

from ray_tpu.util.state.api import (cluster_profile, cluster_timeline,
                                    list_actors, list_nodes, list_objects,
                                    list_placement_groups, list_tasks,
                                    list_tasks_from_head, summarize_tasks,
                                    task_breakdown, timeline,
                                    timeline_from_head)

__all__ = ["list_tasks", "list_actors", "list_objects", "list_nodes",
           "list_placement_groups", "summarize_tasks", "timeline",
           "cluster_timeline", "cluster_profile", "task_breakdown",
           "list_tasks_from_head", "timeline_from_head"]
