"""Programmatic cluster state introspection.

Reference: `python/ray/util/state/api.py` (list_tasks/list_actors/
list_objects/list_nodes/list_placement_groups/summarize) backed by
GcsTaskManager / GCS tables; here backed directly by the runtime tables.
"""

from __future__ import annotations

from collections import Counter as _Counter
from typing import Any, Dict, List, Optional


def _rt():
    from ray_tpu._private import worker as _worker
    rt = _worker.global_runtime()
    if rt is None:
        raise RuntimeError("ray_tpu is not initialized")
    return rt


def list_tasks(*, filters: Optional[List] = None,
               limit: int = 1000) -> List[Dict[str, Any]]:
    """In-flight tasks from the live table + terminal tasks from the task
    event buffer (reference: GcsTaskManager keeps completed-task state;
    the in-flight table alone forgets finished tasks)."""
    rt = _rt()
    rows: Dict[str, Dict[str, Any]] = {}
    for ev in rt.task_events.events():
        if ev["event"] == "SPAN":
            continue    # latency spans are not state transitions
        row = rows.setdefault(ev["task_id"], {
            "task_id": ev["task_id"], "name": ev["name"],
            "state": ev["event"], "node_id": ev["node_id"] or None,
            "required_resources": {}})
        row["state"] = ev["event"]
        if ev["node_id"]:
            row["node_id"] = ev["node_id"]
    with rt._tasks_lock:
        items = list(rt._tasks.items())
    for task_id, t in items:
        rows[task_id.hex()] = {
            "task_id": task_id.hex(),
            "name": t.spec.name,
            "state": t.state.name if hasattr(t.state, "name") else
            str(t.state),
            "node_id": t.node_id.hex() if t.node_id else None,
            "required_resources": dict(t.spec.resources or {}),
        }
    return _apply_filters(list(rows.values())[:limit], filters)


def list_actors(*, filters: Optional[List] = None,
                limit: int = 1000) -> List[Dict[str, Any]]:
    rt = _rt()
    out = []
    for actor_id, info in list(rt.gcs.actors.items())[:limit]:
        out.append({
            "actor_id": actor_id.hex(),
            "class_name": getattr(info, "class_name", ""),
            "name": getattr(info, "name", None),
            "state": getattr(info, "state", ""),
            "node_id": (info.node_id.hex()
                        if getattr(info, "node_id", None) else None),
            "num_restarts": getattr(info, "num_restarts", 0),
        })
    return _apply_filters(out, filters)


def list_objects(*, limit: int = 1000) -> List[Dict[str, Any]]:
    rt = _rt()
    out = []
    with rt._loc_lock:
        locations = {oid: set(nodes) for oid, nodes
                     in rt._locations.items()}
    for oid in list(rt.memory_store.object_ids())[:limit]:
        out.append({"object_id": oid.hex(), "tier": "memory",
                    "locations": []})
    for oid, nodes in list(locations.items())[:limit]:
        out.append({"object_id": oid.hex(), "tier": "node_store",
                    "locations": [n.hex() for n in nodes]})
    return out[:limit]


def list_nodes() -> List[Dict[str, Any]]:
    rt = _rt()
    out = []
    for node_id, info in rt.gcs.nodes.items():
        out.append({
            "node_id": node_id.hex(),
            "alive": info.alive,
            "resources": dict(info.resources),
            "labels": dict(getattr(info, "labels", {}) or {}),
        })
    return out


def list_placement_groups() -> List[Dict[str, Any]]:
    rt = _rt()
    out = []
    for pg_id, pg in rt.gcs.placement_groups.items():
        out.append({
            "placement_group_id": pg_id.hex(),
            "state": getattr(pg, "state", ""),
            "strategy": getattr(pg, "strategy", ""),
            "bundles": [dict(b.resources) for b in pg.bundles],
        })
    return out


def summarize_tasks() -> Dict[str, int]:
    counts = _Counter(t["state"] for t in list_tasks(limit=100_000))
    return dict(counts)


def list_tasks_from_head(address: str, *, job_id: str = "",
                         limit: int = 10_000) -> List[Dict[str, Any]]:
    """Post-mortem task listing straight from the HEAD's task-event
    store (reference: gcs_task_manager.h:94) — works with no runtime in
    this process and after the submitting driver exited. ``address`` is
    the head's host:port."""
    from ray_tpu._private.head import HeadClient
    host, port = address.rsplit(":", 1)
    head = HeadClient((host, int(port)))
    try:
        events = head.task_events_get(job_id=job_id, limit=limit)
    finally:
        head.close()
    rows: Dict[str, Dict[str, Any]] = {}
    for ev in events:
        if ev.get("event") == "SPAN":
            continue    # latency spans are not state transitions
        row = rows.setdefault(ev["task_id"], {
            "task_id": ev["task_id"], "name": ev["name"],
            "state": ev["event"], "node_id": ev.get("node_id") or None,
            "job_id": ev.get("job_id", ""),
            "required_resources": {}})
        row["state"] = ev["event"]
        # placement is only known from RUNNING onward: keep the latest
        # non-empty node rather than the submission event's blank
        if ev.get("node_id"):
            row["node_id"] = ev["node_id"]
    return list(rows.values())


def timeline_from_head(address: str, path: Optional[str] = None,
                       *, job_id: str = "") -> Any:
    """Chrome-trace timeline rebuilt from the head's task-event store —
    post-mortem counterpart of :func:`timeline`. Includes per-phase span
    lanes from every process that flushed to the head."""
    import json as _json

    from ray_tpu._private.events import merged_chrome_trace
    from ray_tpu._private.head import HeadClient
    host, port = address.rsplit(":", 1)
    head = HeadClient((host, int(port)))
    try:
        events = head.task_events_get(job_id=job_id)
    finally:
        head.close()
    trace = merged_chrome_trace(events)
    if path:
        with open(path, "w") as f:
            _json.dump(trace, f)
        return path
    return trace


def timeline(path: Optional[str] = None) -> Any:
    """Chrome-trace dump of task events (reference: `ray timeline`)."""
    rt = _rt()
    if path is not None:
        return rt.task_events.dump_chrome_trace(path)
    return rt.task_events.chrome_trace()


def _gather_cluster_events() -> list:
    """Driver-local events merged with the head's store (daemon/worker
    spans land there via heartbeats), deduplicated — the driver's own
    events are also flushed to the head."""
    rt = _rt()
    events = list(rt.task_events.events())
    backend = getattr(rt, "cluster_backend", None)
    head = getattr(backend, "head", None)
    if head is not None:
        try:
            events += head.task_events_get()
        except Exception:
            pass
    seen = set()
    out = []
    for ev in events:
        key = (ev.get("proc", ""), ev.get("task_id"), ev.get("event"),
               ev.get("phase", ""), round(ev.get("wall_ts", 0.0), 6))
        if key in seen:
            continue
        seen.add(key)
        out.append(ev)
    return out


def cluster_timeline(path: Optional[str] = None) -> Any:
    """MERGED chrome trace across every process: one lane per recorder
    (driver / daemon:<node> / worker:<pid>), wall-clock timebase with
    the head's per-node clock correction applied at ingestion. The
    `ray-tpu timeline` CLI emits this view."""
    import json as _json

    from ray_tpu._private.events import merged_chrome_trace
    trace = merged_chrome_trace(_gather_cluster_events())
    if path is not None:
        with open(path, "w") as f:
            _json.dump(trace, f)
        return path
    return trace


def cluster_profile(duration_s: float = 1.0, *,
                    node: Optional[str] = None,
                    path: Optional[str] = None,
                    fmt: str = "speedscope") -> Dict[str, Any]:
    """Cluster-wide stack profile, one record per process (the
    ``ray-tpu profile`` backend): a burst on the driver, every
    in-process pool worker, and (daemon topology) a ``profile_burst``
    fan-out to each daemon + its workers, merged with the head's
    federated continuous aggregates. Returns ``{"records", "speedscope",
    "collapsed"}``; with ``path`` the chosen ``fmt`` ("speedscope" JSON
    or "collapsed" text) is also written there."""
    import threading as _threading

    from ray_tpu.util import profiling as _profiling
    rt = _rt()
    records: Dict[str, Dict[str, Any]] = {}

    def add(rec, replace=False):
        if isinstance(rec, dict) and rec.get("proc"):
            if replace or rec["proc"] not in records:
                records[rec["proc"]] = rec

    # daemon fan-out first (concurrent with the driver's own burst, so
    # the wall clock stays ~duration_s instead of 2x)
    backend = getattr(rt, "cluster_backend", None)
    daemons = dict(getattr(backend, "daemons", None) or {})
    if node:
        daemons = {nid: h for nid, h in daemons.items()
                   if nid.hex().startswith(node)}
    threads = []
    fanned: List[List[Dict[str, Any]]] = []
    for handle in daemons.values():
        def burst_one(handle=handle):
            try:
                fanned.append(handle.profile_burst(duration_s))
            except Exception:
                pass    # a dead daemon must not fail the profile
        t = _threading.Thread(target=burst_one, daemon=True)
        t.start()
        threads.append(t)
    # in-process pool workers (empty in the daemon topology)
    from ray_tpu._private import worker_process as _wp
    wthreads = []
    if not node:
        for w in _wp.live_workers():
            def wburst(w=w):
                add(w.profile_burst(duration_s), replace=True)
            t = _threading.Thread(target=wburst, daemon=True)
            t.start()
            wthreads.append(t)
        add(_profiling.burst_record("driver", duration_s=duration_s),
            replace=True)
    for t in threads + wthreads:
        t.join(timeout=duration_s + 15.0)
    for recs in fanned:
        for rec in recs:
            add(rec, replace=True)
    # continuous-mode leftovers: the driver's sampler, result-frame
    # worker ingests, and the head's federated per-node aggregates
    for rec in (_profiling.node_profile() or {}).get("procs", []):
        add(rec)
    head = getattr(backend, "head", None)
    if head is not None and not node:
        try:
            fed = head.profile_get()
            add(fed.get("head"))
            for payload in (fed.get("nodes") or {}).values():
                for rec in (payload or {}).get("procs", []):
                    add(rec)
        except Exception:
            pass
    recs = sorted(records.values(), key=lambda r: r.get("proc", ""))
    out = {"records": recs,
           "speedscope": _profiling.speedscope_document(recs),
           "collapsed": _profiling.merged_collapsed(recs)}
    if path is not None:
        import json as _json
        with open(path, "w") as f:
            if fmt == "collapsed":
                f.write(out["collapsed"] + "\n")
            else:
                _json.dump(out["speedscope"], f)
        out["path"] = path
    return out


def task_breakdown(task_id: str, *, address: Optional[str] = None
                   ) -> Dict[str, float]:
    """Per-phase latency vector for one task:
    ``{submit, linger, queue, dispatch, exec, result}`` seconds (0.0 for
    phases not recorded — e.g. no linger outside the batched wire path).
    With ``address`` the spans come from that head's store alone (post-
    mortem); otherwise from the live runtime + its head."""
    from ray_tpu._private.events import PHASES
    if address is not None:
        from ray_tpu._private.head import HeadClient
        host, port = address.rsplit(":", 1)
        head = HeadClient((host, int(port)))
        try:
            events = head.task_events_get()
        finally:
            head.close()
    else:
        events = _gather_cluster_events()
    out = {p: 0.0 for p in PHASES}
    for ev in events:
        if (ev.get("event") == "SPAN" and ev.get("task_id") == task_id
                and ev.get("phase") in out):
            out[ev["phase"]] = float(ev.get("dur_s", 0.0))
    return out


def _apply_filters(rows: List[Dict], filters: Optional[List]
                   ) -> List[Dict]:
    if not filters:
        return rows
    for key, op, value in filters:
        if op == "=":
            rows = [r for r in rows if str(r.get(key)) == str(value)]
        elif op == "!=":
            rows = [r for r in rows if str(r.get(key)) != str(value)]
    return rows
