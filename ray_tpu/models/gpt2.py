"""GPT-2 family — BASELINE.md config 2 (GPT-2 125M, 4-worker DP).

Reference capability: trained via TorchTrainer+DDP in the reference's
release tests; here a pjit data/tensor-parallel functional model (pre-LN,
learned positions, tied embeddings, GELU MLP).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import attention
from ray_tpu.ops.norms import layer_norm

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50_257
    dim: int = 768
    n_layers: int = 12
    n_heads: int = 12
    max_seq_len: int = 1024
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    remat: bool = True

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def ffn_dim(self) -> int:
        return 4 * self.dim

    @staticmethod
    def gpt2_125m() -> "GPT2Config":
        return GPT2Config()

    @staticmethod
    def debug() -> "GPT2Config":
        return GPT2Config(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                          max_seq_len=128, remat=False)

    def num_params(self) -> int:
        d, f = self.dim, self.ffn_dim
        per_layer = 4 * d * d + 2 * d * f + 4 * d + d + f + 2 * d
        return (self.vocab_size * d + self.max_seq_len * d
                + self.n_layers * per_layer + 2 * d)


def param_logical_axes(cfg: GPT2Config) -> Params:
    return {
        "wte": ("vocab", "embed_in"),
        "wpe": (None, "embed_in"),
        "layers": {
            "ln1_w": (None, "embed_in"), "ln1_b": (None, "embed_in"),
            "wqkv": (None, "embed_in", None, "heads", None),
            "bqkv": (None, None, "heads", None),
            "wo": (None, "heads", None, "embed_in"),
            "bo": (None, "embed_in"),
            "ln2_w": (None, "embed_in"), "ln2_b": (None, "embed_in"),
            "w_up": (None, "embed_in", "mlp"), "b_up": (None, "mlp"),
            "w_down": (None, "mlp", "embed_in"),
            "b_down": (None, "embed_in"),
        },
        "lnf_w": ("embed_in",), "lnf_b": ("embed_in",),
    }


class GPT2Model:
    def __init__(self, cfg: GPT2Config, mesh=None,
                 rules: Optional[Dict] = None):
        self.cfg = cfg
        self.mesh = mesh
        self.rules = rules

    def init(self, rng: jax.Array) -> Params:
        cfg = self.cfg
        d, hd, L = cfg.dim, cfg.head_dim, cfg.n_layers
        k = iter(jax.random.split(rng, 8))

        def dense(key, shape, fan_in):
            return jax.random.normal(key, shape, jnp.float32) * (
                fan_in ** -0.5)

        return {
            "wte": dense(next(k), (cfg.vocab_size, d), d),
            "wpe": dense(next(k), (cfg.max_seq_len, d), d) * 0.1,
            "layers": {
                "ln1_w": jnp.ones((L, d)), "ln1_b": jnp.zeros((L, d)),
                "wqkv": dense(next(k), (L, d, 3, cfg.n_heads, hd), d),
                "bqkv": jnp.zeros((L, 3, cfg.n_heads, hd)),
                "wo": dense(next(k), (L, cfg.n_heads, hd, d), d),
                "bo": jnp.zeros((L, d)),
                "ln2_w": jnp.ones((L, d)), "ln2_b": jnp.zeros((L, d)),
                "w_up": dense(next(k), (L, d, cfg.ffn_dim), d),
                "b_up": jnp.zeros((L, cfg.ffn_dim)),
                "w_down": dense(next(k), (L, cfg.ffn_dim, d), cfg.ffn_dim),
                "b_down": jnp.zeros((L, d)),
            },
            "lnf_w": jnp.ones((d,)), "lnf_b": jnp.zeros((d,)),
        }

    def param_shardings(self):
        from ray_tpu.parallel.mesh import named_sharding
        axes = param_logical_axes(self.cfg)
        return jax.tree.map(
            lambda names: named_sharding(self.mesh, *names,
                                         rules=self.rules),
            axes, is_leaf=lambda x: isinstance(x, tuple))

    def _block(self, x, layer):
        cfg = self.cfg
        dt = cfg.dtype
        h = layer_norm(x, layer["ln1_w"], layer["ln1_b"], eps=cfg.norm_eps)
        qkv = jnp.einsum("bsd,dthk->bsthk", h, layer["wqkv"].astype(dt))
        qkv = qkv + layer["bqkv"].astype(dt)
        q, kk, vv = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        o = attention(q, kk, vv, causal=True)
        o = jnp.einsum("bshk,hkd->bsd", o, layer["wo"].astype(dt))
        x = x + o + layer["bo"].astype(dt)
        h = layer_norm(x, layer["ln2_w"], layer["ln2_b"], eps=cfg.norm_eps)
        up = jnp.einsum("bsd,df->bsf", h, layer["w_up"].astype(dt))
        up = jax.nn.gelu(up + layer["b_up"].astype(dt))
        down = jnp.einsum("bsf,fd->bsd", up, layer["w_down"].astype(dt))
        return x + down + layer["b_down"].astype(dt)

    def apply(self, params: Params, tokens: jax.Array) -> jax.Array:
        cfg = self.cfg
        B, S = tokens.shape
        x = params["wte"].astype(cfg.dtype)[tokens]
        x = x + params["wpe"].astype(cfg.dtype)[:S][None]

        block = self._block
        if cfg.remat:
            block = jax.checkpoint(block)

        def scan_body(x, layer):
            return block(x, layer), None

        x, _ = jax.lax.scan(scan_body, x, params["layers"])
        x = layer_norm(x, params["lnf_w"], params["lnf_b"],
                       eps=cfg.norm_eps)
        logits = jnp.einsum("bsd,vd->bsv", x,
                            params["wte"].astype(cfg.dtype))  # tied head
        return logits.astype(jnp.float32)

    def loss(self, params: Params, tokens: jax.Array,
             targets: jax.Array) -> jax.Array:
        logits = self.apply(params, tokens)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(jnp.take_along_axis(
            logp, targets[..., None], axis=-1))
