"""Model families, TPU-first.

Pure-functional JAX models (param pytrees + logical sharding axes — no
framework lock-in), scan-over-layers for O(1) compile scaling, bfloat16
matmuls on the MXU, sharding expressed by logical axis names resolved
against the 6-axis mesh of ``ray_tpu.parallel.mesh``.

Coverage mirrors BASELINE.md target configs: Llama-3 family (flagship),
GPT-2, MLP (Fashion-MNIST baseline), ViT (ImageNet streaming).
"""

from ray_tpu.models.gpt2 import GPT2Config, GPT2Model
from ray_tpu.models.llama import LlamaConfig, LlamaModel
from ray_tpu.models.mlp import MLPConfig, MLPModel
from ray_tpu.models.moe import MoEConfig, MoEModel
from ray_tpu.models.vit import ViTConfig, ViTModel

__all__ = ["LlamaConfig", "LlamaModel", "MLPConfig", "MLPModel",
           "GPT2Config", "GPT2Model", "ViTConfig", "ViTModel",
           "MoEConfig", "MoEModel"]
