"""Llama-3 family, TPU-native.

Reference capability: Ray trains Llama via TorchTrainer+FSDP wrappers
(`release/train_tests/benchmark/train_benchmark.py`) and serves it via vLLM
(`python/ray/llm`) — the model itself lives outside the reference tree. Here
it is in-tree and TPU-first:

- params are a pytree of stacked-layer arrays; the transformer stack is a
  single ``lax.scan`` (one compiled block regardless of depth);
- every param/activation carries logical axis names resolved to the 6-axis
  mesh (dp/fsdp/pp/tp/sp/ep) by ``ray_tpu.parallel.mesh`` rules —
  Megatron-style TP, ZeRO-style fsdp sharding, ring-attention SP all come
  from the same annotations;
- compute dtype bfloat16 (MXU-native), params/optimizer f32;
- ``remat`` on each layer trades FLOPs for HBM (the standard TPU recipe).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import attention
from ray_tpu.ops.norms import rms_norm
from ray_tpu.ops.ring_attention import ring_attention
from ray_tpu.ops.rope import apply_rope, rope_frequencies

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128_256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14_336
    max_seq_len: int = 8192
    rope_theta: float = 500_000.0
    norm_eps: float = 1e-5
    tie_embeddings: bool = False
    dtype: Any = jnp.bfloat16
    remat: bool = True
    # remat policy: "full" recomputes everything (min HBM, +2N FLOPs);
    # "dots" saves matmul outputs (recompute only elementwise — near-6N
    # useful FLOPs at higher HBM); the standard TPU MFU/memory dial.
    remat_policy: str = "full"
    # Attention implementation (SURVEY §5.7):
    # "ring" = ppermute K/V rotation CP (any head count, O(S/sp) memory);
    # "ulysses" = all-to-all head/seq swap CP (needs n_heads % sp == 0,
    # local full-sequence attention so any local kernel applies);
    # "flash" = single-device Pallas flash kernel (ops/attention.py) —
    # the MFU path for sp==1 (bench default); interpret-mode on CPU;
    # "xla" = blockwise online-softmax in pure XLA (O(S·block) memory)
    # — the A/B baseline the Pallas kernel must beat.
    attention_impl: str = "ring"
    # Pallas flash tile sizes (the per-grid-step overhead vs VMEM dial)
    flash_block_q: int = 128
    flash_block_k: int = 128
    # KV-cache decode attention: "xla" masked fallback or the "pallas"
    # ragged kernel (skips KV blocks past each slot's length —
    # ops/decode_attention.py).
    decode_attention: str = "xla"

    def __post_init__(self):
        if self.attention_impl not in ("ring", "ulysses", "flash", "xla"):
            raise ValueError(
                f"attention_impl must be 'ring', 'ulysses', 'flash' or "
                f"'xla', got {self.attention_impl!r}")
        if self.remat_policy not in ("full", "dots"):
            raise ValueError(
                f"remat_policy must be 'full' or 'dots', "
                f"got {self.remat_policy!r}")
        if self.decode_attention not in ("xla", "pallas"):
            raise ValueError(
                f"decode_attention must be 'xla' or 'pallas', "
                f"got {self.decode_attention!r}")

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    def num_params(self) -> int:
        d, f, v = self.dim, self.ffn_dim, self.vocab_size
        kv = self.n_kv_heads * self.head_dim
        per_layer = d * d + 2 * d * kv + d * d + 3 * d * f + 2 * d
        heads = 0 if self.tie_embeddings else v * d
        return v * d + self.n_layers * per_layer + d + heads

    # -- presets (sizes match the public Llama-3 family) --
    @staticmethod
    def llama3_8b() -> "LlamaConfig":
        return LlamaConfig()

    @staticmethod
    def llama3_1b() -> "LlamaConfig":
        return LlamaConfig(dim=2048, n_layers=16, n_heads=32, n_kv_heads=8,
                           ffn_dim=8192)

    @staticmethod
    def bench_400m(max_seq_len: int = 2048) -> "LlamaConfig":
        """~440M params: sized so f32 params+adam+grads fit a 16GB chip.

        head_dim=128 (MXU tile width) so the Pallas flash kernel — the
        bench default — tiles cleanly onto the systolic array.
        """
        return LlamaConfig(vocab_size=32_000, dim=1024, n_layers=24,
                           n_heads=8, n_kv_heads=4, ffn_dim=4096,
                           max_seq_len=max_seq_len, attention_impl="flash")

    @staticmethod
    def debug(vocab_size: int = 256, max_seq_len: int = 128) -> "LlamaConfig":
        return LlamaConfig(vocab_size=vocab_size, dim=64, n_layers=2,
                           n_heads=4, n_kv_heads=2, ffn_dim=128,
                           max_seq_len=max_seq_len, remat=False)


# Logical axis names per param leaf (see parallel/mesh.py DEFAULT_RULES).
def param_logical_axes(cfg: LlamaConfig) -> Params:
    axes = {
        "embed": ("vocab", "embed_in"),
        "layers": {
            "attn_norm": (None, "embed_in"),
            "wq": (None, "embed_in", "heads", None),
            "wk": (None, "embed_in", "kv_heads", None),
            "wv": (None, "embed_in", "kv_heads", None),
            "wo": (None, "heads", None, "embed_in"),
            "mlp_norm": (None, "embed_in"),
            "w_gate": (None, "embed_in", "mlp"),
            "w_up": (None, "embed_in", "mlp"),
            "w_down": (None, "mlp", "embed_in"),
        },
        "norm_f": ("embed_in",),
    }
    if not cfg.tie_embeddings:
        axes["lm_head"] = ("embed_in", "vocab")
    return axes


class LlamaModel:
    """Functional model: ``init`` makes params, ``apply`` runs the forward.

    ``mesh``/``rules`` (optional) activate sharding constraints on
    activations and select ring attention when the sp axis is >1.
    """

    def __init__(self, cfg: LlamaConfig, mesh=None,
                 rules: Optional[Dict] = None):
        self.cfg = cfg
        self.mesh = mesh
        self.rules = rules
        self._sp = 1 if mesh is None else mesh.shape.get("sp", 1)
        if self._sp > 1 and cfg.attention_impl == "flash":
            raise ValueError(
                "attention_impl='flash' is a single-device kernel; with an "
                "sp>1 mesh use 'ring' or 'ulysses' context parallelism")
        self._angles = rope_frequencies(cfg.head_dim, cfg.max_seq_len,
                                        theta=cfg.rope_theta)

    # -- init ---------------------------------------------------------------
    def init(self, rng: jax.Array) -> Params:
        cfg = self.cfg
        d, hd = cfg.dim, cfg.head_dim
        k = iter(jax.random.split(rng, 16))

        def dense(key, shape, fan_in):
            return (jax.random.normal(key, shape, jnp.float32)
                    * (fan_in ** -0.5))

        L = cfg.n_layers
        params: Params = {
            "embed": dense(next(k), (cfg.vocab_size, d), d),
            "layers": {
                "attn_norm": jnp.ones((L, d), jnp.float32),
                "wq": dense(next(k), (L, d, cfg.n_heads, hd), d),
                "wk": dense(next(k), (L, d, cfg.n_kv_heads, hd), d),
                "wv": dense(next(k), (L, d, cfg.n_kv_heads, hd), d),
                "wo": dense(next(k), (L, cfg.n_heads, hd, d), d),
                "mlp_norm": jnp.ones((L, d), jnp.float32),
                "w_gate": dense(next(k), (L, d, cfg.ffn_dim), d),
                "w_up": dense(next(k), (L, d, cfg.ffn_dim), d),
                "w_down": dense(next(k), (L, cfg.ffn_dim, d), cfg.ffn_dim),
            },
            "norm_f": jnp.ones((d,), jnp.float32),
        }
        if not cfg.tie_embeddings:
            params["lm_head"] = dense(next(k), (d, cfg.vocab_size), d)
        return params

    # -- sharding helpers ---------------------------------------------------
    def _constrain(self, x, *names):
        if self.mesh is None:
            return x
        from ray_tpu.parallel.mesh import shard_constraint
        return shard_constraint(x, self.mesh, *names, rules=self.rules)

    def param_shardings(self):
        """NamedSharding pytree for params (pass to jit in_shardings)."""
        from ray_tpu.parallel.mesh import named_sharding
        axes = param_logical_axes(self.cfg)
        return jax.tree.map(
            lambda names: named_sharding(self.mesh, *names,
                                         rules=self.rules),
            axes, is_leaf=lambda x: isinstance(x, tuple))

    # -- forward ------------------------------------------------------------
    def _embed_lookup(self, table: jax.Array, tokens: jax.Array) -> jax.Array:
        """Vocab-parallel embedding lookup.

        The table is vocab-sharded over tp; a plain gather forces XLA into
        "involuntary full rematerialization" (replicate + repartition) of
        the table. Megatron-style instead: each tp shard looks up only
        tokens in its vocab range and a psum combines — communication is
        one all-reduce of [B,S,D] activations, never the table.
        """
        mesh = self.mesh
        if mesh is None or mesh.shape.get("tp", 1) == 1:
            return table[tokens]
        from jax.sharding import PartitionSpec as P

        from ray_tpu.parallel.mesh import shard_map_compat

        present = set(mesh.shape.keys())
        sp = mesh.shape.get("sp", 1)
        # decode steps carry T=1 (or odd prefill lengths): only shard the
        # seq dim when it actually divides over sp
        seq_ax = ("sp" if "sp" in present and sp > 1
                  and tokens.shape[1] % sp == 0 else None)
        # The table keeps BOTH its shardings inside the shard_map (vocab
        # over tp, embed dim over fsdp) so no table bytes ever move; each
        # fsdp rank looks up its D-slice for the dp batch shard, and the
        # follow-up _constrain reshards only the [B,S,D] activations.
        dp_ax = "dp" if "dp" in present else None
        fsdp_ax = "fsdp" if "fsdp" in present else None
        vshard = self.cfg.vocab_size // mesh.shape["tp"]

        def lookup(table_local, tok):
            start = jax.lax.axis_index("tp") * vshard
            local = tok - start
            valid = (local >= 0) & (local < vshard)
            safe = jnp.where(valid, local, 0)
            out = table_local[safe] * valid[..., None].astype(
                table_local.dtype)
            return jax.lax.psum(out, "tp")

        fn = shard_map_compat(
            lookup, mesh,
            (P("tp", fsdp_ax), P(dp_ax, seq_ax)),
            P(dp_ax, seq_ax, fsdp_ax))
        return fn(table, tokens)

    def _attention(self, q, k, v, positions):
        if self._sp > 1:
            if positions is not None:
                raise NotImplementedError(
                    "explicit positions are not supported with sp>1: the "
                    "context-parallel causal mask assumes contiguous "
                    "0..S-1")
            # Inside pjit the arrays are globally-shaped; shard_map splits
            # them per-device and runs the collective scheme over ICI.
            if self.cfg.attention_impl == "ulysses":
                from ray_tpu.ops.ulysses import ulysses_attention_sharded
                return ulysses_attention_sharded(q, k, v, self.mesh,
                                                 causal=True)
            from ray_tpu.ops.ring_attention import ring_attention_sharded
            return ring_attention_sharded(q, k, v, self.mesh, causal=True)
        # sp==1: "flash" forces the Pallas kernel (interpret-mode
        # off-TPU) with the config's tile sizes; "xla" forces the
        # blockwise online-softmax fallback; otherwise the dispatcher
        # auto-selects by platform/shape.
        cfg = self.cfg
        if cfg.attention_impl == "flash" and positions is None:
            from ray_tpu.ops.attention import flash_attention
            # positional: custom_vjp functions reject keyword args
            return flash_attention(q, k, v, True, cfg.flash_block_q,
                                   cfg.flash_block_k)
        if cfg.attention_impl == "xla" and positions is None:
            from ray_tpu.ops.attention import blockwise_attention
            return blockwise_attention(q, k, v, causal=True)
        return attention(q, k, v, causal=True, positions_q=positions,
                         positions_k=positions, use_flash=None)

    def _block(self, x, layer: Params, positions):
        cfg = self.cfg
        dt = cfg.dtype
        h = rms_norm(x, layer["attn_norm"], eps=cfg.norm_eps)
        q = jnp.einsum("bsd,dhk->bshk", h, layer["wq"].astype(dt))
        kk = jnp.einsum("bsd,dhk->bshk", h, layer["wk"].astype(dt))
        vv = jnp.einsum("bsd,dhk->bshk", h, layer["wv"].astype(dt))
        q = self._constrain(q, "batch", "seq", "heads", None)
        q = apply_rope(q, self._angles, positions)
        kk = apply_rope(kk, self._angles, positions)
        o = self._attention(q, kk, vv, positions)
        o = jnp.einsum("bshk,hkd->bsd", o, layer["wo"].astype(dt))
        x = x + self._constrain(o, "batch", "seq", "embed")

        h = rms_norm(x, layer["mlp_norm"], eps=cfg.norm_eps)
        gate = jnp.einsum("bsd,df->bsf", h, layer["w_gate"].astype(dt))
        up = jnp.einsum("bsd,df->bsf", h, layer["w_up"].astype(dt))
        ff = jax.nn.silu(gate) * up
        ff = self._constrain(ff, "batch", "seq", "mlp")
        down = jnp.einsum("bsf,fd->bsd", ff, layer["w_down"].astype(dt))
        return x + self._constrain(down, "batch", "seq", "embed")

    def apply(self, params: Params, tokens: jax.Array,
              positions: Optional[jax.Array] = None) -> jax.Array:
        """tokens [B, S] int32 -> logits [B, S, V] (f32)."""
        cfg = self.cfg
        x = self._embed_lookup(params["embed"].astype(cfg.dtype), tokens)
        x = self._constrain(x, "batch", "seq", "embed")

        block = self._block
        if cfg.remat:
            if cfg.remat_policy == "dots":
                block = jax.checkpoint(
                    block, policy=jax.checkpoint_policies.dots_saveable)
            else:
                block = jax.checkpoint(block, static_argnums=())

        def scan_body(x, layer):
            return block(x, layer, positions), None

        x, _ = jax.lax.scan(scan_body, x, params["layers"])
        x = rms_norm(x, params["norm_f"], eps=cfg.norm_eps)
        head = (params["embed"].T if cfg.tie_embeddings
                else params["lm_head"])
        logits = jnp.einsum("bsd,dv->bsv", x, head.astype(cfg.dtype))
        logits = self._constrain(logits, "batch", "seq", "vocab")
        return logits.astype(jnp.float32)

    # -- KV-cache inference path (serving; BASELINE.md config 5) ----------
    def init_kv_cache(self, batch: int, max_seq: int) -> Params:
        """Slot-major cache: [L, B, S, Hkv, D] per k/v, bf16 in HBM."""
        cfg = self.cfg
        shape = (cfg.n_layers, batch, max_seq, cfg.n_kv_heads, cfg.head_dim)
        return {"k": jnp.zeros(shape, cfg.dtype),
                "v": jnp.zeros(shape, cfg.dtype)}

    def forward_step(self, params: Params, tokens: jax.Array,
                     cache: Params, offsets: jax.Array
                     ) -> Tuple[jax.Array, Params]:
        """Unified prefill/decode step with KV cache.

        tokens  [B, T] — T = padded prompt length (prefill) or 1 (decode)
        offsets [B]    — how many tokens each slot has already cached
        Returns (logits [B, T, V], updated cache). Static shapes: the same
        jit specialization serves every request of a given (B, T, S).
        """
        cfg = self.cfg
        B, T = tokens.shape
        S = cache["k"].shape[2]
        q_pos = offsets[:, None] + jnp.arange(T)[None, :]        # [B, T]
        x = self._embed_lookup(params["embed"].astype(cfg.dtype), tokens)

        batch_idx = jnp.arange(B)[:, None]

        def block(carry, layer_and_cache):
            x = carry
            layer, k_cache, v_cache = layer_and_cache
            dt = cfg.dtype
            h = rms_norm(x, layer["attn_norm"], eps=cfg.norm_eps)
            q = jnp.einsum("bsd,dhk->bshk", h, layer["wq"].astype(dt))
            k_new = jnp.einsum("bsd,dhk->bshk", h, layer["wk"].astype(dt))
            v_new = jnp.einsum("bsd,dhk->bshk", h, layer["wv"].astype(dt))
            q = apply_rope(q, self._angles, q_pos)
            k_new = apply_rope(k_new, self._angles, q_pos)
            # scatter new k/v into the cache at each slot's write offsets
            k_cache = k_cache.at[batch_idx, q_pos].set(k_new)
            v_cache = v_cache.at[batch_idx, q_pos].set(v_new)
            if T == 1 and cfg.decode_attention == "pallas":
                # single-token decode: ragged kernel skips KV blocks past
                # each slot's live length
                from ray_tpu.ops.decode_attention import \
                    ragged_decode_attention_pallas
                o = ragged_decode_attention_pallas(
                    q[:, 0], k_cache, v_cache, q_pos[:, 0] + 1)[:, None]
            else:
                # attend over cache positions <= own position
                from ray_tpu.ops.attention import NEG_INF, _repeat_kv
                kk = _repeat_kv(k_cache, cfg.n_heads)
                vv = _repeat_kv(v_cache, cfg.n_heads)
                s = jnp.einsum("bthd,bshd->bhts", q, kk,
                               preferred_element_type=jnp.float32)
                s = s * (cfg.head_dim ** -0.5)
                mask = (jnp.arange(S)[None, None, :] <= q_pos[:, :, None])
                s = jnp.where(mask[:, None], s, NEG_INF)
                p = jax.nn.softmax(s, axis=-1)
                o = jnp.einsum("bhts,bshd->bthd", p.astype(dt), vv)
            o = jnp.einsum("bshk,hkd->bsd", o, layer["wo"].astype(dt))
            x = x + o
            h = rms_norm(x, layer["mlp_norm"], eps=cfg.norm_eps)
            gate = jnp.einsum("bsd,df->bsf", h, layer["w_gate"].astype(dt))
            up = jnp.einsum("bsd,df->bsf", h, layer["w_up"].astype(dt))
            down = jnp.einsum("bsf,fd->bsd", jax.nn.silu(gate) * up,
                              layer["w_down"].astype(dt))
            return x + down, (k_cache, v_cache)

        x, (k_out, v_out) = jax.lax.scan(
            block, x, (params["layers"], cache["k"], cache["v"]))
        x = rms_norm(x, params["norm_f"], eps=cfg.norm_eps)
        head = (params["embed"].T if cfg.tie_embeddings
                else params["lm_head"])
        logits = jnp.einsum("bsd,dv->bsv", x, head.astype(cfg.dtype))
        return logits.astype(jnp.float32), {"k": k_out, "v": v_out}

    # -- paged KV-cache path (llm/engine.py + llm/paged_cache.py) ---------
    def init_kv_pool(self, num_blocks: int, block_size: int) -> Params:
        """Block-pool cache: k/v [L, num_blocks, block_size, Hkv, D],
        bf16 in HBM, shared by every slot via per-slot block tables."""
        cfg = self.cfg
        shape = (cfg.n_layers, num_blocks, block_size, cfg.n_kv_heads,
                 cfg.head_dim)
        return {"k": jnp.zeros(shape, cfg.dtype),
                "v": jnp.zeros(shape, cfg.dtype)}

    def decode_step_paged(self, params: Params, tokens: jax.Array,
                          pool: Params, block_tables: jax.Array,
                          offsets: jax.Array
                          ) -> Tuple[jax.Array, Params]:
        """One decode step for every slot against the block pool.

        tokens [B] int32 (each slot's last sampled token)
        pool   k/v [L, NB, bs, Hkv, D]
        block_tables [B, MAXB] int32 physical ids (logical order)
        offsets [B] tokens already cached per slot
        Returns (logits [B, V], updated pool). Slots whose table rows
        point at garbage simply compute garbage that the engine masks.
        """
        cfg = self.cfg
        bs = pool["k"].shape[2]
        dest_block = jnp.take_along_axis(
            block_tables, (offsets // bs)[:, None], axis=1)[:, 0]  # [B]
        dest_off = offsets % bs
        lengths = offsets + 1
        q_pos = offsets[:, None]                                   # [B, 1]
        x = self._embed_lookup(params["embed"].astype(cfg.dtype),
                               tokens[:, None])                    # [B,1,D]
        impl = "pallas" if cfg.decode_attention == "pallas" else "xla"
        from ray_tpu.ops.paged_attention import paged_decode_attention

        def block(carry, layer_and_pool):
            x = carry
            layer, k_pool, v_pool = layer_and_pool
            dt = cfg.dtype
            h = rms_norm(x, layer["attn_norm"], eps=cfg.norm_eps)
            q = jnp.einsum("bsd,dhk->bshk", h, layer["wq"].astype(dt))
            k_new = jnp.einsum("bsd,dhk->bshk", h, layer["wk"].astype(dt))
            v_new = jnp.einsum("bsd,dhk->bshk", h, layer["wv"].astype(dt))
            q = apply_rope(q, self._angles, q_pos)
            k_new = apply_rope(k_new, self._angles, q_pos)
            # each slot writes its own private tail block (refcount 1 —
            # shared prefix blocks are never write targets)
            k_pool = k_pool.at[dest_block, dest_off].set(
                k_new[:, 0].astype(dt))
            v_pool = v_pool.at[dest_block, dest_off].set(
                v_new[:, 0].astype(dt))
            o = paged_decode_attention(q[:, 0], k_pool, v_pool,
                                       block_tables, lengths, impl=impl)
            o = jnp.einsum("bhk,hkd->bd", o, layer["wo"].astype(dt))
            x = x + o[:, None]
            h = rms_norm(x, layer["mlp_norm"], eps=cfg.norm_eps)
            gate = jnp.einsum("bsd,df->bsf", h, layer["w_gate"].astype(dt))
            up = jnp.einsum("bsd,df->bsf", h, layer["w_up"].astype(dt))
            down = jnp.einsum("bsf,fd->bsd", jax.nn.silu(gate) * up,
                              layer["w_down"].astype(dt))
            return x + down, (k_pool, v_pool)

        x, (k_out, v_out) = jax.lax.scan(
            block, x, (params["layers"], pool["k"], pool["v"]))
        x = rms_norm(x, params["norm_f"], eps=cfg.norm_eps)
        head = (params["embed"].T if cfg.tie_embeddings
                else params["lm_head"])
        logits = jnp.einsum("bsd,dv->bsv", x, head.astype(cfg.dtype))
        return logits[:, 0].astype(jnp.float32), {"k": k_out, "v": v_out}

    def prefill_with_prefix(self, params: Params, tokens: jax.Array,
                            prefix_k: jax.Array, prefix_v: jax.Array,
                            prefix_len: jax.Array, lengths: jax.Array
                            ) -> Tuple[jax.Array, Params]:
        """Suffix prefill attending over a cached (shared) prefix.

        tokens   [N, Tb] suffix tokens (right-padded)
        prefix_k/v [L, N, Pmax, Hkv, D] dense prefix K/V gathered from
                 the pool, right-padded past ``prefix_len``
        prefix_len [N] valid prefix tokens
        lengths  [N] valid suffix tokens
        Returns (last-token logits [N, V], suffix K/V [L, N, Tb, Hkv, D])
        — the caller scatters the suffix K/V into fresh pool blocks; the
        prefix blocks are never copied or rewritten (prefix-reuse skips
        their FLOPs entirely).
        """
        cfg = self.cfg
        N, Tb = tokens.shape
        Pmax = prefix_k.shape[2]
        dt = cfg.dtype
        # absolute positions: suffix token t sits at prefix_len + t;
        # padded prefix rows get a position PAST every query so the
        # causal mask drops them
        pos_q = prefix_len[:, None] + jnp.arange(Tb)[None, :]       # [N,Tb]
        far = jnp.int32(2 ** 30)
        pos_prefix = jnp.where(
            jnp.arange(Pmax)[None, :] < prefix_len[:, None],
            jnp.arange(Pmax)[None, :], far)                          # [N,Pmax]
        x = self._embed_lookup(params["embed"].astype(dt), tokens)

        from ray_tpu.ops.attention import NEG_INF, _repeat_kv

        def block(carry, layer_and_prefix):
            x = carry
            layer, kp, vp = layer_and_prefix       # kp/vp [N, Pmax, Hkv, D]
            h = rms_norm(x, layer["attn_norm"], eps=cfg.norm_eps)
            q = jnp.einsum("bsd,dhk->bshk", h, layer["wq"].astype(dt))
            k_new = jnp.einsum("bsd,dhk->bshk", h, layer["wk"].astype(dt))
            v_new = jnp.einsum("bsd,dhk->bshk", h, layer["wv"].astype(dt))
            q = apply_rope(q, self._angles, pos_q)
            k_new = apply_rope(k_new, self._angles, pos_q)
            k_all = jnp.concatenate([kp.astype(dt), k_new], axis=1)
            v_all = jnp.concatenate([vp.astype(dt), v_new], axis=1)
            pos_k = jnp.concatenate(
                [pos_prefix, pos_q], axis=1)                        # [N,P+Tb]
            # per-row positions (prefix_len varies by row) — masked
            # attention inline; padded prefix rows have pos_k=2^30 so
            # the causal test drops them
            kk = _repeat_kv(k_all, cfg.n_heads)
            vv = _repeat_kv(v_all, cfg.n_heads)
            s = jnp.einsum("bqhd,bkhd->bhqk", q, kk,
                           preferred_element_type=jnp.float32)
            s = s * (cfg.head_dim ** -0.5)
            mask = pos_q[:, None, :, None] >= pos_k[:, None, None, :]
            s = jnp.where(mask, s, NEG_INF)
            p = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(dt), vv)
            o = jnp.einsum("bshk,hkd->bsd", o, layer["wo"].astype(dt))
            x = x + o
            h = rms_norm(x, layer["mlp_norm"], eps=cfg.norm_eps)
            gate = jnp.einsum("bsd,df->bsf", h, layer["w_gate"].astype(dt))
            up = jnp.einsum("bsd,df->bsf", h, layer["w_up"].astype(dt))
            down = jnp.einsum("bsf,fd->bsd", jax.nn.silu(gate) * up,
                              layer["w_down"].astype(dt))
            return x + down, (k_new, v_new)

        x, (k_out, v_out) = jax.lax.scan(
            block, x, (params["layers"], prefix_k, prefix_v))
        x = rms_norm(x, params["norm_f"], eps=cfg.norm_eps)
        head = (params["embed"].T if cfg.tie_embeddings
                else params["lm_head"])
        last = jnp.take_along_axis(x, (lengths - 1)[:, None, None],
                                   axis=1)[:, 0]                    # [N, D]
        logits = jnp.einsum("bd,dv->bv", last, head.astype(dt))
        return logits.astype(jnp.float32), {"k": k_out, "v": v_out}

    def loss(self, params: Params, tokens: jax.Array,
             targets: jax.Array,
             mask: Optional[jax.Array] = None) -> jax.Array:
        """Mean next-token cross-entropy."""
        logits = self.apply(params, tokens)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None],
                                   axis=-1).squeeze(-1)
        if mask is not None:
            return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1)
        return jnp.mean(nll)
