"""Pipeline-parallel Llama: the flagship model on a real ``pp`` mesh axis.

Reference capability: the reference expresses pipeline parallelism as a
compiled actor DAG with NCCL channels and an explicit tick schedule
(``python/ray/dag/compiled_dag_node.py:809``, schedule construction
``python/ray/dag/dag_node_operation.py:14-24``). TPU-first shape: the
schedule is DATA, not actors — the stacked layer params get a leading
``[num_stages, layers_per_stage, ...]`` dim sharded over the ``pp`` mesh
axis, and the GPipe fill/drain schedule is the ``lax.scan`` +
``lax.ppermute`` program in ``ray_tpu.parallel.pipeline``. Autodiff
through the scan IS the backward pipeline schedule; XLA overlaps the
neighbor ppermute with stage compute over ICI.

Composition (the classic 3D recipe):
  - ``pp``    — stages (this module)
  - ``dp``/``fsdp`` — batch axes for the microbatches (both act as plain
    data parallelism here: inside the stage shard_map weights are NOT
    fsdp-sharded — ZeRO resharding of stage-local weights would need
    per-leaf all-gathers in the stage body)
  - ``tp``    — Megatron tensor parallelism INSIDE each stage: head-dim
    sharded qkv/wo, ffn-dim sharded gate/up/down, with the two psums per
    block placed exactly where GSPMD would put them (shard_map makes the
    collectives explicit)
  - ``sp``/``ep`` must be 1 (ring/Ulysses CP and MoE dispatch compose
    with GSPMD in ``LlamaModel``/``MoEModel``, not the shard_map stage)

Embedding lookup and the LM head run OUTSIDE the pipelined section under
GSPMD (replicated over pp, tp-sharded via the vocab-parallel lookup), so
the stage contract stays ``y.shape == x.shape`` at every boundary.
"""

from __future__ import annotations

import dataclasses
from typing import Dict

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu.models.llama import LlamaConfig, LlamaModel, Params
from ray_tpu.ops.attention import attention
from ray_tpu.ops.norms import rms_norm
from ray_tpu.ops.rope import apply_rope, rope_frequencies


def stack_stages(params: Params, num_stages: int) -> Params:
    """Reshape the stacked-layer leaves [L, ...] -> [S, L/S, ...].

    Stage s holds layers ``s*L/S .. (s+1)*L/S - 1`` — the same order the
    un-pipelined ``lax.scan`` applies them, so a ``LlamaModel`` checkpoint
    restacks losslessly in either direction."""
    out = dict(params)
    out["layers"] = jax.tree.map(
        lambda p: p.reshape((num_stages, p.shape[0] // num_stages)
                            + p.shape[1:]),
        params["layers"])
    return out


def unstack_stages(params: Params) -> Params:
    """Inverse of :func:`stack_stages` (for checkpoint interop)."""
    out = dict(params)
    out["layers"] = jax.tree.map(
        lambda p: p.reshape((p.shape[0] * p.shape[1],) + p.shape[2:]),
        params["layers"])
    return out


# Per-leaf PartitionSpecs for the [S, l, ...] stage weights: leading dim
# over pp, Megatron tp on the head/ffn dims, everything else replicated
# (see module docstring for why fsdp stays off stage weights).
_STAGE_SPECS: Dict[str, P] = {
    "attn_norm": P("pp", None, None),
    "wq": P("pp", None, None, "tp", None),
    "wk": P("pp", None, None, "tp", None),
    "wv": P("pp", None, None, "tp", None),
    "wo": P("pp", None, "tp", None, None),
    "mlp_norm": P("pp", None, None),
    "w_gate": P("pp", None, None, "tp"),
    "w_up": P("pp", None, None, "tp"),
    "w_down": P("pp", None, "tp", None),
}


class PipelinedLlama:
    """Stage-split Llama driven by the GPipe microbatch schedule.

    Exposes the same functional surface as ``LlamaModel`` (``init`` /
    ``apply`` / ``loss`` / ``param_shardings``) so ``make_train_step``,
    the JaxTrainer and the dryrun drive it unchanged.

    Reference parity contract: same forward math as ``LlamaModel`` —
    ``tests/test_pipeline_llama.py`` asserts loss parity with pp=1.
    """

    def __init__(self, cfg: LlamaConfig, mesh, *,
                 num_microbatches: int = 2):
        self.cfg = cfg
        self.mesh = mesh
        self.num_microbatches = num_microbatches
        self.num_stages = mesh.shape.get("pp", 1)
        if self.num_stages < 2:
            raise ValueError(
                f"PipelinedLlama needs a pp>=2 mesh axis, got "
                f"pp={self.num_stages}; use LlamaModel for pp=1")
        if cfg.n_layers % self.num_stages != 0:
            raise ValueError(
                f"n_layers={cfg.n_layers} not divisible by "
                f"pp={self.num_stages}")
        if mesh.shape.get("sp", 1) != 1 or mesh.shape.get("ep", 1) != 1:
            raise ValueError(
                "PipelinedLlama composes pp x dp x fsdp x tp; sp/ep must "
                "be 1 (context parallelism lives in LlamaModel's GSPMD "
                "path)")
        tp = mesh.shape.get("tp", 1)
        if cfg.n_heads % tp or cfg.n_kv_heads % tp or cfg.ffn_dim % tp:
            raise ValueError(
                f"n_heads/n_kv_heads/ffn_dim must divide tp={tp}")
        self._tp = tp
        # the un-pipelined twin supplies init + the vocab-parallel
        # embedding lookup and activation constraints
        base_cfg = cfg if cfg.attention_impl != "flash" else \
            dataclasses.replace(cfg, attention_impl="ring")
        self._base = LlamaModel(base_cfg, mesh=mesh)
        self._angles = rope_frequencies(cfg.head_dim, cfg.max_seq_len,
                                        theta=cfg.rope_theta)

    # -- init / shardings --------------------------------------------------
    def init(self, rng: jax.Array) -> Params:
        return stack_stages(self._base.init(rng), self.num_stages)

    def param_shardings(self):
        base = self._base.param_shardings()
        out = dict(base)
        out["layers"] = {
            name: NamedSharding(self.mesh, _STAGE_SPECS[name])
            for name in base["layers"]}
        return out

    # -- stage body (runs INSIDE shard_map: collectives are manual) --------
    def _stage_fn(self, local_layers: Params, x: jax.Array) -> jax.Array:
        cfg = self.cfg
        dt = cfg.dtype
        angles = self._angles

        def block(x, layer):
            h = rms_norm(x, layer["attn_norm"], eps=cfg.norm_eps)
            q = jnp.einsum("bsd,dhk->bshk", h, layer["wq"].astype(dt))
            kk = jnp.einsum("bsd,dhk->bshk", h, layer["wk"].astype(dt))
            vv = jnp.einsum("bsd,dhk->bshk", h, layer["wv"].astype(dt))
            q = apply_rope(q, angles)
            kk = apply_rope(kk, angles)
            # local heads only (tp shards the head dim); the kernel
            # dispatcher picks flash on TPU when shapes tile
            o = attention(q, kk, vv, causal=True)
            o = jnp.einsum("bshk,hkd->bsd", o, layer["wo"].astype(dt))
            # Megatron psum #1: wo is row-sharded over tp
            o = jax.lax.psum(o, "tp")
            x = x + o
            h = rms_norm(x, layer["mlp_norm"], eps=cfg.norm_eps)
            gate = jnp.einsum("bsd,df->bsf", h, layer["w_gate"].astype(dt))
            up = jnp.einsum("bsd,df->bsf", h, layer["w_up"].astype(dt))
            down = jnp.einsum("bsf,fd->bsd", jax.nn.silu(gate) * up,
                              layer["w_down"].astype(dt))
            # Megatron psum #2: w_down is row-sharded over tp
            return x + jax.lax.psum(down, "tp")

        if cfg.remat:
            block = jax.checkpoint(block)

        def scan_body(x, layer):
            return block(x, layer), None

        y, _ = jax.lax.scan(scan_body, x, local_layers)
        return y

    # -- forward -----------------------------------------------------------
    def apply(self, params: Params, tokens: jax.Array) -> jax.Array:
        """tokens [B, S] int32 -> logits [B, S, V] (f32)."""
        from ray_tpu.parallel.pipeline import pipelined

        cfg = self.cfg
        B = tokens.shape[0]
        if B % self.num_microbatches:
            raise ValueError(
                f"batch {B} not divisible by num_microbatches="
                f"{self.num_microbatches}")
        x = self._base._embed_lookup(params["embed"].astype(cfg.dtype),
                                     tokens)
        x = self._base._constrain(x, "batch", None, "embed")

        param_specs = {name: _STAGE_SPECS[name]
                       for name in params["layers"]}
        run = pipelined(self._stage_fn, self.mesh,
                        num_microbatches=self.num_microbatches,
                        param_specs=param_specs)
        x = run(params["layers"], x)

        x = rms_norm(x, params["norm_f"], eps=cfg.norm_eps)
        head = (params["embed"].T if cfg.tie_embeddings
                else params["lm_head"])
        logits = jnp.einsum("bsd,dv->bsv", x, head.astype(cfg.dtype))
        logits = self._base._constrain(logits, "batch", None, "vocab")
        return logits.astype(jnp.float32)

    # identical objective, routed through the pipelined apply (the
    # base implementation only touches self.apply)
    loss = LlamaModel.loss
