"""MLP classifier — BASELINE.md config 1 (Fashion-MNIST DDP baseline).

The reference trains this via TorchTrainer+gloo over 2 CPU workers
(`python/ray/train/examples`); here the same capability is a pjit
data-parallel program over a dp mesh axis.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class MLPConfig:
    in_dim: int = 784
    hidden: Sequence[int] = (512, 512)
    num_classes: int = 10
    dtype: Any = jnp.float32


class MLPModel:
    def __init__(self, cfg: MLPConfig, mesh=None):
        self.cfg = cfg
        self.mesh = mesh

    def init(self, rng: jax.Array) -> Params:
        dims = [self.cfg.in_dim, *self.cfg.hidden, self.cfg.num_classes]
        params = []
        keys = jax.random.split(rng, len(dims) - 1)
        for k, d_in, d_out in zip(keys, dims[:-1], dims[1:]):
            params.append({
                "w": jax.random.normal(k, (d_in, d_out), jnp.float32)
                * (2.0 / d_in) ** 0.5,
                "b": jnp.zeros((d_out,), jnp.float32),
            })
        return {"layers": params}

    def apply(self, params: Params, x: jax.Array) -> jax.Array:
        x = x.astype(self.cfg.dtype)
        layers = params["layers"]
        for layer in layers[:-1]:
            x = jax.nn.relu(x @ layer["w"] + layer["b"])
        last = layers[-1]
        return x @ last["w"] + last["b"]

    def loss(self, params: Params, x: jax.Array,
             labels: jax.Array) -> jax.Array:
        logits = self.apply(params, x)
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(
            jnp.take_along_axis(logp, labels[:, None], axis=-1))

    def accuracy(self, params: Params, x: jax.Array,
                 labels: jax.Array) -> jax.Array:
        return jnp.mean(jnp.argmax(self.apply(params, x), -1) == labels)
