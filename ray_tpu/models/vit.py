"""Vision Transformer — BASELINE.md config 4 (ImageNet streaming →
ViT-L/16 with HBM-prefetching data ingest).

Patch embedding is a reshape + one matmul (not a conv) — identical math,
lands directly on the MXU with no im2col.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import attention
from ray_tpu.ops.norms import layer_norm

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    dim: int = 1024
    n_layers: int = 24
    n_heads: int = 16
    ffn_dim: int = 4096
    norm_eps: float = 1e-6
    dtype: Any = jnp.bfloat16
    remat: bool = True

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @staticmethod
    def vit_l16() -> "ViTConfig":
        return ViTConfig()

    @staticmethod
    def debug() -> "ViTConfig":
        return ViTConfig(image_size=32, patch_size=8, num_classes=10,
                         dim=64, n_layers=2, n_heads=4, ffn_dim=128,
                         remat=False)


class ViTModel:
    def __init__(self, cfg: ViTConfig, mesh=None,
                 rules: Optional[Dict] = None):
        self.cfg = cfg
        self.mesh = mesh
        self.rules = rules

    def init(self, rng: jax.Array) -> Params:
        cfg = self.cfg
        d, hd, L = cfg.dim, cfg.head_dim, cfg.n_layers
        patch_dim = 3 * cfg.patch_size ** 2
        k = iter(jax.random.split(rng, 10))

        def dense(key, shape, fan_in):
            return jax.random.normal(key, shape, jnp.float32) * (
                fan_in ** -0.5)

        return {
            "patch_w": dense(next(k), (patch_dim, d), patch_dim),
            "patch_b": jnp.zeros((d,)),
            "cls": jnp.zeros((1, 1, d)),
            "pos": dense(next(k), (cfg.num_patches + 1, d), d) * 0.1,
            "layers": {
                "ln1_w": jnp.ones((L, d)), "ln1_b": jnp.zeros((L, d)),
                "wqkv": dense(next(k), (L, d, 3, cfg.n_heads, hd), d),
                "wo": dense(next(k), (L, cfg.n_heads, hd, d), d),
                "ln2_w": jnp.ones((L, d)), "ln2_b": jnp.zeros((L, d)),
                "w_up": dense(next(k), (L, d, cfg.ffn_dim), d),
                "b_up": jnp.zeros((L, cfg.ffn_dim)),
                "w_down": dense(next(k), (L, cfg.ffn_dim, d), cfg.ffn_dim),
                "b_down": jnp.zeros((L, d)),
            },
            "lnf_w": jnp.ones((d,)), "lnf_b": jnp.zeros((d,)),
            "head_w": dense(next(k), (d, cfg.num_classes), d),
            "head_b": jnp.zeros((cfg.num_classes,)),
        }

    def _patchify(self, images: jax.Array) -> jax.Array:
        """[B, H, W, 3] -> [B, N, patch_dim] via reshape (MXU-friendly)."""
        cfg = self.cfg
        B, H, W, C = images.shape
        p = cfg.patch_size
        x = images.reshape(B, H // p, p, W // p, p, C)
        x = x.transpose(0, 1, 3, 2, 4, 5)
        return x.reshape(B, (H // p) * (W // p), p * p * C)

    def _block(self, x, layer):
        cfg = self.cfg
        dt = cfg.dtype
        h = layer_norm(x, layer["ln1_w"], layer["ln1_b"], eps=cfg.norm_eps)
        qkv = jnp.einsum("bsd,dthk->bsthk", h, layer["wqkv"].astype(dt))
        q, kk, vv = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        o = attention(q, kk, vv, causal=False)
        x = x + jnp.einsum("bshk,hkd->bsd", o, layer["wo"].astype(dt))
        h = layer_norm(x, layer["ln2_w"], layer["ln2_b"], eps=cfg.norm_eps)
        up = jax.nn.gelu(
            jnp.einsum("bsd,df->bsf", h, layer["w_up"].astype(dt))
            + layer["b_up"].astype(dt))
        down = jnp.einsum("bsf,fd->bsd", up, layer["w_down"].astype(dt))
        return x + down + layer["b_down"].astype(dt)

    def apply(self, params: Params, images: jax.Array) -> jax.Array:
        """images [B, H, W, 3] float → logits [B, num_classes]."""
        cfg = self.cfg
        patches = self._patchify(images.astype(cfg.dtype))
        x = patches @ params["patch_w"].astype(cfg.dtype) \
            + params["patch_b"].astype(cfg.dtype)
        cls = jnp.broadcast_to(params["cls"].astype(cfg.dtype),
                               (x.shape[0], 1, cfg.dim))
        x = jnp.concatenate([cls, x], axis=1)
        x = x + params["pos"].astype(cfg.dtype)[None]

        block = self._block
        if cfg.remat:
            block = jax.checkpoint(block)

        def scan_body(x, layer):
            return block(x, layer), None

        x, _ = jax.lax.scan(scan_body, x, params["layers"])
        x = layer_norm(x[:, 0], params["lnf_w"], params["lnf_b"],
                       eps=cfg.norm_eps)
        logits = x @ params["head_w"].astype(cfg.dtype) + params["head_b"]
        return logits.astype(jnp.float32)

    def loss(self, params: Params, images: jax.Array,
             labels: jax.Array) -> jax.Array:
        logits = self.apply(params, images)
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, labels[:, None],
                                             axis=-1))

    def accuracy(self, params: Params, images, labels) -> jax.Array:
        return jnp.mean(jnp.argmax(self.apply(params, images), -1)
                        == labels)
