"""Mixture-of-Experts Llama — expert parallelism (SURVEY.md §2.3: EP is
absent in the reference — vLLM handles MoE internally — so this is a
native capability).

GShard/Switch-style top-k routing with capacity-based einsum dispatch:
- all routing math is dense one-hot einsums (no gather/scatter in the hot
  path — XLA maps these straight onto the MXU);
- the expert dimension carries the ``experts`` logical axis → ``ep`` mesh
  axis; expert FFNs run where their weights live, dispatch/combine
  einsums become all-to-alls over ICI;
- tokens beyond an expert's capacity are dropped (standard
  capacity_factor trade).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.models.llama import LlamaConfig, LlamaModel, Params


@dataclasses.dataclass(frozen=True)
class MoEConfig(LlamaConfig):
    num_experts: int = 8
    expert_top_k: int = 2
    capacity_factor: float = 1.25
    router_z_loss: float = 1e-3
    load_balance_loss: float = 1e-2
    # "einsum" = dense one-hot dispatch, XLA chooses collectives;
    # "alltoall" = explicit capacity-bounded expert all-to-all inside
    # shard_map (ops/moe_dispatch.py) — VERDICT r1 #7.
    moe_dispatch: str = "einsum"

    def __post_init__(self):
        super().__post_init__()
        if self.moe_dispatch not in ("einsum", "alltoall"):
            raise ValueError(
                f"moe_dispatch must be 'einsum' or 'alltoall', "
                f"got {self.moe_dispatch!r}")

    @staticmethod
    def debug_moe(num_experts: int = 4) -> "MoEConfig":
        return MoEConfig(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                         n_kv_heads=2, ffn_dim=128, max_seq_len=128,
                         remat=False, num_experts=num_experts)


def moe_param_logical_axes(cfg: MoEConfig) -> Params:
    from ray_tpu.models.llama import param_logical_axes
    axes = param_logical_axes(cfg)
    layers = dict(axes["layers"])
    for key in ("w_gate", "w_up", "w_down"):
        del layers[key]
    layers["router"] = (None, "embed_in", "experts")
    layers["e_gate"] = (None, "experts", "embed_in", "mlp")
    layers["e_up"] = (None, "experts", "embed_in", "mlp")
    layers["e_down"] = (None, "experts", "mlp", "embed_in")
    axes["layers"] = layers
    return axes


class MoEModel(LlamaModel):
    """Llama with MoE FFN blocks. Aux losses accumulated per forward."""

    def __init__(self, cfg: MoEConfig, mesh=None,
                 rules: Optional[Dict] = None):
        super().__init__(cfg, mesh=mesh, rules=rules)

    def init(self, rng: jax.Array) -> Params:
        params = super().init(rng)
        cfg: MoEConfig = self.cfg
        d, f, E, L = cfg.dim, cfg.ffn_dim, cfg.num_experts, cfg.n_layers
        keys = jax.random.split(jax.random.fold_in(rng, 1), 4)
        layers = params["layers"]
        for key in ("w_gate", "w_up", "w_down"):
            del layers[key]
        layers["router"] = jax.random.normal(
            keys[0], (L, d, E), jnp.float32) * 0.02
        layers["e_gate"] = jax.random.normal(
            keys[1], (L, E, d, f), jnp.float32) * d ** -0.5
        layers["e_up"] = jax.random.normal(
            keys[2], (L, E, d, f), jnp.float32) * d ** -0.5
        layers["e_down"] = jax.random.normal(
            keys[3], (L, E, f, d), jnp.float32) * f ** -0.5
        return params

    def param_shardings(self):
        from ray_tpu.parallel.mesh import named_sharding
        axes = moe_param_logical_axes(self.cfg)
        return jax.tree.map(
            lambda names: named_sharding(self.mesh, *names,
                                         rules=self.rules),
            axes, is_leaf=lambda x: isinstance(x, tuple))

    # -- MoE FFN -----------------------------------------------------------
    def _moe_ffn(self, h: jax.Array, layer: Params
                 ) -> Tuple[jax.Array, jax.Array]:
        """h [B, S, D] → (out [B, S, D], aux_loss scalar)."""
        cfg: MoEConfig = self.cfg
        if cfg.moe_dispatch == "alltoall":
            if self.mesh is None:
                raise ValueError(
                    "moe_dispatch='alltoall' needs a device mesh "
                    "(pass mesh= to MoEModel)")
            from ray_tpu.ops.moe_dispatch import expert_alltoall_ffn
            out, aux = expert_alltoall_ffn(
                h, layer["router"], layer["e_gate"], layer["e_up"],
                layer["e_down"], self.mesh,
                num_experts=cfg.num_experts, top_k=cfg.expert_top_k,
                capacity_factor=cfg.capacity_factor,
                z_coef=cfg.router_z_loss, lb_coef=cfg.load_balance_loss,
                dtype=cfg.dtype)
            return out, jnp.mean(aux)
        dt = cfg.dtype
        B, S, D = h.shape
        E, K = cfg.num_experts, cfg.expert_top_k
        T = B * S
        C = max(1, int(cfg.capacity_factor * T * K / E))

        x = h.reshape(T, D)
        # Shared GShard-style router math (collision-free slot positions
        # across the top-k passes): ops/moe_dispatch._topk_dispatch.
        from ray_tpu.ops.moe_dispatch import topk_dispatch
        dispatch, combine, aux = topk_dispatch(
            x, layer["router"], E, K, C,
            cfg.router_z_loss, cfg.load_balance_loss)

        expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(dt),
                               x.astype(dt))                   # [E, C, D]
        gate = jnp.einsum("ecd,edf->ecf", expert_in,
                          layer["e_gate"].astype(dt))
        up = jnp.einsum("ecd,edf->ecf", expert_in,
                        layer["e_up"].astype(dt))
        act = jax.nn.silu(gate) * up
        expert_out = jnp.einsum("ecf,efd->ecd", act,
                                layer["e_down"].astype(dt))    # [E, C, D]
        out = jnp.einsum("tec,ecd->td", combine.astype(dt), expert_out)
        return out.reshape(B, S, D), aux

    def _moe_block(self, x, layer: Params, positions):
        """Returns (x, aux) — aux threads through the scan carry."""
        from ray_tpu.ops.norms import rms_norm
        from ray_tpu.ops.rope import apply_rope
        cfg = self.cfg
        dt = cfg.dtype
        h = rms_norm(x, layer["attn_norm"], eps=cfg.norm_eps)
        q = jnp.einsum("bsd,dhk->bshk", h, layer["wq"].astype(dt))
        kk = jnp.einsum("bsd,dhk->bshk", h, layer["wk"].astype(dt))
        vv = jnp.einsum("bsd,dhk->bshk", h, layer["wv"].astype(dt))
        q = apply_rope(q, self._angles, positions)
        kk = apply_rope(kk, self._angles, positions)
        o = self._attention(q, kk, vv, positions)
        o = jnp.einsum("bshk,hkd->bsd", o, layer["wo"].astype(dt))
        x = x + o
        h = rms_norm(x, layer["mlp_norm"], eps=cfg.norm_eps)
        ffn, aux = self._moe_ffn(h, layer)
        return x + ffn, aux

    def apply_with_aux(self, params: Params, tokens: jax.Array,
                       positions=None):
        from ray_tpu.ops.norms import rms_norm
        cfg = self.cfg
        x = self._embed_lookup(params["embed"].astype(cfg.dtype), tokens)
        x = self._constrain(x, "batch", "seq", "embed")

        block = self._moe_block
        if cfg.remat:
            block = jax.checkpoint(block)

        def scan_body(carry, layer):
            x, aux = carry
            x, aux_i = block(x, layer, positions)
            return (x, aux + aux_i), None

        (x, aux), _ = jax.lax.scan(
            scan_body, (x, jnp.float32(0.0)), params["layers"])
        x = rms_norm(x, params["norm_f"], eps=cfg.norm_eps)
        head = (params["embed"].T if cfg.tie_embeddings
                else params["lm_head"])
        logits = jnp.einsum("bsd,dv->bsv", x, head.astype(cfg.dtype))
        return logits.astype(jnp.float32), aux

    def apply(self, params: Params, tokens: jax.Array,
              positions=None) -> jax.Array:
        return self.apply_with_aux(params, tokens, positions)[0]

    def loss(self, params: Params, tokens: jax.Array, targets: jax.Array,
             mask=None) -> jax.Array:
        logits, aux = self.apply_with_aux(params, tokens)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None],
                                   axis=-1).squeeze(-1)
        ce = (jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1)
              if mask is not None else jnp.mean(nll))
        return ce + aux
