"""Cluster launcher: `ray-tpu up / down` from a YAML config.

Reference capability: the cluster launcher
(``python/ray/autoscaler/_private/commands.py`` create_or_update_cluster,
``updater.py`` NodeUpdater, cloud ``node_provider.py`` implementations,
CLI at ``python/ray/scripts/scripts.py:1419`` `ray up`). That stack
SSHes to cloud instances and bootstraps head/worker daemons; here the
same three seams exist TPU-shaped:

- :class:`LauncherProvider` — create/terminate/list raw hosts.
- :class:`SubprocessProvider` — "hosts" are processes on this machine;
  `up` genuinely creates a running multi-daemon cluster (the
  fake-multi-node role, but through the REAL `ray-tpu start` path).
- :class:`SshProvider` — bootstraps a remote host over ``ssh`` with the
  same command lines (the NodeUpdater role). Command construction is
  unit-tested; actually reaching hosts needs sshd + keys, which the
  zero-egress image lacks.

Config (YAML):

    cluster_name: demo
    max_workers: 4
    provider:
      type: subprocess        # or: ssh
      # ssh: {user: ubuntu, hosts: [a, b], key: ~/.ssh/id, repo: /path}
    head:
      resources: {CPU: 4}
    worker:
      resources: {CPU: 4, TPU: 4}
      count: 2
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

CLUSTER_STATE_DIR = os.path.expanduser("~/.ray_tpu/clusters")


def _load_config(path: str) -> Dict[str, Any]:
    import yaml
    with open(path) as f:
        cfg = yaml.safe_load(f)
    cfg.setdefault("cluster_name", "default")
    cfg.setdefault("provider", {"type": "subprocess"})
    cfg.setdefault("head", {}).setdefault("resources", {"CPU": 4.0})
    cfg.setdefault("worker", {}).setdefault("resources", {"CPU": 4.0})
    cfg["worker"].setdefault("count", 1)
    return cfg


def _state_path(name: str) -> str:
    os.makedirs(CLUSTER_STATE_DIR, exist_ok=True)
    return os.path.join(CLUSTER_STATE_DIR, f"{name}.json")


# ---------------------------------------------------------------------------
# providers
# ---------------------------------------------------------------------------

class LauncherProvider:
    """create_head/create_worker/terminate over raw hosts."""

    def create_head(self, head_cfg: Dict[str, Any]) -> Dict[str, Any]:
        raise NotImplementedError

    def create_worker(self, address: str,
                      worker_cfg: Dict[str, Any]) -> Dict[str, Any]:
        raise NotImplementedError

    def terminate(self, record: Dict[str, Any]) -> None:
        raise NotImplementedError


class SubprocessProvider(LauncherProvider):
    """Real head+daemon OS processes on this machine."""

    def __init__(self, session: Optional[str] = None):
        self.session = session or os.path.join(
            "/tmp", "ray_tpu", f"launcher_{os.getpid()}")
        os.makedirs(self.session, exist_ok=True)

    def create_head(self, head_cfg):
        from ray_tpu._private.cluster import _spawn
        head_proc, head_port = _spawn(
            "ray_tpu._private.head",
            ["--state-path", os.path.join(self.session, "head_state.db")],
            output_path=os.path.join(self.session, "head.log"))
        return {"kind": "head", "pid": head_proc.pid,
                "address": f"127.0.0.1:{head_port}"}

    def create_worker(self, address, worker_cfg):
        from ray_tpu._private.cluster import _spawn
        from ray_tpu._private.ids import NodeID
        node_id = NodeID.from_random().hex()
        proc, _port = _spawn(
            "ray_tpu._private.daemon",
            ["--head", address, "--node-id", node_id,
             "--resources", json.dumps(worker_cfg["resources"]),
             "--object-store-bytes",
             str(worker_cfg.get("object_store_bytes",
                                256 * 1024 * 1024)),
             "--persist"],
            output_path=os.path.join(self.session, f"daemon-{node_id[:8]}.log"))
        return {"kind": "worker", "pid": proc.pid, "node_id": node_id}

    def terminate(self, record):
        import signal
        try:
            os.kill(record["pid"], signal.SIGTERM)
        except ProcessLookupError:
            pass


class SshProvider(LauncherProvider):
    """Bootstrap remote hosts over ssh (the NodeUpdater role).

    ``bootstrap_command``/``head_command`` build the exact remote
    command lines; ``run=False`` (tests) returns them instead of
    executing."""

    def __init__(self, user: str, hosts: List[str], key: str = "",
                 repo: str = "/root/repo", python: str = "python",
                 run: bool = True):
        self.user = user
        self.hosts = list(hosts)
        self.key = key
        self.repo = repo
        self.python = python
        self.run = run
        self._next_host = 0

    def _ssh_base(self, host: str) -> List[str]:
        cmd = ["ssh", "-o", "StrictHostKeyChecking=no"]
        if self.key:
            cmd += ["-i", self.key]
        cmd.append(f"{self.user}@{host}" if self.user else host)
        return cmd

    def head_command(self, host: str) -> List[str]:
        remote = (f"cd {self.repo} && PYTHONPATH={self.repo} "
                  f"JAX_PLATFORMS=cpu nohup {self.python} -m "
                  f"ray_tpu._private.head --port 6379 "
                  f"> /tmp/ray_tpu_head.log 2>&1 & echo started")
        return self._ssh_base(host) + [remote]

    def bootstrap_command(self, host: str, address: str,
                          node_id: str, resources: Dict[str, float]
                          ) -> List[str]:
        remote = (f"cd {self.repo} && PYTHONPATH={self.repo} "
                  f"JAX_PLATFORMS=cpu nohup {self.python} -m "
                  f"ray_tpu._private.daemon --head {address} "
                  f"--node-id {node_id} "
                  f"--resources '{json.dumps(resources)}' --persist "
                  f"--host 0.0.0.0 "
                  f"> /tmp/ray_tpu_daemon.log 2>&1 & echo started")
        return self._ssh_base(host) + [remote]

    def create_head(self, head_cfg):
        host = self.hosts[0]
        cmd = self.head_command(host)
        if self.run:
            subprocess.run(cmd, check=True, timeout=60)
        return {"kind": "head", "host": host, "address": f"{host}:6379",
                "command": cmd}

    def create_worker(self, address, worker_cfg):
        from ray_tpu._private.ids import NodeID
        host = self.hosts[self._next_host % len(self.hosts)]
        self._next_host += 1
        node_id = NodeID.from_random().hex()
        cmd = self.bootstrap_command(host, address, node_id,
                                     worker_cfg["resources"])
        if self.run:
            subprocess.run(cmd, check=True, timeout=60)
        return {"kind": "worker", "host": host, "node_id": node_id,
                "command": cmd}

    def terminate(self, record):
        if not self.run:
            return
        host = record.get("host")
        if host:
            subprocess.run(
                self._ssh_base(host)
                + ["pkill -f ray_tpu._private || true"],
                timeout=60, check=False)


def _make_provider(cfg: Dict[str, Any]) -> LauncherProvider:
    pcfg = cfg["provider"]
    ptype = pcfg.get("type", "subprocess")
    if ptype in ("subprocess", "local"):
        return SubprocessProvider(session=pcfg.get("session"))
    if ptype == "ssh":
        ssh = pcfg.get("ssh", pcfg)
        return SshProvider(user=ssh.get("user", ""),
                           hosts=ssh.get("hosts", []),
                           key=ssh.get("key", ""),
                           repo=ssh.get("repo", "/root/repo"),
                           python=ssh.get("python", "python"))
    raise ValueError(f"unknown provider type {ptype!r}")


# ---------------------------------------------------------------------------
# up / down
# ---------------------------------------------------------------------------

def _head_alive(address: str, timeout: float = 3.0) -> bool:
    if not address:
        return False
    try:
        from ray_tpu._private import rpc as _rpc
        host, port = address.rsplit(":", 1)
        _rpc.wait_for_server((host, int(port)), timeout=timeout)
        return True
    except Exception:
        return False


def up(config_path: str, *, provider: Optional[LauncherProvider] = None
       ) -> Dict[str, Any]:
    """Create (or extend) the cluster described by ``config_path``;
    returns the cluster state record (also persisted under
    ``~/.ray_tpu/clusters/<name>.json``)."""
    cfg = _load_config(config_path)
    provider = provider or _make_provider(cfg)
    state_file = _state_path(cfg["cluster_name"])
    state: Dict[str, Any] = {"cluster_name": cfg["cluster_name"],
                             "nodes": []}
    if os.path.exists(state_file):
        with open(state_file) as f:
            state = json.load(f)
        # stale-state recovery: a state file from a crashed/rebooted
        # cluster records a head that no longer answers — probe it, and
        # start fresh instead of wedging every subsequent `up`
        if not _head_alive(state.get("address", "")):
            state = {"cluster_name": cfg["cluster_name"], "nodes": []}
    if not any(n["kind"] == "head" for n in state["nodes"]):
        head = provider.create_head(cfg["head"])
        state["address"] = head["address"]
        state["nodes"].append(head)
    address = state["address"]
    # wait for the head to answer before registering workers
    from ray_tpu._private import rpc as _rpc
    host, port = address.rsplit(":", 1)
    _rpc.wait_for_server((host, int(port)), timeout=30.0)
    have = sum(1 for n in state["nodes"] if n["kind"] == "worker")
    want = int(cfg["worker"]["count"])
    for _ in range(max(0, want - have)):
        state["nodes"].append(
            provider.create_worker(address, cfg["worker"]))
    with open(state_file, "w") as f:
        json.dump(state, f, indent=2)
    return state


def down(config_path: str, *,
         provider: Optional[LauncherProvider] = None) -> int:
    """Terminate every node of the cluster; returns the count."""
    cfg = _load_config(config_path)
    provider = provider or _make_provider(cfg)
    state_file = _state_path(cfg["cluster_name"])
    if not os.path.exists(state_file):
        return 0
    with open(state_file) as f:
        state = json.load(f)
    n = 0
    # workers first, head last (the reference teardown order)
    for record in sorted(state["nodes"],
                         key=lambda r: r["kind"] == "head"):
        provider.terminate(record)
        n += 1
    os.remove(state_file)
    return n


def wait_for_nodes(address: str, count: int,
                   timeout: float = 60.0) -> bool:
    """Block until ``count`` alive nodes registered at the head."""
    from ray_tpu._private.head import HeadClient
    host, port = address.rsplit(":", 1)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            head = HeadClient((host, int(port)))
            try:
                alive = [n for n in head.list_nodes() if n["alive"]]
            finally:
                head.close()
            if len(alive) >= count:
                return True
        except (OSError, Exception):
            pass
        time.sleep(0.3)
    return False
