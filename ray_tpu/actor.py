"""Actor classes and handles (reference: python/ray/actor.py)."""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Union

from ray_tpu import exceptions as exc
from ray_tpu._private import worker
from ray_tpu._private.gcs import ActorState
from ray_tpu._private.ids import ActorID, ObjectID, TaskID, next_seqno
from ray_tpu.tenancy import context as _tenancy_ctx
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.runtime_env_packaging import \
    prepare_runtime_env as _prepare_runtime_env
from ray_tpu._private.task_spec import (DEFAULT_ACTOR_OPTIONS,
                                        DEFAULT_TASK_OPTIONS, TaskKind,
                                        TaskSpec, resources_from_options,
                                        validate_options)


class ActorMethod:
    """Bound remote method on an actor handle."""

    def __init__(self, handle: "ActorHandle", method_name: str,
                 options: Optional[Dict[str, Any]] = None):
        self._handle = handle
        self._method_name = method_name
        self._options = options or {}

    def options(self, **opts) -> "ActorMethod":
        merged = dict(self._options)
        merged.update(opts)
        return ActorMethod(self._handle, self._method_name, merged)

    def remote(self, *args, **kwargs):
        return self._handle._submit_method(
            self._method_name, args, kwargs, self._options)

    def bind(self, *args, **kwargs):
        """Build a DAG node (reference: dag/class_node.py)."""
        from ray_tpu.dag.node import ClassMethodNode
        return ClassMethodNode(self._handle, self._method_name, args,
                               kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor method {self._method_name} cannot be called directly; "
            f"use .remote()")


class ActorHandle:
    def __init__(self, actor_id: ActorID, class_name: str = "Actor",
                 method_options: Optional[Dict[str, Dict[str, Any]]] = None):
        self._actor_id = actor_id
        self._class_name = class_name
        self._method_options = method_options or {}

    @property
    def _ray_actor_id(self) -> ActorID:
        return self._actor_id

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self, name,
                           dict(self._method_options.get(name, {})))

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:12]})"

    def __reduce__(self):
        return (ActorHandle,
                (self._actor_id, self._class_name, self._method_options))

    def __hash__(self):
        return hash(self._actor_id)

    def __eq__(self, other):
        return (isinstance(other, ActorHandle)
                and other._actor_id == self._actor_id)

    def _submit_method(self, method_name: str, args, kwargs,
                       options: Dict[str, Any]):
        rt = worker.global_worker()
        info = rt.gcs.get_actor_info(self._actor_id)
        if info is None:
            raise ValueError(f"unknown actor {self._actor_id}")
        # max_pending_calls backpressure
        with rt._actor_lock:
            executor = rt._actor_executors.get(self._actor_id)
        spec_limit = getattr(info.creation_spec, "max_pending_calls", -1) \
            if info.creation_spec else -1
        if (spec_limit and spec_limit > 0 and executor is not None
                and executor.num_pending >= spec_limit):
            raise exc.PendingCallsLimitExceeded(
                f"actor has {executor.num_pending} pending calls "
                f"(max_pending_calls={spec_limit})")

        num_returns = options.get("num_returns", 1)
        n_ids = 1 if not isinstance(num_returns, int) else max(num_returns, 1)
        task_id = TaskID.from_random()
        spec = TaskSpec(
            task_id=task_id,
            kind=TaskKind.ACTOR_TASK,
            name=f"{self._class_name}.{method_name}",
            func=None,
            args=tuple(args),
            kwargs=dict(kwargs),
            resources={},
            num_returns=num_returns,
            return_ids=[ObjectID.from_random() for _ in range(n_ids)],
            max_retries=info.max_task_retries,
            scheduling_strategy="DEFAULT",
            job_id=_tenancy_ctx.current_job_id(rt),
            actor_id=self._actor_id,
            method_name=method_name,
            seqno=next_seqno(),
            concurrency_group=options.get("concurrency_group", ""),
        )
        refs = rt.submit_task(spec)
        if num_returns == "streaming":
            from ray_tpu.remote_function import ObjectRefGenerator
            return ObjectRefGenerator(task_id)
        if isinstance(num_returns, int) and num_returns != 1:
            return refs if num_returns > 0 else None
        return refs[0]


class ActorClass:
    def __init__(self, cls: type, default_options: Dict[str, Any]):
        self._cls = cls
        merged = dict(DEFAULT_ACTOR_OPTIONS)
        merged.update(default_options)
        self._default_options = validate_options(merged, for_actor=True)
        # Per-method defaults declared with @ray_tpu.method(**opts).
        self._method_options: Dict[str, Dict[str, Any]] = {}
        for name in dir(cls):
            m = getattr(cls, name, None)
            opts = getattr(m, "__ray_tpu_method_options__", None)
            if opts:
                self._method_options[name] = dict(opts)
        functools.update_wrapper(self, cls, updated=[])

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor class {self._cls.__name__} cannot be instantiated "
            f"directly; use {self._cls.__name__}.remote()")

    def options(self, **options) -> "_ActorOptionsWrapper":
        merged = dict(self._default_options)
        merged.update(options)
        validate_options(merged, for_actor=True)
        return _ActorOptionsWrapper(self, merged)

    def remote(self, *args, **kwargs) -> ActorHandle:
        return self._remote(args, kwargs, self._default_options)

    def _remote(self, args, kwargs, options) -> ActorHandle:
        rt = worker.global_worker()
        name = options.get("name")
        namespace = options.get("namespace") or rt.namespace
        actor_id = ActorID.from_random()
        spec = TaskSpec(
            task_id=TaskID.from_random(),
            kind=TaskKind.ACTOR_CREATION,
            runtime_env=_prepare_runtime_env(
                options.get("runtime_env")),
            name=f"{self._cls.__name__}.__init__",
            func=self._cls,
            args=tuple(args),
            kwargs=dict(kwargs),
            resources=resources_from_options(options),
            num_returns=1,
            return_ids=[ObjectID.from_random()],
            scheduling_strategy=worker.capture_parent_pg_strategy(
                options.get("scheduling_strategy", "DEFAULT")),
            job_id=_tenancy_ctx.current_job_id(rt),
            actor_id=actor_id,
            max_restarts=options.get("max_restarts", 0),
            max_task_retries=options.get("max_task_retries", 0),
            max_concurrency=options.get("max_concurrency", 1),
            max_pending_calls=options.get("max_pending_calls", -1),
            concurrency_groups=options.get("concurrency_groups"),
            lifetime=options.get("lifetime"),
            actor_name=name,
            namespace=namespace,
            label_selector=options.get("label_selector"),
            in_process=bool(options.get("_in_process")),
            method_options=dict(self._method_options),
        )
        real_id = rt.create_actor(
            spec, get_if_exists=bool(options.get("get_if_exists")))
        if real_id != actor_id:  # got an existing named actor
            info = rt.gcs.get_actor_info(real_id)
            return ActorHandle(real_id,
                               info.class_name if info else "Actor",
                               dict(info.method_options) if info else None)
        return ActorHandle(actor_id, self._cls.__name__,
                           dict(self._method_options))


class _ActorOptionsWrapper:
    def __init__(self, actor_cls: ActorClass, options: Dict[str, Any]):
        self._actor_cls = actor_cls
        self._options = options

    def remote(self, *args, **kwargs) -> ActorHandle:
        return self._actor_cls._remote(args, kwargs, self._options)


def get_actor(name: str, namespace: Optional[str] = None) -> ActorHandle:
    rt = worker.global_worker()
    ns = namespace or rt.namespace
    actor_id = rt.gcs.get_named_actor(name, ns)
    if actor_id is None:
        raise ValueError(
            f"failed to look up actor {name!r} in namespace {ns!r}")
    info = rt.gcs.get_actor_info(actor_id)
    return ActorHandle(actor_id, info.class_name if info else "Actor",
                       dict(info.method_options) if info else None)


def exit_actor() -> None:
    """Terminate the current actor from inside one of its methods."""
    from ray_tpu._private.worker import _ExitActor
    raise _ExitActor()
