"""IMPALA learner: V-trace off-policy actor-critic.

Reference capability: `rllib/algorithms/impala/` — an asynchronous
actor-learner architecture where EnvRunners sample with STALE (behavior)
weights and the learner corrects the off-policyness with V-trace
(Espeholt et al. 2018). TPU-first shape: the V-trace recursion is a
`lax.scan` inside one jitted update (no Python loop over timesteps), and
the batch of runner fragments is vmapped.

The async control loop lives in `rl/algorithm.py::Algorithm._train_async`
(one in-flight sample per runner; learner updates as fragments land —
the IMPALA queue, not the PPO barrier).
"""

from __future__ import annotations

from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rl.ppo import ActorCriticPolicy, _mlp_apply


def vtrace(behavior_logp, target_logp, rewards, discounts, values,
           bootstrap_value, rho_bar: float = 1.0, c_bar: float = 1.0):
    """V-trace targets + policy-gradient advantages for ONE trajectory
    fragment ([T] arrays). Pure jax; differentiable inputs must be
    stopped by the caller where the paper requires."""
    rhos = jnp.exp(target_logp - behavior_logp)
    clipped_rhos = jnp.minimum(rho_bar, rhos)
    cs = jnp.minimum(c_bar, rhos)
    values_next = jnp.concatenate([values[1:], bootstrap_value[None]])
    deltas = clipped_rhos * (rewards + discounts * values_next - values)

    def body(acc, xs):
        delta, discount, c = xs
        acc = delta + discount * c * acc
        return acc, acc

    _, vs_minus_v = jax.lax.scan(
        body, jnp.zeros_like(bootstrap_value),
        (deltas, discounts, cs), reverse=True)
    vs = vs_minus_v + values
    vs_next = jnp.concatenate([vs[1:], bootstrap_value[None]])
    pg_adv = clipped_rhos * (rewards + discounts * vs_next - values)
    return vs, pg_adv


class ImpalaLearner:
    """Learner-group role (`rllib/core/learner/learner.py:108`) for the
    IMPALA algorithm; shares the actor-critic network with PPO."""

    def __init__(self, obs_dim: int, n_actions: int, *, hidden=(64, 64),
                 lr: float = 6e-4, gamma: float = 0.99,
                 vf_coef: float = 0.5, ent_coef: float = 0.01,
                 rho_bar: float = 1.0, c_bar: float = 1.0,
                 seed: int = 0):
        self.policy = ActorCriticPolicy(obs_dim, n_actions, hidden, seed)
        self.optimizer = optax.rmsprop(lr, decay=0.99, eps=0.1)
        self.opt_state = self.optimizer.init(self.policy.params)
        self.gamma = gamma
        self.vf_coef = vf_coef
        self.ent_coef = ent_coef
        self.rho_bar = rho_bar
        self.c_bar = c_bar
        self._update = jax.jit(self._update_impl)
        self.num_updates = 0

    # -- jitted update ---------------------------------------------------
    def _pg_loss(self, target_logp, behavior_logp, pg_adv):
        """Policy objective on the V-trace advantages; APPO overrides
        with the clipped surrogate."""
        return -jnp.mean(target_logp * pg_adv)

    def _loss(self, params, batch):
        logits = _mlp_apply(params["pi"], batch["obs"])        # [T, A]
        logp_all = jax.nn.log_softmax(logits)
        target_logp = jnp.take_along_axis(
            logp_all, batch["actions"][:, None], axis=1)[:, 0]
        values = _mlp_apply(params["vf"], batch["obs"])[:, 0]
        bootstrap = _mlp_apply(params["vf"],
                               batch["next_obs_last"][None])[0, 0]
        discounts = self.gamma * (1.0 - batch["dones"])
        vs, pg_adv = vtrace(batch["logp"], jax.lax.stop_gradient(
            target_logp), batch["rewards"], discounts,
            jax.lax.stop_gradient(values),
            jax.lax.stop_gradient(bootstrap),
            rho_bar=self.rho_bar, c_bar=self.c_bar)
        pg_loss = self._pg_loss(target_logp, batch["logp"], pg_adv)
        vf_loss = 0.5 * jnp.mean((vs - values) ** 2)
        entropy = -jnp.mean(
            jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
        loss = pg_loss + self.vf_coef * vf_loss - self.ent_coef * entropy
        return loss, {"pg_loss": pg_loss, "vf_loss": vf_loss,
                      "entropy": entropy}

    def _update_impl(self, params, opt_state, batch):
        (loss, aux), grads = jax.value_and_grad(
            self._loss, has_aux=True)(params, batch)
        grads = jax.tree.map(lambda g: jnp.clip(g, -40.0, 40.0), grads)
        updates, opt_state = self.optimizer.update(grads, opt_state,
                                                   params)
        params = optax.apply_updates(params, updates)
        aux["loss"] = loss
        return params, opt_state, aux

    # -- host API ----------------------------------------------------------
    def update(self, rollouts: List[Dict[str, np.ndarray]]
               ) -> Dict[str, Any]:
        metrics: Dict[str, Any] = {}
        for r in rollouts:   # fragments arrive asynchronously; one pass each
            batch = {
                "obs": jnp.asarray(r["obs"]),
                "next_obs_last": jnp.asarray(r["next_obs_last"]),
                "actions": jnp.asarray(r["actions"]),
                "rewards": jnp.asarray(r["rewards"]),
                "dones": jnp.asarray(r["dones"], jnp.float32),
                "logp": jnp.asarray(r["logp"]),
            }
            self.policy.params, self.opt_state, aux = self._update(
                self.policy.params, self.opt_state, batch)
            self.num_updates += 1
            metrics = {k: float(v) for k, v in aux.items()}
        self.policy._sync_np()
        metrics["num_learner_updates"] = self.num_updates
        return metrics

    def get_weights(self):
        return self.policy.params

    def set_weights(self, params):
        self.policy.set_weights(params)


class APPOLearner(ImpalaLearner):
    """APPO (reference: ``rllib/algorithms/appo/``): the IMPALA
    architecture (async runners, V-trace target correction) with PPO's
    clipped-surrogate policy objective on the V-trace advantages —
    tolerates more policy lag than plain IMPALA's policy gradient."""

    def __init__(self, obs_dim: int, n_actions: int, *,
                 clip: float = 0.2, **kwargs):
        super().__init__(obs_dim, n_actions, **kwargs)
        # read at first trace (after __init__), so setting it after
        # super() is safe; the inherited jitted _update dispatches to
        # THIS class's _pg_loss through self
        self.clip = clip

    def _pg_loss(self, target_logp, behavior_logp, pg_adv):
        # PPO clip on the importance ratio vs the BEHAVIOR policy
        ratio = jnp.exp(target_logp - behavior_logp)
        unclipped = ratio * pg_adv
        clipped = jnp.clip(ratio, 1 - self.clip, 1 + self.clip) * pg_adv
        return -jnp.mean(jnp.minimum(unclipped, clipped))
