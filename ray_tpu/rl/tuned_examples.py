"""Tuned examples: curated known-good configs per algorithm.

Reference capability: `rllib/tuned_examples/` — a registry of
algorithm configs that demonstrably reach a target return on a named
environment, runnable by name. Here each entry is an AlgorithmConfig
factory plus its convergence contract (target return, iteration
budget); ``run(name)`` trains until the target or the budget and
reports whether the contract held.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

import numpy as np

from ray_tpu.rl.algorithm import AlgorithmConfig


@dataclasses.dataclass
class TunedExample:
    make_config: Callable[[], AlgorithmConfig]
    target_return: float
    max_iterations: int
    description: str = ""


def _ppo_cartpole() -> AlgorithmConfig:
    return (AlgorithmConfig(algo="PPO", seed=0)
            .environment("CartPole-v1")
            .env_runners(2, rollout_fragment_length=512)
            .training(lr=3e-4, epochs=6, minibatch_size=128,
                      ent_coef=0.01))


def _dqn_cartpole() -> AlgorithmConfig:
    return (AlgorithmConfig(algo="DQN", seed=0)
            .environment("CartPole-v1")
            .env_runners(2, rollout_fragment_length=256))


def _impala_cartpole() -> AlgorithmConfig:
    return (AlgorithmConfig(algo="IMPALA", seed=0)
            .environment("CartPole-v1")
            .env_runners(2, rollout_fragment_length=256))


def _appo_cartpole() -> AlgorithmConfig:
    return (AlgorithmConfig(algo="APPO", seed=0)
            .environment("CartPole-v1")
            .env_runners(2, rollout_fragment_length=256))


def _sac_cartpole() -> AlgorithmConfig:
    return (AlgorithmConfig(algo="SAC", seed=0)
            .environment("CartPole-v1")
            .env_runners(2, rollout_fragment_length=256))


def _ppo_multi_agent() -> AlgorithmConfig:
    from ray_tpu.rl.env import register_env
    from ray_tpu.rl.multi_agent import MultiAgentCartPole
    register_env("tuned/MultiCartPole-2",
                 lambda seed=0: MultiAgentCartPole(2, seed=seed,
                                                  max_steps=200))
    return (AlgorithmConfig(algo="PPO", seed=0)
            .environment("tuned/MultiCartPole-2")
            .env_runners(2, rollout_fragment_length=256)
            .training(epochs=4, minibatch_size=128)
            .multi_agent(
                policies={"p0": None, "p1": None},
                policy_mapping_fn=lambda aid: (
                    "p0" if aid.endswith("0") else "p1")))


def _ppo_gridworld() -> AlgorithmConfig:
    return (AlgorithmConfig(algo="PPO", seed=0)
            .environment("GridWorld-5x5")
            .env_runners(2, rollout_fragment_length=256)
            .training(lr=5e-4, epochs=6, minibatch_size=128,
                      ent_coef=0.02))


def _dqn_gridworld() -> AlgorithmConfig:
    return (AlgorithmConfig(algo="DQN", seed=0)
            .environment("GridWorld-5x5")
            .env_runners(2, rollout_fragment_length=128))


def _ppo_mountaincar() -> AlgorithmConfig:
    return (AlgorithmConfig(algo="PPO", seed=0)
            .environment("MountainCarShaped-v0")
            .env_runners(2, rollout_fragment_length=512)
            .training(lr=5e-4, epochs=6, minibatch_size=128,
                      ent_coef=0.01))


def _impala_gridworld() -> AlgorithmConfig:
    return (AlgorithmConfig(algo="IMPALA", seed=0)
            .environment("GridWorld-5x5")
            .env_runners(2, rollout_fragment_length=128))


TUNED: Dict[str, TunedExample] = {
    "ppo-cartpole": TunedExample(
        _ppo_cartpole, target_return=200.0, max_iterations=40,
        description="PPO reaches 200+ on CartPole within 40 iters"),
    "dqn-cartpole": TunedExample(
        _dqn_cartpole, target_return=80.0, max_iterations=40,
        description="DQN clears 80 on CartPole within 40 iters"),
    "impala-cartpole": TunedExample(
        _impala_cartpole, target_return=100.0, max_iterations=40,
        description="IMPALA (V-trace) clears 100 within 40 iters"),
    "appo-cartpole": TunedExample(
        _appo_cartpole, target_return=100.0, max_iterations=40,
        description="APPO clears 100 within 40 iters"),
    "sac-cartpole": TunedExample(
        _sac_cartpole, target_return=40.0, max_iterations=40,
        description="discrete SAC clears 40 within 40 iters"),
    "ppo-multi-agent-cartpole": TunedExample(
        _ppo_multi_agent, target_return=60.0, max_iterations=30,
        description="2-policy PPO on MultiAgentCartPole clears 60"),
    # optimal 5x5 GridWorld return = 10 - 0.1*7 ~ 9.3; random walk is
    # deeply negative, so >= 5 is a real learned-policy bar
    "ppo-gridworld": TunedExample(
        _ppo_gridworld, target_return=5.0, max_iterations=30,
        description="PPO solves sparse 5x5 GridWorld (>=5 return)"),
    "dqn-gridworld": TunedExample(
        _dqn_gridworld, target_return=5.0, max_iterations=30,
        description="DQN solves sparse 5x5 GridWorld (>=5 return)"),
    "impala-gridworld": TunedExample(
        _impala_gridworld, target_return=3.0, max_iterations=30,
        description="IMPALA clears 3 on 5x5 GridWorld"),
    # shaped mountain car: random policy stays ~-195; energy-pumping
    # policies reach the flag (bonus +100) -> >= -100 is a clear pass
    "ppo-mountaincar-shaped": TunedExample(
        _ppo_mountaincar, target_return=-100.0, max_iterations=40,
        description="PPO builds momentum on shaped MountainCar"),
}


def run(name: str, max_iterations: Optional[int] = None,
        target_return: Optional[float] = None) -> Dict[str, Any]:
    """Train a tuned example until its target return (rolling best) or
    the iteration budget; returns the final metrics plus
    ``converged``/``best_return``."""
    ex = TUNED[name]
    target = target_return if target_return is not None \
        else ex.target_return
    budget = max_iterations if max_iterations is not None \
        else ex.max_iterations
    algo = ex.make_config().build()
    best = float("-inf")
    metrics: Dict[str, Any] = {}
    try:
        for _ in range(budget):
            metrics = algo.train()
            ret = metrics.get("episode_return_mean", float("nan"))
            if np.isfinite(ret):
                best = max(best, float(ret))
            if best >= target:
                break
    finally:
        algo.stop()
    metrics["best_return"] = best
    metrics["converged"] = best >= target
    metrics["target_return"] = target
    return metrics
