"""RL environments + EnvRunner actors.

Reference: RLlib `rllib/env/env_runner_group.py` (rollout worker actors),
`rllib/env/single_agent_env_runner.py`. Env API is gymnasium-shaped:
reset() -> (obs, info); step(a) -> (obs, reward, terminated, truncated,
info). CartPole ships in-tree (classic dynamics) so tests need no gym.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np


class CartPoleEnv:
    """Classic cart-pole balancing (standard physics constants)."""

    n_actions = 2
    obs_dim = 4

    def __init__(self, seed: int = 0, max_steps: int = 500):
        self.rng = np.random.default_rng(seed)
        self.max_steps = max_steps
        self.gravity = 9.8
        self.masscart, self.masspole = 1.0, 0.1
        self.length = 0.5
        self.force_mag = 10.0
        self.tau = 0.02
        self.theta_lim = 12 * 2 * np.pi / 360
        self.x_lim = 2.4
        self._steps = 0
        self.state = None

    def reset(self, seed: Optional[int] = None):
        if seed is not None:
            self.rng = np.random.default_rng(seed)
        self.state = self.rng.uniform(-0.05, 0.05, size=4)
        self._steps = 0
        return self.state.astype(np.float32), {}

    def step(self, action: int):
        x, x_dot, th, th_dot = self.state
        force = self.force_mag if action == 1 else -self.force_mag
        costh, sinth = np.cos(th), np.sin(th)
        total_mass = self.masscart + self.masspole
        pml = self.masspole * self.length
        temp = (force + pml * th_dot ** 2 * sinth) / total_mass
        th_acc = (self.gravity * sinth - costh * temp) / (
            self.length * (4.0 / 3.0 - self.masspole * costh ** 2
                           / total_mass))
        x_acc = temp - pml * th_acc * costh / total_mass
        x += self.tau * x_dot
        x_dot += self.tau * x_acc
        th += self.tau * th_dot
        th_dot += self.tau * th_acc
        self.state = np.array([x, x_dot, th, th_dot])
        self._steps += 1
        terminated = bool(abs(x) > self.x_lim or abs(th) > self.theta_lim)
        truncated = self._steps >= self.max_steps
        return (self.state.astype(np.float32), 1.0, terminated, truncated,
                {})


class GridWorldEnv:
    """N x N gridworld, sparse goal reward with a small step penalty
    (the FrozenLake/tabular-control slice of the classic suite): start
    top-left, goal bottom-right, actions = R/L/D/U. Obs is the (row,
    col) pair normalized to [0, 1] so the same MLP policies apply."""

    n_actions = 4
    obs_dim = 2

    def __init__(self, seed: int = 0, size: int = 5,
                 max_steps: int = 40):
        # dynamics are fully deterministic: no rng (the seed parameter
        # is accepted for creator-signature uniformity only)
        self.size = size
        self.max_steps = max_steps
        self.pos = (0, 0)
        self._steps = 0

    def _obs(self):
        return np.array([self.pos[0] / (self.size - 1),
                         self.pos[1] / (self.size - 1)], np.float32)

    def reset(self, seed: Optional[int] = None):
        self.pos = (0, 0)
        self._steps = 0
        return self._obs(), {}

    def step(self, action: int):
        r, c = self.pos
        dr, dc = ((0, 1), (0, -1), (1, 0), (-1, 0))[int(action)]
        self.pos = (min(max(r + dr, 0), self.size - 1),
                    min(max(c + dc, 0), self.size - 1))
        self._steps += 1
        at_goal = self.pos == (self.size - 1, self.size - 1)
        reward = 10.0 if at_goal else -0.1
        truncated = self._steps >= self.max_steps
        return self._obs(), reward, at_goal, truncated, {}


class MountainCarEnv:
    """Classic mountain car (standard dynamics), discrete actions,
    with OPTIONAL velocity-shaped reward: the raw sparse task needs
    long-horizon exploration tricks the tuned-example CI budget does
    not buy, so the shaped variant keeps the contract honest AND
    reachable (the shaping term is documented, not hidden)."""

    n_actions = 3
    obs_dim = 2

    def __init__(self, seed: int = 0, max_steps: int = 200,
                 shaped: bool = True):
        self.rng = np.random.default_rng(seed)
        self.max_steps = max_steps
        self.shaped = shaped
        self.state = None
        self._steps = 0

    def reset(self, seed: Optional[int] = None):
        if seed is not None:
            self.rng = np.random.default_rng(seed)
        self.state = np.array([self.rng.uniform(-0.6, -0.4), 0.0])
        self._steps = 0
        return self.state.astype(np.float32), {}

    def step(self, action: int):
        pos, vel = self.state
        vel += (int(action) - 1) * 0.001 + np.cos(3 * pos) * (-0.0025)
        vel = float(np.clip(vel, -0.07, 0.07))
        pos = float(np.clip(pos + vel, -1.2, 0.6))
        if pos <= -1.2:
            vel = max(vel, 0.0)
        self.state = np.array([pos, vel])
        self._steps += 1
        done = pos >= 0.5
        reward = -1.0
        if self.shaped:
            reward += 10.0 * abs(vel)        # energy-building signal
        if done:
            reward += 100.0
        truncated = self._steps >= self.max_steps
        return (self.state.astype(np.float32), reward, done, truncated,
                {})


ENV_REGISTRY: Dict[str, Callable] = {
    "CartPole-v1": CartPoleEnv,
    "GridWorld-5x5": GridWorldEnv,
    "MountainCarShaped-v0": MountainCarEnv,
}


def register_env(name: str, creator: Callable) -> None:
    ENV_REGISTRY[name] = creator


def make_env(name_or_creator, seed: int = 0):
    if callable(name_or_creator):
        return name_or_creator(seed)
    creator = ENV_REGISTRY.get(name_or_creator)
    if creator is None:
        raise KeyError(f"unknown env {name_or_creator!r} "
                       f"(register_env first)")
    return creator(seed=seed)


class EnvRunner:
    """Actor: collects rollouts with the current policy weights."""

    def __init__(self, env_spec, policy_factory, seed: int = 0,
                 env_to_module=None, module_to_env=None):
        """``env_to_module``/``module_to_env``: optional connector
        pipelines (reference: rllib/connectors/) — observations pass
        through env_to_module before the policy; actions through
        module_to_env before the env."""
        self.env = make_env(env_spec, seed=seed)
        self.policy = policy_factory()
        self.seed = seed
        self.env_to_module = env_to_module
        self.module_to_env = module_to_env
        self._obs, _ = self.env.reset(seed=seed)
        self._episode_return = 0.0
        self.completed_returns: List[float] = []

    def _pre(self, obs):
        return self.env_to_module(obs) if self.env_to_module else obs

    def _post(self, action):
        return self.module_to_env(action) if self.module_to_env else action

    def set_weights(self, weights) -> None:
        self.policy.set_weights(weights)

    def sample(self, num_steps: int) -> Dict[str, np.ndarray]:
        """Collect num_steps transitions (episodes auto-reset)."""
        obs_buf, act_buf, rew_buf, done_buf, logp_buf = [], [], [], [], []
        for _ in range(num_steps):
            module_obs = self._pre(self._obs)
            action, logp = self.policy.act(module_obs)
            nobs, rew, term, trunc, _ = self.env.step(
                self._post(action))
            obs_buf.append(module_obs)
            act_buf.append(action)
            rew_buf.append(rew)
            done_buf.append(term or trunc)
            logp_buf.append(logp)
            self._episode_return += rew
            if term or trunc:
                self.completed_returns.append(self._episode_return)
                self._episode_return = 0.0
                self._obs, _ = self.env.reset()
                if self.env_to_module is not None:
                    self.env_to_module.reset()
            else:
                self._obs = nobs
        obs_buf.append(self._pre(self._obs))   # bootstrap observation
        return {
            "obs": np.asarray(obs_buf[:-1], np.float32),
            "next_obs_last": np.asarray(obs_buf[-1], np.float32),
            "actions": np.asarray(act_buf, np.int32),
            "rewards": np.asarray(rew_buf, np.float32),
            "dones": np.asarray(done_buf, np.bool_),
            "logp": np.asarray(logp_buf, np.float32),
        }

    def episode_returns(self, clear: bool = True) -> List[float]:
        out = list(self.completed_returns)
        if clear:
            self.completed_returns = []
        return out
