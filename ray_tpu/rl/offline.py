"""Offline RL: experience datasets, behavior cloning, offline DQN (CQL).

Reference: ``rllib/offline/`` — sample writers/readers over datasets and
offline training without an environment. TPU-first shape: experiences
live in ``ray_tpu.data`` datasets (arrow blocks, streaming shards), the
learners are jitted jax programs batched over the MXU:

- :func:`write_experiences` / :func:`read_experiences` — dataset IO for
  EnvRunner sample batches (the JsonWriter/JsonReader role, on parquet).
- :class:`BCLearner` — behavior cloning (cross-entropy on logged
  actions).
- :class:`OfflineDQNLearner` — double-DQN TD learning on logged
  transitions plus a CQL conservative penalty (logsumexp Q minus logged
  Q) so values of out-of-distribution actions stay bounded.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Iterator, List, Optional

import jax
import jax.numpy as jnp
import numpy as np


# ---------------------------------------------------------------------------
# dataset IO
# ---------------------------------------------------------------------------

def write_experiences(batches: List[Dict[str, np.ndarray]],
                      path: str) -> int:
    """Persist EnvRunner sample batches as parquet; returns row count.
    Transitions are flattened to (obs, action, reward, done, next_obs)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from ray_tpu.data.block import block_from_batch

    rows = 0
    tables = []
    for batch in batches:
        obs = np.asarray(batch["obs"], np.float32)
        nxt = np.concatenate(
            [obs[1:], np.asarray(batch["next_obs_last"],
                                 np.float32)[None]], axis=0)
        tables.append(block_from_batch({
            "obs": obs,
            "next_obs": nxt,
            "actions": np.asarray(batch["actions"], np.int64),
            "rewards": np.asarray(batch["rewards"], np.float32),
            "dones": np.asarray(batch["dones"], np.bool_),
        }))
        rows += len(batch["rewards"])
    pq.write_table(pa.concat_tables(tables), path)
    return rows


def read_experiences(paths) -> "Any":
    """Experience dataset (ray_tpu.data.Dataset over parquet shards)."""
    from ray_tpu.data import read_parquet

    return read_parquet(paths)


def iter_transition_batches(ds, batch_size: int = 256,
                            epochs: int = 1) -> Iterator[Dict]:
    for _ in range(epochs):
        for batch in ds.iter_batches(batch_size=batch_size):
            yield batch


# ---------------------------------------------------------------------------
# behavior cloning
# ---------------------------------------------------------------------------

def _mlp_init(rng, sizes):
    params = []
    for i, (m, n) in enumerate(zip(sizes[:-1], sizes[1:])):
        k = jax.random.fold_in(rng, i)
        params.append({
            "w": jax.random.normal(k, (m, n), jnp.float32) * (m ** -0.5),
            "b": jnp.zeros((n,), jnp.float32)})
    return params


def _mlp_apply(params, x):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            x = jax.nn.tanh(x)
    return x


class BCLearner:
    """Behavior cloning: cross-entropy on the logged actions."""

    def __init__(self, obs_dim: int, n_actions: int, *,
                 hidden: int = 64, lr: float = 1e-3, seed: int = 0):
        self.n_actions = n_actions
        self.params = _mlp_init(jax.random.key(seed),
                                (obs_dim, hidden, hidden, n_actions))
        self.lr = lr
        self._step = jax.jit(self._step_impl)

    def _step_impl(self, params, obs, actions):
        def loss_fn(p):
            logits = _mlp_apply(p, obs)
            logp = jax.nn.log_softmax(logits)
            nll = -jnp.take_along_axis(
                logp, actions[:, None], axis=-1).mean()
            return nll
        loss, grads = jax.value_and_grad(loss_fn)(params)
        params = jax.tree.map(lambda p, g: p - self.lr * g, params, grads)
        return params, loss

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        obs = jnp.asarray(batch["obs"], jnp.float32)
        actions = jnp.asarray(batch["actions"], jnp.int32)
        self.params, loss = self._step(self.params, obs, actions)
        return {"bc_loss": float(loss)}

    def act(self, obs) -> int:
        logits = _mlp_apply(self.params,
                            jnp.asarray(obs, jnp.float32)[None])
        return int(jnp.argmax(logits, axis=-1)[0])

    def evaluate_accuracy(self, batch: Dict[str, np.ndarray]) -> float:
        logits = _mlp_apply(self.params,
                            jnp.asarray(batch["obs"], jnp.float32))
        pred = jnp.argmax(logits, axis=-1)
        return float((pred == jnp.asarray(batch["actions"])).mean())


# ---------------------------------------------------------------------------
# offline (conservative) DQN
# ---------------------------------------------------------------------------

class OfflineDQNLearner:
    """Double-DQN TD on logged transitions + CQL penalty."""

    def __init__(self, obs_dim: int, n_actions: int, *,
                 hidden: int = 64, lr: float = 1e-3, gamma: float = 0.99,
                 cql_alpha: float = 1.0, target_update_every: int = 100,
                 seed: int = 0):
        self.params = _mlp_init(jax.random.key(seed),
                                (obs_dim, hidden, hidden, n_actions))
        self.target = jax.tree.map(lambda x: x, self.params)
        self.lr = lr
        self.gamma = gamma
        self.cql_alpha = cql_alpha
        self.target_update_every = target_update_every
        self._updates = 0
        self._step = jax.jit(self._step_impl)

    def _step_impl(self, params, target, obs, actions, rewards, dones,
                   next_obs):
        def loss_fn(p):
            q = _mlp_apply(p, obs)                        # [B, A]
            q_logged = jnp.take_along_axis(
                q, actions[:, None], axis=-1)[:, 0]
            # double DQN target: online argmax, target value
            next_q_online = _mlp_apply(p, next_obs)
            next_a = jnp.argmax(next_q_online, axis=-1)
            next_q_target = jnp.take_along_axis(
                _mlp_apply(target, next_obs), next_a[:, None],
                axis=-1)[:, 0]
            td_target = rewards + self.gamma * next_q_target * (
                1.0 - dones)
            td = jnp.mean((q_logged
                           - jax.lax.stop_gradient(td_target)) ** 2)
            # CQL: push down out-of-distribution action values
            cql = jnp.mean(jax.scipy.special.logsumexp(q, axis=-1)
                           - q_logged)
            return td + self.cql_alpha * cql, (td, cql)
        (loss, (td, cql)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        params = jax.tree.map(lambda p, g: p - self.lr * g, params, grads)
        return params, loss, td, cql

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        self.params, loss, td, cql = self._step(
            self.params, self.target,
            jnp.asarray(batch["obs"], jnp.float32),
            jnp.asarray(batch["actions"], jnp.int32),
            jnp.asarray(batch["rewards"], jnp.float32),
            jnp.asarray(batch["dones"], jnp.float32),
            jnp.asarray(batch["next_obs"], jnp.float32))
        self._updates += 1
        if self._updates % self.target_update_every == 0:
            self.target = jax.tree.map(lambda x: x, self.params)
        return {"loss": float(loss), "td_loss": float(td),
                "cql_penalty": float(cql)}

    def act(self, obs) -> int:
        q = _mlp_apply(self.params, jnp.asarray(obs, jnp.float32)[None])
        return int(jnp.argmax(q, axis=-1)[0])


def train_offline(ds, learner, *, batch_size: int = 256,
                  epochs: int = 1) -> Dict[str, float]:
    """Drive a learner over an experience dataset; returns last metrics."""
    metrics: Dict[str, float] = {}
    for batch in iter_transition_batches(ds, batch_size, epochs):
        metrics = learner.update(batch)
    return metrics
