"""ray_tpu.rl — reinforcement learning (RLlib-capability layer).

Reference: RLlib (`rllib/`, SURVEY.md §2.2): AlgorithmConfig/Algorithm,
EnvRunnerGroup rollout actors, jax Learners (PPO, DQN), env registry.
"""

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.env import CartPoleEnv, EnvRunner, register_env
from ray_tpu.rl.multi_agent import MultiAgentCartPole, MultiAgentEnvRunner

__all__ = ["Algorithm", "AlgorithmConfig", "CartPoleEnv", "EnvRunner",
           "register_env", "MultiAgentCartPole", "MultiAgentEnvRunner"]
