"""PPO learner (reference: `rllib/algorithms/ppo/` — clipped surrogate +
GAE; the Learner role of `rllib/core/learner/learner.py:108`).

Policy/value network and update are jitted jax; rollout-time action
sampling runs the same network on host-side numpy weights.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax


def _mlp_init(rng, sizes) -> List[Dict]:
    params = []
    keys = jax.random.split(rng, len(sizes) - 1)
    for k, din, dout in zip(keys, sizes[:-1], sizes[1:]):
        params.append({
            "w": jax.random.normal(k, (din, dout), jnp.float32)
            * (2.0 / din) ** 0.5,
            "b": jnp.zeros((dout,), jnp.float32)})
    return params


def _mlp_apply(params, x, final_tanh=False):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            x = jnp.tanh(x)
    return x


class ActorCriticPolicy:
    """Shared-nothing actor/critic MLPs with numpy act() for rollouts."""

    def __init__(self, obs_dim: int, n_actions: int, hidden=(64, 64),
                 seed: int = 0):
        rng = jax.random.key(seed)
        k1, k2 = jax.random.split(rng)
        self.params = {
            "pi": _mlp_init(k1, [obs_dim, *hidden, n_actions]),
            "vf": _mlp_init(k2, [obs_dim, *hidden, 1]),
        }
        self._np_pi = None
        self._rng = np.random.default_rng(seed)
        self._sync_np()

    def _sync_np(self):
        self._np_pi = jax.tree.map(np.asarray, self.params["pi"])

    def set_weights(self, params):
        self.params = params
        self._sync_np()

    def get_weights(self):
        return self.params

    def act(self, obs: np.ndarray) -> Tuple[int, float]:
        x = obs
        n = len(self._np_pi)
        for i, layer in enumerate(self._np_pi):
            x = x @ layer["w"] + layer["b"]
            if i < n - 1:
                x = np.tanh(x)
        z = x - x.max()
        p = np.exp(z)
        p /= p.sum()
        a = int(self._rng.choice(len(p), p=p))
        return a, float(np.log(p[a] + 1e-9))


def compute_gae(rewards, dones, values, last_value, gamma=0.99,
                lam=0.95):
    """Host-side GAE over a rollout (numpy; T small)."""
    T = len(rewards)
    adv = np.zeros(T, np.float32)
    last = 0.0
    for t in range(T - 1, -1, -1):
        nonterm = 0.0 if dones[t] else 1.0
        next_v = last_value if t == T - 1 else values[t + 1]
        delta = rewards[t] + gamma * next_v * nonterm - values[t]
        last = delta + gamma * lam * nonterm * last
        adv[t] = last
    returns = adv + values
    return adv, returns


class PPOLearner:
    def __init__(self, obs_dim: int, n_actions: int, *, hidden=(64, 64),
                 lr: float = 3e-4, clip: float = 0.2, vf_coef: float = 0.5,
                 ent_coef: float = 0.01, epochs: int = 4,
                 minibatch_size: int = 128, gamma: float = 0.99,
                 gae_lambda: float = 0.95, seed: int = 0):
        self.policy = ActorCriticPolicy(obs_dim, n_actions, hidden, seed)
        self.optimizer = optax.adam(lr)
        self.opt_state = self.optimizer.init(self.policy.params)
        self.clip = clip
        self.vf_coef = vf_coef
        self.ent_coef = ent_coef
        self.epochs = epochs
        self.minibatch_size = minibatch_size
        self.gamma = gamma
        self.lam = gae_lambda
        self._rng = np.random.default_rng(seed)
        self._update = jax.jit(self._update_impl)
        self._values = jax.jit(
            lambda params, obs: _mlp_apply(params["vf"], obs)[:, 0])

    def _update_impl(self, params, opt_state, batch):
        def loss_fn(p):
            logits = _mlp_apply(p["pi"], batch["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][:, None], axis=1)[:, 0]
            ratio = jnp.exp(logp - batch["logp_old"])
            adv = batch["adv"]
            unclipped = ratio * adv
            clipped = jnp.clip(ratio, 1 - self.clip, 1 + self.clip) * adv
            pi_loss = -jnp.mean(jnp.minimum(unclipped, clipped))
            v = _mlp_apply(p["vf"], batch["obs"])[:, 0]
            vf_loss = jnp.mean((v - batch["returns"]) ** 2)
            ent = -jnp.mean(
                jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
            total = pi_loss + self.vf_coef * vf_loss - self.ent_coef * ent
            return total, {"pi_loss": pi_loss, "vf_loss": vf_loss,
                           "entropy": ent}

        (loss, metrics), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt_state = self.optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        metrics["total_loss"] = loss
        return params, opt_state, metrics

    def update(self, rollouts: List[Dict[str, np.ndarray]]
               ) -> Dict[str, float]:
        """GAE + minibatched clipped-surrogate epochs over the rollouts."""
        obs = np.concatenate([r["obs"] for r in rollouts])
        actions = np.concatenate([r["actions"] for r in rollouts])
        logp_old = np.concatenate([r["logp"] for r in rollouts])
        advs, rets = [], []
        for r in rollouts:
            values = np.asarray(self._values(self.policy.params,
                                             jnp.asarray(r["obs"])))
            last_v = float(self._values(
                self.policy.params,
                jnp.asarray(r["next_obs_last"][None]))[0])
            adv, ret = compute_gae(r["rewards"], r["dones"], values,
                                   last_v, self.gamma, self.lam)
            advs.append(adv)
            rets.append(ret)
        adv = np.concatenate(advs)
        ret = np.concatenate(rets)
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)

        n = len(obs)
        metrics = {}
        for _ in range(self.epochs):
            perm = self._rng.permutation(n)
            for lo in range(0, n, self.minibatch_size):
                idx = perm[lo:lo + self.minibatch_size]
                batch = {
                    "obs": jnp.asarray(obs[idx]),
                    "actions": jnp.asarray(actions[idx]),
                    "logp_old": jnp.asarray(logp_old[idx]),
                    "adv": jnp.asarray(adv[idx]),
                    "returns": jnp.asarray(ret[idx]),
                }
                self.policy.params, self.opt_state, metrics = self._update(
                    self.policy.params, self.opt_state, batch)
        self.policy._sync_np()
        return {k: float(v) for k, v in metrics.items()}

    def get_weights(self):
        return self.policy.params
