"""SAC learner (discrete actions).

Reference capability: `rllib/algorithms/sac/` — soft actor-critic with
twin Q networks, target networks, and automatic temperature tuning
(Haarnoja et al. 2018; discrete variant per Christodoulou 2019: the
expectation over actions is exact — a sum weighted by the categorical
policy — no reparameterized sampling needed). Off-policy via the replay
buffer shared with DQN. All three updates (twin-Q, policy, temperature)
run inside one jitted step.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rl.dqn import ReplayBuffer
from ray_tpu.rl.ppo import _mlp_apply, _mlp_init


class SACPolicy:
    """Categorical policy for rollouts (stochastic sampling; numpy)."""

    def __init__(self, obs_dim: int, n_actions: int, hidden=(64, 64),
                 seed: int = 0):
        rng = jax.random.key(seed)
        self.params = {"pi": _mlp_init(rng, [obs_dim, *hidden, n_actions])}
        self._np_pi = None
        self._rng = np.random.default_rng(seed)
        self._sync_np()

    def _sync_np(self):
        self._np_pi = jax.tree.map(np.asarray, self.params["pi"])

    def set_weights(self, payload):
        self.params = {"pi": payload["pi"]}
        self._sync_np()

    def get_weights(self):
        return self.params

    def act(self, obs: np.ndarray) -> Tuple[int, float]:
        x = obs
        n = len(self._np_pi)
        for i, layer in enumerate(self._np_pi):
            x = x @ layer["w"] + layer["b"]
            if i < n - 1:
                x = np.tanh(x)
        z = x - x.max()
        p = np.exp(z)
        p /= p.sum()
        a = int(self._rng.choice(len(p), p=p))
        return a, float(np.log(p[a] + 1e-9))


class SACLearner:
    def __init__(self, obs_dim: int, n_actions: int, *, hidden=(64, 64),
                 lr: float = 3e-4, gamma: float = 0.99, tau: float = 0.01,
                 target_entropy_scale: float = 0.7,
                 buffer_capacity: int = 50_000, batch_size: int = 256,
                 updates_per_call: int = 16, seed: int = 0):
        rng = jax.random.key(seed)
        kp, k1, k2 = jax.random.split(rng, 3)
        sizes = [obs_dim, *hidden, n_actions]
        self.policy = SACPolicy(obs_dim, n_actions, hidden, seed)
        self.policy.params = {"pi": _mlp_init(kp, sizes)}
        self.policy._sync_np()
        self.q1 = _mlp_init(k1, sizes)
        self.q2 = _mlp_init(k2, sizes)
        self.q1_target = jax.tree.map(jnp.copy, self.q1)
        self.q2_target = jax.tree.map(jnp.copy, self.q2)
        self.log_alpha = jnp.zeros(())
        # exact-expectation discrete SAC target: a fraction of max entropy
        self.target_entropy = target_entropy_scale * float(
            np.log(n_actions))
        self.gamma = gamma
        self.tau = tau
        self.batch_size = batch_size
        self.updates_per_call = updates_per_call
        self.buffer = ReplayBuffer(buffer_capacity, obs_dim, seed=seed)
        self.opt = optax.adam(lr)
        self.opt_state = self.opt.init(
            {"pi": self.policy.params["pi"], "q1": self.q1, "q2": self.q2,
             "log_alpha": self.log_alpha})
        self._step = jax.jit(self._step_impl)
        self.num_updates = 0

    # -- jitted one gradient step ---------------------------------------
    def _loss(self, params, targets, batch):
        obs, actions = batch["obs"], batch["actions"]
        rewards, dones, next_obs = (batch["rewards"], batch["dones"],
                                    batch["next_obs"])
        alpha = jnp.exp(params["log_alpha"])

        # target: soft state value of s' under the CURRENT policy
        next_logits = _mlp_apply(params["pi"], next_obs)
        next_logp = jax.nn.log_softmax(next_logits)
        next_pi = jnp.exp(next_logp)
        q1_t = _mlp_apply(targets["q1"], next_obs)
        q2_t = _mlp_apply(targets["q2"], next_obs)
        minq_t = jnp.minimum(q1_t, q2_t)
        v_next = jnp.sum(next_pi * (minq_t
                                    - jax.lax.stop_gradient(alpha)
                                    * next_logp), axis=-1)
        y = jax.lax.stop_gradient(
            rewards + self.gamma * (1.0 - dones) * v_next)

        q1 = _mlp_apply(params["q1"], obs)
        q2 = _mlp_apply(params["q2"], obs)
        q1_a = jnp.take_along_axis(q1, actions[:, None], axis=1)[:, 0]
        q2_a = jnp.take_along_axis(q2, actions[:, None], axis=1)[:, 0]
        q_loss = 0.5 * (jnp.mean((q1_a - y) ** 2)
                        + jnp.mean((q2_a - y) ** 2))

        # policy: exact expectation over the categorical support
        logits = _mlp_apply(params["pi"], obs)
        logp = jax.nn.log_softmax(logits)
        pi = jnp.exp(logp)
        minq = jax.lax.stop_gradient(jnp.minimum(q1, q2))
        pi_loss = jnp.mean(jnp.sum(
            pi * (jax.lax.stop_gradient(alpha) * logp - minq), axis=-1))

        # temperature: drive policy entropy toward the target
        entropy = -jnp.sum(pi * logp, axis=-1)
        alpha_loss = jnp.mean(params["log_alpha"] * jax.lax.stop_gradient(
            entropy - self.target_entropy))

        loss = q_loss + pi_loss + alpha_loss
        return loss, {"q_loss": q_loss, "pi_loss": pi_loss,
                      "alpha": alpha, "entropy": jnp.mean(entropy)}

    def _step_impl(self, params, targets, opt_state, batch):
        (loss, aux), grads = jax.value_and_grad(
            self._loss, has_aux=True)(params, targets, batch)
        updates, opt_state = self.opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        targets = jax.tree.map(
            lambda t, s: (1.0 - self.tau) * t + self.tau * s,
            targets, {"q1": params["q1"], "q2": params["q2"]})
        aux["loss"] = loss
        return params, targets, opt_state, aux

    # -- host API --------------------------------------------------------
    def update(self, rollouts: List[Dict[str, np.ndarray]]
               ) -> Dict[str, Any]:
        for r in rollouts:
            self.buffer.add_rollout(r)
        if self.buffer.size < self.batch_size:
            return {"buffer_size": self.buffer.size}
        params = {"pi": self.policy.params["pi"], "q1": self.q1,
                  "q2": self.q2, "log_alpha": self.log_alpha}
        targets = {"q1": self.q1_target, "q2": self.q2_target}
        aux = {}
        for _ in range(self.updates_per_call):
            batch = self.buffer.sample(self.batch_size)
            jb = {k: jnp.asarray(v) for k, v in batch.items()}
            jb["dones"] = jb["dones"].astype(jnp.float32)
            params, targets, self.opt_state, aux = self._step(
                params, targets, self.opt_state, jb)
            self.num_updates += 1
        self.policy.params = {"pi": params["pi"]}
        self.policy._sync_np()
        self.q1, self.q2 = params["q1"], params["q2"]
        self.log_alpha = params["log_alpha"]
        self.q1_target, self.q2_target = targets["q1"], targets["q2"]
        out = {k: float(v) for k, v in aux.items()}
        out["num_learner_updates"] = self.num_updates
        out["buffer_size"] = self.buffer.size
        return out

    def get_weights(self):
        return {"pi": self.policy.params["pi"]}

    def set_weights(self, payload):
        self.policy.set_weights(payload)
