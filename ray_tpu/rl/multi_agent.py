"""Multi-agent RL: env API, rollout runner, per-policy training.

Reference capability: RLlib multi-agent (`rllib/env/multi_agent_env.py`,
`rllib/env/multi_agent_env_runner.py`, `rllib/algorithms/algorithm_config.py`
``.multi_agent(policies=..., policy_mapping_fn=...)``). Env API is the
RLlib dict convention: ``reset() -> (obs_dict, info)``;
``step(action_dict) -> (obs, rew, terminated, truncated, info)`` dicts
keyed by agent id, with ``terminated["__all__"]`` ending the episode.

TPU-first shape: each POLICY keeps one jitted learner (the same PPO/DQN
learners as single-agent — their update is already one compiled SPMD
program); the runner groups per-agent trajectory fragments by policy via
``policy_mapping_fn``, so N agents sharing a policy just mean more
rollout rows through the same jit. (Homogeneous-policy vmap-stacking is
a further step; per-policy jit is the RLlib-parity baseline.)
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.rl.env import CartPoleEnv, make_env


class MultiAgentCartPole:
    """N independent cart-poles, one per agent (the standard RLlib
    multi-agent test env). Agents terminate individually; the episode
    ends when every agent is done."""

    n_actions = 2
    obs_dim = 4

    def __init__(self, num_agents: int = 2, seed: int = 0,
                 max_steps: int = 200):
        self.agent_ids = [f"agent_{i}" for i in range(num_agents)]
        self._envs = {aid: CartPoleEnv(seed=seed + i, max_steps=max_steps)
                      for i, aid in enumerate(self.agent_ids)}
        self._done: Dict[str, bool] = {}

    def reset(self, seed: Optional[int] = None):
        obs = {}
        for i, (aid, env) in enumerate(self._envs.items()):
            o, _ = env.reset(None if seed is None else seed + i)
            obs[aid] = o
        self._done = {aid: False for aid in self.agent_ids}
        return obs, {}

    def step(self, action_dict: Dict[str, int]):
        obs, rew, term, trunc = {}, {}, {}, {}
        for aid, action in action_dict.items():
            if self._done.get(aid, True):
                continue
            o, r, te, tr, _ = self._envs[aid].step(action)
            rew[aid] = r
            term[aid] = te
            trunc[aid] = tr
            if te or tr:
                self._done[aid] = True
            else:
                obs[aid] = o
        all_done = all(self._done.values())
        term["__all__"] = all_done
        trunc["__all__"] = False
        return obs, rew, term, trunc, {}


class MultiAgentEnvRunner:
    """Actor: collects rollouts from a multi-agent env, grouped by
    policy. ``sample`` returns {policy_id: [per-agent fragment, ...]} in
    the exact single-agent batch format, so the per-policy learners are
    unchanged — each agent's fragment keeps its own bootstrap
    observation for GAE."""

    def __init__(self, env_spec, policy_factories: Dict[str, Callable],
                 policy_mapping_fn: Callable[[str], str], seed: int = 0):
        self.env = make_env(env_spec, seed=seed)
        self.policies = {pid: factory()
                         for pid, factory in policy_factories.items()}
        self.mapping = policy_mapping_fn
        self._obs, _ = self.env.reset(seed=seed)
        self._ep_return: Dict[str, float] = {}
        self.completed_returns: Dict[str, List[float]] = {}

    def set_weights(self, weights: Dict[str, Any]) -> None:
        for pid, w in weights.items():
            self.policies[pid].set_weights(w)

    def sample(self, num_steps: int) -> Dict[str, List[Dict]]:
        bufs: Dict[str, Dict[str, list]] = {}   # agent -> buffers

        def buf(aid):
            return bufs.setdefault(aid, {
                "obs": [], "actions": [], "rewards": [], "dones": [],
                "logp": []})

        for _ in range(num_steps):
            actions, logps = {}, {}
            for aid, o in self._obs.items():
                pid = self.mapping(aid)
                if pid not in self.policies:
                    raise ValueError(
                        f"policy_mapping_fn({aid!r}) -> {pid!r}, not in "
                        f"policies {sorted(self.policies)}")
                pol = self.policies[pid]
                a, lp = pol.act(o)
                actions[aid] = a
                logps[aid] = lp
            nobs, rew, term, trunc, _ = self.env.step(actions)
            # an env may end the EPISODE via __all__ (shared time limit,
            # one agent winning) without flagging every live agent: the
            # reset below must not let trajectories bootstrap across it
            episode_over = bool(term.get("__all__")
                                or trunc.get("__all__"))
            for aid in actions:
                b = buf(aid)
                b["obs"].append(self._obs[aid])
                b["actions"].append(actions[aid])
                b["rewards"].append(rew.get(aid, 0.0))
                done = (term.get(aid, False) or trunc.get(aid, False)
                        or episode_over)
                b["dones"].append(done)
                b["logp"].append(logps[aid])
                self._ep_return[aid] = (self._ep_return.get(aid, 0.0)
                                        + rew.get(aid, 0.0))
                # keep the agent's last obs around for the bootstrap
                # even after it leaves the obs dict
                b["last_obs"] = nobs.get(aid, self._obs[aid])
                if done:
                    self.completed_returns.setdefault(aid, []).append(
                        self._ep_return.pop(aid, 0.0))
            if episode_over:
                self._obs, _ = self.env.reset()
            else:
                # agents keep their previous obs only if still live
                self._obs = nobs

        out: Dict[str, List[Dict]] = {}
        for aid, b in bufs.items():
            if not b["obs"]:
                continue
            fragment = {
                "obs": np.asarray(b["obs"], np.float32),
                "next_obs_last": np.asarray(b["last_obs"], np.float32),
                "actions": np.asarray(b["actions"], np.int32),
                "rewards": np.asarray(b["rewards"], np.float32),
                "dones": np.asarray(b["dones"], np.bool_),
                "logp": np.asarray(b["logp"], np.float32),
            }
            out.setdefault(self.mapping(aid), []).append(fragment)
        return out

    def episode_returns(self, clear: bool = True) -> List[float]:
        out = [x for v in self.completed_returns.values() for x in v]
        if clear:
            self.completed_returns = {}
        return out
