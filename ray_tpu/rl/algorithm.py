"""Algorithm + AlgorithmConfig (reference: `rllib/algorithms/algorithm.py`
Algorithm.step :986/training_step :2047 and `algorithm_config.py` fluent
config; `env_runner_group.py` parallel sample + sync_weights
:570 — SURVEY.md §8.11).

Control loop per iteration: EnvRunner actors sample in parallel →
learner.update (jitted jax) → broadcast weights back to runners.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rl.env import EnvRunner, make_env


@dataclasses.dataclass
class AlgorithmConfig:
    algo: str = "PPO"
    env: Any = "CartPole-v1"
    num_env_runners: int = 2
    rollout_fragment_length: int = 256
    train_iterations_per_call: int = 1
    learner_kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    seed: int = 0
    # connector FACTORIES (each runner needs its own stateful pipeline;
    # reference: rllib/connectors/)
    env_to_module_connector: Any = None
    module_to_env_connector: Any = None
    # multi-agent (reference: algorithm_config.py .multi_agent()):
    # policies maps policy_id -> per-policy learner_kwargs override (or
    # None); policy_mapping_fn maps agent_id -> policy_id
    policies: Optional[Dict[str, Any]] = None
    policy_mapping_fn: Optional[Callable[[str], str]] = None

    # fluent API (reference AlgorithmConfig.environment/.env_runners/...)
    def environment(self, env) -> "AlgorithmConfig":
        self.env = env
        return self

    def env_runners(self, num_env_runners: int,
                    rollout_fragment_length: Optional[int] = None
                    ) -> "AlgorithmConfig":
        self.num_env_runners = num_env_runners
        if rollout_fragment_length:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kwargs) -> "AlgorithmConfig":
        self.learner_kwargs.update(kwargs)
        return self

    def connectors(self, env_to_module=None, module_to_env=None
                   ) -> "AlgorithmConfig":
        """Factories returning a Connector/ConnectorPipeline per runner."""
        self.env_to_module_connector = env_to_module
        self.module_to_env_connector = module_to_env
        return self

    def multi_agent(self, policies: Dict[str, Any],
                    policy_mapping_fn: Callable[[str], str]
                    ) -> "AlgorithmConfig":
        """Train several policies against a multi-agent env (reference
        AlgorithmConfig.multi_agent). ``policies``: policy_id ->
        learner_kwargs override dict (or None for defaults)."""
        self.policies = dict(policies)
        self.policy_mapping_fn = policy_mapping_fn
        return self

    def build(self) -> "Algorithm":
        return Algorithm(self)


def _build_learner(algo: str, obs_dim: int, n_actions: int, seed: int,
                   learner_kwargs: Dict[str, Any]):
    """(learner, policy_factory) for one policy of ``algo``."""
    algo = algo.upper()
    if algo == "PPO":
        from ray_tpu.rl.ppo import ActorCriticPolicy, PPOLearner
        learner = PPOLearner(obs_dim, n_actions, seed=seed,
                             **learner_kwargs)
        factory = lambda: ActorCriticPolicy(  # noqa: E731
            obs_dim, n_actions, seed=seed)
    elif algo == "DQN":
        from ray_tpu.rl.dqn import DQNLearner, QPolicy
        learner = DQNLearner(obs_dim, n_actions, seed=seed,
                             **learner_kwargs)
        factory = lambda: QPolicy(  # noqa: E731
            obs_dim, n_actions, seed=seed)
    elif algo in ("IMPALA", "APPO"):
        from ray_tpu.rl.impala import APPOLearner, ImpalaLearner
        from ray_tpu.rl.ppo import ActorCriticPolicy
        cls = APPOLearner if algo == "APPO" else ImpalaLearner
        learner = cls(obs_dim, n_actions, seed=seed, **learner_kwargs)
        factory = lambda: ActorCriticPolicy(  # noqa: E731
            obs_dim, n_actions, seed=seed)
    elif algo == "SAC":
        from ray_tpu.rl.sac import SACLearner, SACPolicy
        learner = SACLearner(obs_dim, n_actions, seed=seed,
                             **learner_kwargs)
        factory = lambda: SACPolicy(  # noqa: E731
            obs_dim, n_actions, seed=seed)
    else:
        raise ValueError(f"unknown algo {algo!r}")
    return learner, factory


class Algorithm:
    def __init__(self, config: AlgorithmConfig):
        self.config = config
        if config.policies:
            self._init_multi_agent()
            return
        probe = make_env(config.env, seed=0)
        obs_dim = probe.obs_dim
        n_actions = probe.n_actions

        if config.env_to_module_connector is not None:
            # the policy sees CONNECTED observations; size it accordingly
            probe_pipeline = config.env_to_module_connector()
            obs_dim = int(np.asarray(
                probe_pipeline(probe.reset(seed=0)[0])).shape[-1])

        self.learner, policy_factory = _build_learner(
            config.algo, obs_dim, n_actions, config.seed,
            config.learner_kwargs)

        # Resolve string env specs against the DRIVER's registry before the
        # runners cross the process boundary (reference: RLlib ships the
        # env_creator callable to rollout workers, not a registry name).
        from ray_tpu.rl.env import ENV_REGISTRY
        env_spec = config.env
        if isinstance(env_spec, str) and env_spec in ENV_REGISTRY:
            env_spec = ENV_REGISTRY[env_spec]
        runner_cls = ray_tpu.remote(EnvRunner)
        def _runner_kwargs(i):
            kw = {"seed": config.seed + 1 + i}
            if config.env_to_module_connector is not None:
                kw["env_to_module"] = config.env_to_module_connector()
            if config.module_to_env_connector is not None:
                kw["module_to_env"] = config.module_to_env_connector()
            return kw

        self.runners = [
            runner_cls.remote(env_spec, policy_factory,
                              **_runner_kwargs(i))
            for i in range(config.num_env_runners)]
        self._sync_weights()
        self.iteration = 0
        # IMPALA: one sample per runner stays permanently in flight
        # (the async actor-learner queue); refs survive across train()
        # calls.
        self._in_flight: Dict[Any, Any] = {}

    # -- multi-agent (reference: rllib multi_agent_env_runner) ------------
    def _init_multi_agent(self) -> None:
        from ray_tpu.rl.multi_agent import MultiAgentEnvRunner
        cfg = self.config
        if cfg.policy_mapping_fn is None:
            raise ValueError("multi_agent() needs a policy_mapping_fn")
        if (cfg.env_to_module_connector is not None
                or cfg.module_to_env_connector is not None):
            raise ValueError(
                "connectors are not supported on the multi-agent path "
                "yet — they would be silently ignored")
        probe = make_env(cfg.env, seed=0)   # handles callables too
        obs_dim, n_actions = probe.obs_dim, probe.n_actions
        # config-time mapping validation: a bad policy_mapping_fn must
        # fail HERE, not as a KeyError inside a remote runner
        for aid in getattr(probe, "agent_ids", []):
            pid = cfg.policy_mapping_fn(aid)
            if pid not in cfg.policies:
                raise ValueError(
                    f"policy_mapping_fn({aid!r}) -> {pid!r}, which is "
                    f"not in policies {sorted(cfg.policies)}")
        self.learners: Dict[str, Any] = {}
        factories: Dict[str, Any] = {}
        for idx, (pid, overrides) in enumerate(cfg.policies.items()):
            kw = dict(cfg.learner_kwargs)
            kw.update(overrides or {})
            # per-policy seed offset: distinct policies must not start
            # bit-identical (self-play symmetry breaking)
            self.learners[pid], factories[pid] = _build_learner(
                cfg.algo, obs_dim, n_actions, cfg.seed + 1000 * idx, kw)
        from ray_tpu.rl.env import ENV_REGISTRY
        env_spec = cfg.env
        if isinstance(env_spec, str) and env_spec in ENV_REGISTRY:
            env_spec = ENV_REGISTRY[env_spec]
        runner_cls = ray_tpu.remote(MultiAgentEnvRunner)
        self.runners = [
            runner_cls.remote(env_spec, factories, cfg.policy_mapping_fn,
                              seed=cfg.seed + 1 + i)
            for i in range(cfg.num_env_runners)]
        self.learner = None
        self._in_flight = {}
        self._sync_weights()
        self.iteration = 0

    def _train_multi_agent(self) -> Dict[str, Any]:
        cfg = self.config
        if cfg.algo.upper() in ("IMPALA", "APPO"):
            return self._train_multi_agent_async()
        metrics: Dict[str, Any] = {}
        for _ in range(cfg.train_iterations_per_call):
            sampled = ray_tpu.get([
                r.sample.remote(cfg.rollout_fragment_length)
                for r in self.runners])
            by_policy: Dict[str, list] = {}
            for batch in sampled:
                for pid, frags in batch.items():
                    by_policy.setdefault(pid, []).extend(frags)
            for pid, frags in by_policy.items():
                m = self.learners[pid].update(frags)
                metrics.update({f"{pid}/{k}": v for k, v in m.items()})
            self._sync_weights()
        return self._finish_iteration(metrics)

    def _train_multi_agent_async(self) -> Dict[str, Any]:
        """Multi-agent IMPALA/APPO: each delivered batch updates every
        policy it contains; ONLY those policies' fresh weights go back
        to the delivering runner (set_weights takes partial dicts) —
        V-trace corrects the per-policy sampler lag."""
        def consume(batch, metrics):
            payload = {}
            for pid, frags in batch.items():
                m = self.learners[pid].update(frags)
                metrics.update({f"{pid}/{k}": v for k, v in m.items()})
                payload[pid] = self.learners[pid].get_weights()
            return payload

        return self._run_async_loop(consume)

    def _run_async_loop(self, consume) -> Dict[str, Any]:
        """Shared IMPALA-style skeleton: one sample per runner stays in
        flight; ``consume(result, metrics)`` applies the update and
        returns the weights payload for the delivering runner."""
        cfg = self.config
        if not self._in_flight:
            self._in_flight = {
                r.sample.remote(cfg.rollout_fragment_length): r
                for r in self.runners}
        metrics: Dict[str, Any] = {}
        updates = cfg.train_iterations_per_call * len(self.runners)
        for _ in range(updates):
            done, _ = ray_tpu.wait(list(self._in_flight), num_returns=1)
            runner = self._in_flight.pop(done[0])
            result = ray_tpu.get(done[0])
            payload = consume(result, metrics)
            runner.set_weights.remote(ray_tpu.put(payload))
            self._in_flight[
                runner.sample.remote(cfg.rollout_fragment_length)] = runner
        return self._finish_iteration(metrics)

    def _finish_iteration(self, metrics: Dict[str, Any]) -> Dict[str, Any]:
        """Shared per-train() tail: bump the counter, fold in episode
        stats gathered from every runner."""
        self.iteration += 1
        returns = [x for r in self.runners
                   for x in ray_tpu.get(r.episode_returns.remote())]
        metrics.update({
            "training_iteration": self.iteration,
            "episode_return_mean": float(np.mean(returns))
            if returns else float("nan"),
            "num_episodes": len(returns),
        })
        return metrics

    def _sync_weights(self) -> None:
        if self.config.policies:
            w = ray_tpu.put({pid: ln.get_weights()
                             for pid, ln in self.learners.items()})
        else:
            w = ray_tpu.put(self.learner.get_weights())
        ray_tpu.get([r.set_weights.remote(w) for r in self.runners])

    def _train_async(self) -> Dict[str, Any]:
        """IMPALA iteration: process fragments AS THEY LAND (no barrier).
        Each runner keeps one sample in flight; the learner updates per
        fragment and pushes fresh weights only to the runner that just
        delivered (reference: IMPALA's actor-learner queue — samplers
        run on stale weights, V-trace corrects the lag)."""
        def consume(rollout, metrics):
            metrics.update(self.learner.update([rollout]))
            return self.learner.get_weights()

        return self._run_async_loop(consume)

    def train(self) -> Dict[str, Any]:
        """One training iteration (reference Algorithm.step)."""
        cfg = self.config
        if cfg.policies:
            return self._train_multi_agent()
        if cfg.algo.upper() in ("IMPALA", "APPO"):
            return self._train_async()
        metrics: Dict[str, Any] = {}
        for _ in range(cfg.train_iterations_per_call):
            rollouts = ray_tpu.get([
                r.sample.remote(cfg.rollout_fragment_length)
                for r in self.runners])
            metrics = self.learner.update(rollouts)
            self._sync_weights()
        return self._finish_iteration(metrics)

    def stop(self) -> None:
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
