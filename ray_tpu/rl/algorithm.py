"""Algorithm + AlgorithmConfig (reference: `rllib/algorithms/algorithm.py`
Algorithm.step :986/training_step :2047 and `algorithm_config.py` fluent
config; `env_runner_group.py` parallel sample + sync_weights
:570 — SURVEY.md §8.11).

Control loop per iteration: EnvRunner actors sample in parallel →
learner.update (jitted jax) → broadcast weights back to runners.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rl.env import EnvRunner, make_env


@dataclasses.dataclass
class AlgorithmConfig:
    algo: str = "PPO"
    env: Any = "CartPole-v1"
    num_env_runners: int = 2
    rollout_fragment_length: int = 256
    train_iterations_per_call: int = 1
    learner_kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    seed: int = 0
    # connector FACTORIES (each runner needs its own stateful pipeline;
    # reference: rllib/connectors/)
    env_to_module_connector: Any = None
    module_to_env_connector: Any = None

    # fluent API (reference AlgorithmConfig.environment/.env_runners/...)
    def environment(self, env) -> "AlgorithmConfig":
        self.env = env
        return self

    def env_runners(self, num_env_runners: int,
                    rollout_fragment_length: Optional[int] = None
                    ) -> "AlgorithmConfig":
        self.num_env_runners = num_env_runners
        if rollout_fragment_length:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kwargs) -> "AlgorithmConfig":
        self.learner_kwargs.update(kwargs)
        return self

    def connectors(self, env_to_module=None, module_to_env=None
                   ) -> "AlgorithmConfig":
        """Factories returning a Connector/ConnectorPipeline per runner."""
        self.env_to_module_connector = env_to_module
        self.module_to_env_connector = module_to_env
        return self

    def build(self) -> "Algorithm":
        return Algorithm(self)


class Algorithm:
    def __init__(self, config: AlgorithmConfig):
        self.config = config
        probe = make_env(config.env, seed=0)
        obs_dim = probe.obs_dim
        n_actions = probe.n_actions

        if config.env_to_module_connector is not None:
            # the policy sees CONNECTED observations; size it accordingly
            probe_pipeline = config.env_to_module_connector()
            obs_dim = int(np.asarray(
                probe_pipeline(probe.reset(seed=0)[0])).shape[-1])

        if config.algo.upper() == "PPO":
            from ray_tpu.rl.ppo import ActorCriticPolicy, PPOLearner
            self.learner = PPOLearner(obs_dim, n_actions,
                                      seed=config.seed,
                                      **config.learner_kwargs)
            policy_factory = lambda: ActorCriticPolicy(  # noqa: E731
                obs_dim, n_actions, seed=config.seed)
        elif config.algo.upper() == "DQN":
            from ray_tpu.rl.dqn import DQNLearner, QPolicy
            self.learner = DQNLearner(obs_dim, n_actions,
                                      seed=config.seed,
                                      **config.learner_kwargs)
            policy_factory = lambda: QPolicy(  # noqa: E731
                obs_dim, n_actions, seed=config.seed)
        elif config.algo.upper() in ("IMPALA", "APPO"):
            from ray_tpu.rl.impala import APPOLearner, ImpalaLearner
            from ray_tpu.rl.ppo import ActorCriticPolicy
            cls = (APPOLearner if config.algo.upper() == "APPO"
                   else ImpalaLearner)
            self.learner = cls(obs_dim, n_actions, seed=config.seed,
                               **config.learner_kwargs)
            policy_factory = lambda: ActorCriticPolicy(  # noqa: E731
                obs_dim, n_actions, seed=config.seed)
        elif config.algo.upper() == "SAC":
            from ray_tpu.rl.sac import SACLearner, SACPolicy
            self.learner = SACLearner(obs_dim, n_actions,
                                      seed=config.seed,
                                      **config.learner_kwargs)
            policy_factory = lambda: SACPolicy(  # noqa: E731
                obs_dim, n_actions, seed=config.seed)
        else:
            raise ValueError(f"unknown algo {config.algo!r}")

        # Resolve string env specs against the DRIVER's registry before the
        # runners cross the process boundary (reference: RLlib ships the
        # env_creator callable to rollout workers, not a registry name).
        from ray_tpu.rl.env import ENV_REGISTRY
        env_spec = config.env
        if isinstance(env_spec, str) and env_spec in ENV_REGISTRY:
            env_spec = ENV_REGISTRY[env_spec]
        runner_cls = ray_tpu.remote(EnvRunner)
        def _runner_kwargs(i):
            kw = {"seed": config.seed + 1 + i}
            if config.env_to_module_connector is not None:
                kw["env_to_module"] = config.env_to_module_connector()
            if config.module_to_env_connector is not None:
                kw["module_to_env"] = config.module_to_env_connector()
            return kw

        self.runners = [
            runner_cls.remote(env_spec, policy_factory,
                              **_runner_kwargs(i))
            for i in range(config.num_env_runners)]
        self._sync_weights()
        self.iteration = 0
        # IMPALA: one sample per runner stays permanently in flight
        # (the async actor-learner queue); refs survive across train()
        # calls.
        self._in_flight: Dict[Any, Any] = {}

    def _sync_weights(self) -> None:
        w = ray_tpu.put(self.learner.get_weights())
        ray_tpu.get([r.set_weights.remote(w) for r in self.runners])

    def _train_async(self) -> Dict[str, Any]:
        """IMPALA iteration: process fragments AS THEY LAND (no barrier).
        Each runner keeps one sample in flight; the learner updates per
        fragment and pushes fresh weights only to the runner that just
        delivered (reference: IMPALA's actor-learner queue — samplers
        run on stale weights, V-trace corrects the lag)."""
        cfg = self.config
        if not self._in_flight:
            self._in_flight = {
                r.sample.remote(cfg.rollout_fragment_length): r
                for r in self.runners}
        metrics: Dict[str, Any] = {}
        updates = cfg.train_iterations_per_call * len(self.runners)
        for _ in range(updates):
            done, _ = ray_tpu.wait(list(self._in_flight), num_returns=1)
            runner = self._in_flight.pop(done[0])
            rollout = ray_tpu.get(done[0])
            metrics = self.learner.update([rollout])
            runner.set_weights.remote(
                ray_tpu.put(self.learner.get_weights()))
            self._in_flight[
                runner.sample.remote(cfg.rollout_fragment_length)] = runner
        self.iteration += 1
        returns = [x for r in self.runners
                   for x in ray_tpu.get(r.episode_returns.remote())]
        metrics.update({
            "training_iteration": self.iteration,
            "episode_return_mean": float(np.mean(returns))
            if returns else float("nan"),
            "num_episodes": len(returns),
        })
        return metrics

    def train(self) -> Dict[str, Any]:
        """One training iteration (reference Algorithm.step)."""
        cfg = self.config
        if cfg.algo.upper() in ("IMPALA", "APPO"):
            return self._train_async()
        metrics: Dict[str, Any] = {}
        for _ in range(cfg.train_iterations_per_call):
            rollouts = ray_tpu.get([
                r.sample.remote(cfg.rollout_fragment_length)
                for r in self.runners])
            metrics = self.learner.update(rollouts)
            self._sync_weights()
        self.iteration += 1
        returns = [x for r in self.runners
                   for x in ray_tpu.get(r.episode_returns.remote())]
        metrics.update({
            "training_iteration": self.iteration,
            "episode_return_mean": float(np.mean(returns))
            if returns else float("nan"),
            "num_episodes": len(returns),
        })
        return metrics

    def stop(self) -> None:
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
