"""DQN learner (reference: `rllib/algorithms/dqn/` — replay buffer,
target network, epsilon-greedy)."""

from __future__ import annotations

from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rl.ppo import _mlp_apply, _mlp_init


class ReplayBuffer:
    def __init__(self, capacity: int, obs_dim: int, seed: int = 0):
        self.capacity = capacity
        self.obs = np.zeros((capacity, obs_dim), np.float32)
        self.next_obs = np.zeros((capacity, obs_dim), np.float32)
        self.actions = np.zeros(capacity, np.int32)
        self.rewards = np.zeros(capacity, np.float32)
        self.dones = np.zeros(capacity, np.bool_)
        self.size = 0
        self.pos = 0
        self.rng = np.random.default_rng(seed)

    def add_rollout(self, r: Dict[str, np.ndarray]) -> None:
        T = len(r["rewards"])
        obs = r["obs"]
        next_obs = np.concatenate([obs[1:], r["next_obs_last"][None]])
        # episode boundaries: next_obs after done is a reset obs — the
        # (1 - done) mask in the target makes the value irrelevant.
        for t in range(T):
            i = self.pos
            self.obs[i] = obs[t]
            self.next_obs[i] = next_obs[t]
            self.actions[i] = r["actions"][t]
            self.rewards[i] = r["rewards"][t]
            self.dones[i] = r["dones"][t]
            self.pos = (self.pos + 1) % self.capacity
            self.size = min(self.size + 1, self.capacity)

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self.rng.integers(0, self.size, batch_size)
        return {"obs": self.obs[idx], "next_obs": self.next_obs[idx],
                "actions": self.actions[idx],
                "rewards": self.rewards[idx], "dones": self.dones[idx]}


class QPolicy:
    """Epsilon-greedy behavior policy over a Q-network."""

    def __init__(self, obs_dim: int, n_actions: int, hidden=(64, 64),
                 seed: int = 0, epsilon: float = 1.0):
        self.params = {"q": _mlp_init(jax.random.key(seed),
                                      [obs_dim, *hidden, n_actions])}
        self.n_actions = n_actions
        self.epsilon = epsilon
        self._rng = np.random.default_rng(seed)
        self._np_q = jax.tree.map(np.asarray, self.params["q"])

    def set_weights(self, payload):
        params, epsilon = payload
        self.params = params
        self.epsilon = epsilon
        self._np_q = jax.tree.map(np.asarray, self.params["q"])

    def act(self, obs: np.ndarray) -> Tuple[int, float]:
        if self._rng.random() < self.epsilon:
            return int(self._rng.integers(self.n_actions)), 0.0
        x = obs
        n = len(self._np_q)
        for i, layer in enumerate(self._np_q):
            x = x @ layer["w"] + layer["b"]
            if i < n - 1:
                x = np.tanh(x)
        return int(np.argmax(x)), 0.0


class DQNLearner:
    def __init__(self, obs_dim: int, n_actions: int, *, hidden=(64, 64),
                 lr: float = 1e-3, gamma: float = 0.99,
                 buffer_size: int = 50_000, batch_size: int = 64,
                 target_update_every: int = 10,
                 epsilon_decay: float = 0.97, epsilon_min: float = 0.05,
                 updates_per_iter: int = 32, seed: int = 0):
        self.policy = QPolicy(obs_dim, n_actions, hidden, seed)
        self.target_params = jax.tree.map(jnp.copy, self.policy.params)
        self.buffer = ReplayBuffer(buffer_size, obs_dim, seed)
        self.optimizer = optax.adam(lr)
        self.opt_state = self.optimizer.init(self.policy.params)
        self.gamma = gamma
        self.batch_size = batch_size
        self.target_update_every = target_update_every
        self.epsilon_decay = epsilon_decay
        self.epsilon_min = epsilon_min
        self.updates_per_iter = updates_per_iter
        self._updates = 0
        self._step = jax.jit(self._step_impl)

    def _step_impl(self, params, target, opt_state, batch):
        def loss_fn(p):
            q = _mlp_apply(p["q"], batch["obs"])
            q_sel = jnp.take_along_axis(
                q, batch["actions"][:, None], axis=1)[:, 0]
            q_next = _mlp_apply(target["q"], batch["next_obs"])
            tgt = batch["rewards"] + self.gamma * jnp.max(q_next, -1) * (
                1.0 - batch["dones"].astype(jnp.float32))
            return jnp.mean((q_sel - jax.lax.stop_gradient(tgt)) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = self.optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    def update(self, rollouts: List[Dict[str, np.ndarray]]
               ) -> Dict[str, float]:
        for r in rollouts:
            self.buffer.add_rollout(r)
        if self.buffer.size < self.batch_size:
            return {"td_loss": float("nan")}
        loss = 0.0
        for _ in range(self.updates_per_iter):
            batch = {k: jnp.asarray(v)
                     for k, v in self.buffer.sample(self.batch_size)
                     .items()}
            self.policy.params, self.opt_state, loss = self._step(
                self.policy.params, self.target_params, self.opt_state,
                batch)
            self._updates += 1
            if self._updates % self.target_update_every == 0:
                self.target_params = jax.tree.map(jnp.copy,
                                                  self.policy.params)
        self.policy.epsilon = max(self.epsilon_min,
                                  self.policy.epsilon
                                  * self.epsilon_decay)
        return {"td_loss": float(loss),
                "epsilon": self.policy.epsilon}

    def get_weights(self):
        return (self.policy.params, self.policy.epsilon)
