"""Connector pipelines: composable transforms between env and module.

Reference: ``rllib/connectors/`` — env→module connectors preprocess
observations before the policy sees them; module→env connectors
postprocess actions before the env executes them. Pipelines are
stateful, serializable objects shipped to every EnvRunner so the exact
preprocessing travels with the policy.

TPU note: connectors run HOST-side in rollout workers (numpy); the
jitted policy sees already-normalized fixed-shape arrays, which keeps
one XLA specialization per pipeline output shape.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np


class Connector:
    """One transform. ``__call__(data)`` maps an observation (env→module)
    or an action (module→env)."""

    def __call__(self, data):
        raise NotImplementedError

    def reset(self) -> None:
        """Episode boundary (stateful connectors clear here)."""


class ConnectorPipeline(Connector):
    def __init__(self, connectors: Optional[List[Connector]] = None):
        self.connectors = list(connectors or [])

    def append(self, connector: Connector) -> "ConnectorPipeline":
        self.connectors.append(connector)
        return self

    def __call__(self, data):
        for c in self.connectors:
            data = c(data)
        return data

    def reset(self) -> None:
        for c in self.connectors:
            c.reset()

    @property
    def output_multiplier(self) -> int:
        """Observation-width multiplier (FrameStack widens the input)."""
        mult = 1
        for c in self.connectors:
            mult *= getattr(c, "obs_multiplier", 1)
        return mult


# -- env -> module ----------------------------------------------------------

class MeanStdObservationNormalizer(Connector):
    """Running mean/std normalization (the MeanStdFilter connector)."""

    def __init__(self, clip: float = 10.0):
        self.clip = clip
        self._count = 1e-4
        self._mean: Optional[np.ndarray] = None
        self._m2: Optional[np.ndarray] = None

    def __call__(self, obs):
        obs = np.asarray(obs, np.float32)
        if self._mean is None:
            self._mean = np.zeros_like(obs)
            self._m2 = np.ones_like(obs)
        # Welford update
        self._count += 1
        delta = obs - self._mean
        self._mean = self._mean + delta / self._count
        self._m2 = self._m2 + delta * (obs - self._mean)
        std = np.sqrt(self._m2 / self._count) + 1e-8
        return np.clip((obs - self._mean) / std, -self.clip, self.clip)


class FrameStack(Connector):
    """Concatenate the last N observations (partial observability)."""

    def __init__(self, n: int = 4):
        self.n = n
        self.obs_multiplier = n
        self._frames: deque = deque(maxlen=n)

    def __call__(self, obs):
        obs = np.asarray(obs, np.float32)
        while len(self._frames) < self.n - 1:
            self._frames.append(np.zeros_like(obs))
        self._frames.append(obs)
        return np.concatenate(list(self._frames), axis=-1)

    def reset(self) -> None:
        self._frames.clear()


class ObservationClipper(Connector):
    def __init__(self, lo: float = -10.0, hi: float = 10.0):
        self.lo, self.hi = lo, hi

    def __call__(self, obs):
        return np.clip(np.asarray(obs, np.float32), self.lo, self.hi)


# -- module -> env ----------------------------------------------------------

class ClipActions(Connector):
    def __init__(self, lo: float, hi: float):
        self.lo, self.hi = lo, hi

    def __call__(self, action):
        return np.clip(action, self.lo, self.hi)


class UnsquashActions(Connector):
    """Map tanh-squashed (-1,1) module outputs to the env's bounds."""

    def __init__(self, lo: float, hi: float):
        self.lo, self.hi = lo, hi

    def __call__(self, action):
        a = np.asarray(action, np.float32)
        return self.lo + (a + 1.0) * 0.5 * (self.hi - self.lo)
