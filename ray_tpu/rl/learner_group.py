"""LearnerGroup: data-parallel learner updates over a device mesh.

Reference capability: ``rllib/core/learner/learner_group.py:234`` — N
DDP learner workers, each on its own GPU, gradients all-reduced by NCCL.
TPU-first shape: the group is ONE jitted SPMD update over a ``dp`` mesh
axis — the minibatch is sharded across devices, params/optimizer state
stay replicated, and XLA inserts the gradient ``psum`` exactly where DDP
would run its all-reduce. No learner actors, no weight broadcast between
"learners": replication is maintained by the compiler.

Works with any learner whose jitted step is a pure 3-arg function
``(params, opt_state, batch) -> (params, opt_state, metrics)`` — PPO
and IMPALA in-tree: batch-major leaves shard over dp, side inputs
(IMPALA's bootstrap observation) stay replicated. (SAC's step threads a
4th ``targets`` pytree and would need its own placement; not wrapped.)
"""

from __future__ import annotations

from typing import Any, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class LearnerGroup:
    """Wrap a learner so its gradient step runs data-parallel over a
    mesh. The learner's host-side logic (GAE, replay, minibatching) is
    untouched; only the jitted step is re-bound with shardings."""

    def __init__(self, learner: Any, *, mesh: Optional[Mesh] = None,
                 num_learners: Optional[int] = None,
                 step_attr: str = "_update",
                 impl_attr: str = "_update_impl",
                 ragged: str = "replicate"):
        if ragged not in ("replicate", "truncate"):
            raise ValueError(f"ragged must be 'replicate' or 'truncate', "
                             f"got {ragged!r}")
        self.ragged = ragged
        devices = jax.devices()
        n = num_learners or len(devices)
        if mesh is None:
            if len(devices) < n:
                raise ValueError(
                    f"num_learners={n} but only {len(devices)} devices")
            # the shared mesh vocabulary (all six named axes, size-1
            # included) so learner shardings compose with the rest of
            # the parallel stack
            from ray_tpu.parallel.mesh import MeshSpec, build_mesh

            mesh = build_mesh(MeshSpec(dp=n), devices[:n])
        if "dp" not in mesh.shape:
            raise ValueError(
                f"LearnerGroup needs a 'dp' mesh axis; mesh has "
                f"{tuple(mesh.shape)}")
        if num_learners is not None and mesh.shape["dp"] != num_learners:
            raise ValueError(
                f"num_learners={num_learners} conflicts with the "
                f"mesh's dp={mesh.shape['dp']}")
        self.mesh = mesh
        self.num_learners = mesh.shape["dp"]
        self.learner = learner

        replicated = NamedSharding(mesh, P())
        batch_sharded = NamedSharding(mesh, P("dp"))
        impl = getattr(learner, impl_attr)
        jitted = jax.jit(impl)   # shardings propagate from the inputs

        def step(params, opt_state, batch):
            # Shard only batch-major leaves (dim 0 == the batch/time
            # length); side inputs like IMPALA's next_obs_last stay
            # replicated. A ragged tail (rows % dp != 0) runs replicated
            # by default: truncating is unsound for time-major learners
            # whose side inputs bootstrap from the step AFTER the last
            # row (IMPALA's next_obs_last) — dropping tail steps would
            # silently bias the V-trace targets. ``ragged="truncate"``
            # opts i.i.d.-minibatch learners (PPO) back into dropping
            # the tail, where the epoch permutation re-covers those rows.
            dp = self.num_learners
            rows = max((x.shape[0] for x in jax.tree.leaves(batch)
                        if getattr(x, "ndim", 0) >= 1), default=0)
            usable = (rows // dp) * dp
            if usable == 0 or (usable != rows
                               and self.ragged == "replicate"):
                return jitted(params, opt_state, batch)

            def place(x):
                if getattr(x, "ndim", 0) >= 1 and x.shape[0] == rows:
                    return jax.device_put(x[:usable], batch_sharded)
                return jax.device_put(x, replicated)

            batch = jax.tree.map(place, batch)
            params = jax.device_put(params, replicated)
            opt_state = jax.device_put(opt_state, replicated)
            return jitted(params, opt_state, batch)

        setattr(learner, step_attr, step)

    # the group IS the learner for the algorithm control loop
    def update(self, rollouts):
        return self.learner.update(rollouts)

    def get_weights(self):
        return self.learner.get_weights()

    def set_weights(self, weights):
        return self.learner.set_weights(weights)


def wrap_learner_data_parallel(learner: Any,
                               num_learners: Optional[int] = None,
                               ragged: str = "replicate") -> Any:
    """Convenience: in-place rebind (returns the same learner)."""
    LearnerGroup(learner, num_learners=num_learners, ragged=ragged)
    return learner
