"""LearnerGroup: data-parallel learner updates over a device mesh.

Reference capability: ``rllib/core/learner/learner_group.py:234`` — N
DDP learner workers, each on its own GPU, gradients all-reduced by NCCL.
TPU-first shape: the group is ONE jitted SPMD update over a ``dp`` mesh
axis — the minibatch is sharded across devices, params/optimizer state
stay replicated, and XLA inserts the gradient ``psum`` exactly where DDP
would run its all-reduce. No learner actors, no weight broadcast between
"learners": replication is maintained by the compiler.

Works with any learner whose jitted step is a pure 3-arg function
``(params, opt_state, batch) -> (params, opt_state, metrics)`` — PPO
and IMPALA in-tree. (SAC's step threads a 4th ``targets`` pytree and
would need its own sharding tuple; not wrapped here.)
"""

from __future__ import annotations

from typing import Any, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class LearnerGroup:
    """Wrap a learner so its gradient step runs data-parallel over a
    mesh. The learner's host-side logic (GAE, replay, minibatching) is
    untouched; only the jitted step is re-bound with shardings."""

    def __init__(self, learner: Any, *, mesh: Optional[Mesh] = None,
                 num_learners: Optional[int] = None,
                 step_attr: str = "_update",
                 impl_attr: str = "_update_impl"):
        devices = jax.devices()
        n = num_learners or len(devices)
        if mesh is None:
            if len(devices) < n:
                raise ValueError(
                    f"num_learners={n} but only {len(devices)} devices")
            # the shared mesh vocabulary (all six named axes, size-1
            # included) so learner shardings compose with the rest of
            # the parallel stack
            from ray_tpu.parallel.mesh import MeshSpec, build_mesh

            mesh = build_mesh(MeshSpec(dp=n), devices[:n])
        if "dp" not in mesh.shape:
            raise ValueError(
                f"LearnerGroup needs a 'dp' mesh axis; mesh has "
                f"{tuple(mesh.shape)}")
        if num_learners is not None and mesh.shape["dp"] != num_learners:
            raise ValueError(
                f"num_learners={num_learners} conflicts with the "
                f"mesh's dp={mesh.shape['dp']}")
        self.mesh = mesh
        self.num_learners = mesh.shape["dp"]
        self.learner = learner

        replicated = NamedSharding(mesh, P())
        batch_sharded = NamedSharding(mesh, P("dp"))
        impl = getattr(learner, impl_attr)
        sharded_step = jax.jit(
            impl,
            in_shardings=(replicated, replicated, batch_sharded),
            out_shardings=(replicated, replicated, replicated))

        def step(params, opt_state, batch):
            # minibatch rows must divide dp; drop the ragged tail (the
            # permutation re-covers those rows across epochs)
            dp = self.num_learners
            first = jax.tree.leaves(batch)[0].shape[0]
            usable = (first // dp) * dp
            if usable == 0:      # batch smaller than the mesh: replicate
                return impl(params, opt_state, batch)
            if usable != first:
                batch = jax.tree.map(lambda x: x[:usable], batch)
            return sharded_step(params, opt_state, batch)

        setattr(learner, step_attr, step)

    # the group IS the learner for the algorithm control loop
    def update(self, rollouts):
        return self.learner.update(rollouts)

    def get_weights(self):
        return self.learner.get_weights()

    def set_weights(self, weights):
        return self.learner.set_weights(weights)


def wrap_learner_data_parallel(learner: Any,
                               num_learners: Optional[int] = None) -> Any:
    """Convenience: in-place rebind (returns the same learner)."""
    LearnerGroup(learner, num_learners=num_learners)
    return learner
