"""ray_tpu.loadgen — open-loop load generation + SLO benchmarking.

The "millions of users" scenario made measurable (ROADMAP item 2):
seeded Poisson/constant arrival schedules with configurable
prompt/output-length distributions, N concurrent client workers over
DeploymentHandles or the HTTP proxy (streaming-aware), per-request
TTFT/TPOT/E2E/queue-time percentiles, and goodput under an SLO.

Quick use::

    from ray_tpu.loadgen import LoadSpec, SLO, HandleTarget, run_load
    report = run_load(HandleTarget(handle),
                      LoadSpec(rate=50, duration_s=10, clients=64,
                               slo=SLO(ttft_s=0.5, e2e_s=5.0)))

CLI: ``python -m ray_tpu.loadgen --clients 64 --rate 50 --duration 10``
(or ``ray-tpu loadgen ...``). See docs/serving.md.
"""

from ray_tpu.loadgen.arrival import (ARRIVAL_KINDS, LengthSampler,
                                     arrival_times)
from ray_tpu.loadgen.recorder import (SLO, LatencyRecorder,
                                      RequestRecord, percentile)
from ray_tpu.loadgen.runner import (HTTPTarget, HandleTarget, LoadSpec,
                                    build_payloads, format_multi_report,
                                    format_report, jain_fairness,
                                    run_load, run_multi_job_load)

__all__ = [
    "ARRIVAL_KINDS", "arrival_times", "LengthSampler",
    "SLO", "LatencyRecorder", "RequestRecord", "percentile",
    "LoadSpec", "HandleTarget", "HTTPTarget", "build_payloads",
    "run_load", "format_report",
    "run_multi_job_load", "format_multi_report", "jain_fairness",
]
