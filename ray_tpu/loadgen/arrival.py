"""Arrival schedules and length distributions for open-loop load.

Open-loop means request arrival times are drawn up front from a rate
process and are INDEPENDENT of completions — a slow server does not
slow the offered load down, it builds queueing delay (the
methodology serving-quality work is judged by: requests/s at a fixed
offered rate plus TTFT/TPOT percentiles, PAPERS.md arXiv 2605.25645).
Closed-loop harnesses (fire the next request when the previous
returns) systematically hide queueing collapse; everything here is
seeded and reproducible so two runs of the same spec offer byte-
identical traffic.
"""

from __future__ import annotations

import random
from typing import List, Union

ARRIVAL_KINDS = ("poisson", "constant")


def arrival_times(kind: str, rate: float, duration_s: float,
                  seed: int = 0) -> List[float]:
    """Absolute arrival offsets (seconds from t0) over ``duration_s``.

    ``poisson``: exponential inter-arrivals with mean ``1/rate`` (the
    classic many-independent-users process — bursty, memoryless).
    ``constant``: uniform ``1/rate`` spacing (worst-case steady load).
    Deterministic for a fixed ``seed``.
    """
    if rate <= 0:
        raise ValueError(f"rate must be > 0, got {rate}")
    if duration_s <= 0:
        raise ValueError(f"duration_s must be > 0, got {duration_s}")
    if kind == "constant":
        return [i / rate for i in range(int(rate * duration_s))]
    if kind == "poisson":
        rng = random.Random(seed)
        out: List[float] = []
        t = 0.0
        while True:
            t += rng.expovariate(rate)
            if t >= duration_s:
                return out
            out.append(t)
    raise ValueError(
        f"unknown arrival kind {kind!r} (one of {ARRIVAL_KINDS})")


class LengthSampler:
    """Token-length distribution parsed from a compact spec string.

    Accepted forms (all values in tokens):
      ``32``                  constant
      ``"uniform:16:64"``     uniform integer in [16, 64] inclusive
      ``"lognormal:64:0.5"``  lognormal with median 64, sigma 0.5
                              (realistic long-tailed prompt lengths)

    Sampling takes the caller's ``random.Random`` so independent
    streams (prompt vs output lengths) stay independently seeded.
    """

    def __init__(self, kind: str, a: float, b: float = 0.0):
        self.kind = kind
        self.a = a
        self.b = b

    @classmethod
    def parse(cls, spec: Union[int, str]) -> "LengthSampler":
        if isinstance(spec, int):
            return cls("constant", spec)
        text = str(spec).strip()
        if ":" not in text:
            return cls("constant", int(text))
        parts = text.split(":")
        kind = parts[0]
        if kind == "uniform" and len(parts) == 3:
            lo, hi = int(parts[1]), int(parts[2])
            if lo < 1 or hi < lo:
                raise ValueError(f"bad uniform bounds in {spec!r}")
            return cls("uniform", lo, hi)
        if kind == "lognormal" and len(parts) == 3:
            median, sigma = float(parts[1]), float(parts[2])
            if median < 1 or sigma < 0:
                raise ValueError(f"bad lognormal params in {spec!r}")
            return cls("lognormal", median, sigma)
        raise ValueError(
            f"bad length spec {spec!r} (int, 'uniform:lo:hi', or "
            f"'lognormal:median:sigma')")

    def sample(self, rng: random.Random) -> int:
        if self.kind == "constant":
            return max(1, int(self.a))
        if self.kind == "uniform":
            return rng.randint(int(self.a), int(self.b))
        # lognormal: exp(N(ln median, sigma)), floored at 1 token
        import math

        return max(1, int(round(
            math.exp(rng.gauss(math.log(self.a), self.b)))))

    def __repr__(self):
        if self.kind == "constant":
            return str(int(self.a))
        return f"{self.kind}:{self.a:g}:{self.b:g}"
