"""Per-request latency records and SLO-aware summaries.

All timestamps are seconds relative to the run's t0 (a single
``time.perf_counter`` anchor), so records from every client worker
share one clock. Percentiles use the nearest-rank method (exact,
deterministic, no interpolation) so the math is hand-checkable in
tests: ``p(q) = sorted[ceil(q/100 * n) - 1]``.

Derived per-request metrics:
  TTFT   first_token_at - sent_at   (time to first token/chunk)
  TPOT   (finished_at - first_token_at) / (output_tokens - 1)
         (time per output token AFTER the first; needs >= 2 tokens)
  E2E    finished_at - sent_at
  queue  sent_at - scheduled_at     (open-loop lateness: how far behind
         the offered schedule the finite client pool fell)

Goodput under SLO counts a request only when it completed without
error AND met every bound the SLO states — "fast p50 with a collapsed
tail" cannot hide in an average (arXiv 2605.25645 methodology).
"""

from __future__ import annotations

import dataclasses
import math
import threading
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class SLO:
    """Latency objective; ``None`` bounds are unconstrained."""

    ttft_s: Optional[float] = None
    e2e_s: Optional[float] = None

    def met_by(self, rec: "RequestRecord") -> bool:
        if rec.error is not None or rec.finished_at is None:
            return False
        if self.ttft_s is not None and (
                rec.ttft_s is None or rec.ttft_s > self.ttft_s):
            return False
        if self.e2e_s is not None and (
                rec.e2e_s is None or rec.e2e_s > self.e2e_s):
            return False
        return True


@dataclasses.dataclass
class RequestRecord:
    scheduled_at: float
    sent_at: float = 0.0
    first_token_at: Optional[float] = None
    finished_at: Optional[float] = None
    output_tokens: int = 0
    error: Optional[str] = None

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_at is None:
            return None
        return self.first_token_at - self.sent_at

    @property
    def e2e_s(self) -> Optional[float]:
        if self.finished_at is None:
            return None
        return self.finished_at - self.sent_at

    @property
    def queue_s(self) -> float:
        return max(0.0, self.sent_at - self.scheduled_at)

    @property
    def tpot_s(self) -> Optional[float]:
        if (self.finished_at is None or self.first_token_at is None
                or self.output_tokens < 2):
            return None
        return ((self.finished_at - self.first_token_at)
                / (self.output_tokens - 1))


def percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an ASCENDING-sorted list."""
    if not sorted_vals:
        return 0.0
    rank = max(1, math.ceil(q / 100.0 * len(sorted_vals)))
    return float(sorted_vals[min(rank, len(sorted_vals)) - 1])


def _dist(vals: List[float]) -> Dict[str, float]:
    vals = sorted(vals)
    return {
        "p50": round(percentile(vals, 50), 6),
        "p90": round(percentile(vals, 90), 6),
        "p99": round(percentile(vals, 99), 6),
        "mean": round(sum(vals) / len(vals), 6) if vals else 0.0,
        "max": round(vals[-1], 6) if vals else 0.0,
    }


class LatencyRecorder:
    """Thread-safe sink the client workers append finished records to."""

    def __init__(self):
        self._lock = threading.Lock()
        self._records: List[RequestRecord] = []  #: guarded by self._lock

    def add(self, rec: RequestRecord) -> None:
        with self._lock:
            self._records.append(rec)

    def records(self) -> List[RequestRecord]:
        with self._lock:
            return list(self._records)

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    def summary(self, slo: Optional[SLO] = None,
                wall_s: Optional[float] = None) -> Dict[str, Any]:
        """Machine-readable report over everything recorded so far."""
        recs = self.records()
        done = [r for r in recs
                if r.error is None and r.finished_at is not None]
        errors = [r for r in recs if r.error is not None]
        if wall_s is None:
            ends = [r.finished_at for r in done]
            wall_s = max(ends) if ends else 0.0
        out_tokens = sum(r.output_tokens for r in done)
        report: Dict[str, Any] = {
            "requests": {"total": len(recs), "completed": len(done),
                         "errors": len(errors)},
            "wall_s": round(wall_s, 4),
            "requests_per_second": round(len(done) / wall_s, 3)
            if wall_s > 0 else 0.0,
            "output_tokens": out_tokens,
            "output_tokens_per_second": round(out_tokens / wall_s, 2)
            if wall_s > 0 else 0.0,
            "ttft_s": _dist([r.ttft_s for r in done
                             if r.ttft_s is not None]),
            "tpot_s": _dist([r.tpot_s for r in done
                             if r.tpot_s is not None]),
            "e2e_s": _dist([r.e2e_s for r in done
                            if r.e2e_s is not None]),
            "queue_s": _dist([r.queue_s for r in recs]),
        }
        if errors:
            # first few error strings: enough to diagnose, bounded size
            report["error_samples"] = sorted(
                {e.error for e in errors if e.error})[:5]
        if slo is not None:
            good = [r for r in done if slo.met_by(r)]
            report["goodput"] = {
                "slo": {"ttft_s": slo.ttft_s, "e2e_s": slo.e2e_s},
                "completed_within_slo": len(good),
                "fraction": round(len(good) / len(done), 4)
                if done else 0.0,
                "requests_per_second": round(len(good) / wall_s, 3)
                if wall_s > 0 else 0.0,
            }
        return report
