"""CLI: ``python -m ray_tpu.loadgen`` / ``ray-tpu loadgen``.

Self-contained by default: boots a local cluster, deploys a
debug-model LLM app with ``--replicas`` replicas, drives it open-loop
through DeploymentHandles, prints the human summary plus one
machine-readable JSON line. ``--url`` skips the self-hosted app and
drives an already-running HTTP proxy instead; ``--http`` serves the
self-hosted app through the HTTP proxy and measures at the client.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ray-tpu loadgen",
        description="open-loop serving load generator (SLO benchmark)")
    p.add_argument("--clients", type=int, default=16,
                   help="concurrent client workers (default 16)")
    p.add_argument("--rate", type=float, default=20.0,
                   help="offered requests/s (default 20)")
    p.add_argument("--duration", type=float, default=5.0,
                   help="arrival window seconds (default 5)")
    p.add_argument("--arrival", choices=("poisson", "constant"),
                   default="poisson")
    p.add_argument("--prompt-len", default="uniform:8:24",
                   help="tokens: N | uniform:lo:hi | "
                        "lognormal:median:sigma (default uniform:8:24)")
    p.add_argument("--output-len", default="8",
                   help="max_tokens distribution (same forms, default 8)")
    p.add_argument("--prefix-len", type=int, default=0,
                   help="common prompt prefix tokens shared by all "
                        "requests (exercises prefix caching)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--no-stream", action="store_true",
                   help="unary requests (TTFT == E2E)")
    p.add_argument("--slo-ttft-s", type=float, default=2.0,
                   help="TTFT bound for goodput (default 2.0)")
    p.add_argument("--slo-e2e-s", type=float, default=30.0,
                   help="E2E bound for goodput (default 30.0)")
    p.add_argument("--timeout-s", type=float, default=120.0,
                   help="per-request client timeout")
    p.add_argument("--drain-timeout-s", type=float, default=300.0,
                   help="wait for in-flight requests after last arrival")
    p.add_argument("--url", default="",
                   help="drive an EXISTING HTTP endpoint "
                        "(host:port[/path]) instead of self-hosting")
    p.add_argument("--http", action="store_true",
                   help="self-host, but drive through the HTTP proxy")
    p.add_argument("--replicas", type=int, default=2,
                   help="replicas for the self-hosted debug app "
                        "(default 2)")
    p.add_argument("--max-slots", type=int, default=4,
                   help="engine slots per replica (self-hosted)")
    p.add_argument("--max-seq", type=int, default=128,
                   help="engine max sequence length (self-hosted)")
    p.add_argument("--jobs", type=int, default=1,
                   help="run N concurrent tenant jobs (offered rate "
                        "split evenly); reports per-job goodput plus "
                        "Jain fairness + isolation p99 ratio")
    p.add_argument("--job-weights", default="", metavar="W1,W2,...",
                   help="per-job fair-share weights for --jobs "
                        "(default: all 1.0)")
    p.add_argument("--json", default="", metavar="PATH",
                   help="also write the full JSON report to PATH")
    return p


def _self_hosted_target(args, spec):
    """Boot cluster + debug LLM app; returns (target, cleanup)."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.llm.serving import LLMConfig, build_llm_app
    from ray_tpu.loadgen.runner import HTTPTarget, HandleTarget

    own = not ray_tpu.is_initialized()
    if own:
        ray_tpu.init(num_nodes=1, resources={"CPU": 8},
                     ignore_reinit_error=True)
    cfg = LLMConfig(model_id="loadgen-debug",
                    max_slots=args.max_slots, max_seq=args.max_seq,
                    num_replicas=args.replicas)
    handle = serve.run(build_llm_app(cfg))

    # Warm EVERY replica's engine (jit prefill/decode shapes) before the
    # timed window — a cold replica's first TTFT measures XLA compile.
    controller = ray_tpu.get_actor("serve_controller")
    replicas = ray_tpu.get(
        controller.get_replicas.remote(cfg.model_id))["replicas"]
    warm = {"prompt": [1] * 8, "max_tokens": 2}
    ray_tpu.get([r.handle_request.remote("__call__", (warm,), {})
                 for r in replicas], timeout=300)

    if args.http:
        port = serve.start_http_proxy(port=0)
        target = HTTPTarget("127.0.0.1", port,
                            timeout_s=spec.timeout_s)
    else:
        target = HandleTarget(handle, stream=spec.stream,
                              timeout_s=spec.timeout_s)

    def cleanup():
        serve.shutdown()
        if own:
            ray_tpu.shutdown()

    return target, cleanup


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    from ray_tpu.loadgen.recorder import SLO
    from ray_tpu.loadgen.runner import (HTTPTarget, LoadSpec,
                                        format_multi_report,
                                        format_report, run_load,
                                        run_multi_job_load)

    spec = LoadSpec(
        rate=args.rate, duration_s=args.duration, clients=args.clients,
        arrival=args.arrival, prompt_len=args.prompt_len,
        output_len=args.output_len, prefix_len=args.prefix_len,
        seed=args.seed, stream=not args.no_stream,
        timeout_s=args.timeout_s, drain_timeout_s=args.drain_timeout_s,
        slo=SLO(ttft_s=args.slo_ttft_s, e2e_s=args.slo_e2e_s))

    cleanup = None
    if args.url:
        target = HTTPTarget.from_url(args.url, timeout_s=spec.timeout_s)
    else:
        target, cleanup = _self_hosted_target(args, spec)
    try:
        if args.jobs > 1:
            weights = [float(w) for w in args.job_weights.split(",")
                       if w.strip()]
            report = run_multi_job_load(target, spec, jobs=args.jobs,
                                        weights=weights)
        else:
            report = run_load(target, spec)
    finally:
        if cleanup is not None:
            cleanup()

    if args.jobs > 1:
        print(format_multi_report(report))
    else:
        print(format_report(report))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
        print(f"report written to {args.json}")
    print(json.dumps(report))
    if args.jobs > 1:
        reqs = [r["requests"] for r in report["jobs"].values()]
        done = sum(r["completed"] for r in reqs)
        errs = sum(r["errors"] for r in reqs)
    else:
        done = report["requests"]["completed"]
        errs = report["requests"]["errors"]
    return 0 if done > 0 and not errs else 1


if __name__ == "__main__":
    sys.exit(main())
