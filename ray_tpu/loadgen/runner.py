"""Open-loop many-client load runner over Serve handles or HTTP.

``run_load(target, spec)`` drives the target with ``spec.clients``
concurrent client workers pulling from one pre-computed arrival
schedule: each request fires at its scheduled offset (workers sleep
until then), and when every worker is busy the schedule keeps
advancing — the lateness lands in the per-request ``queue_s`` instead
of silently thinning the offered load (open loop; see
``loadgen/arrival.py``).

Targets are callables ``(payload, rec, t0)`` that execute one request
and stamp ``rec.sent_at / first_token_at / finished_at /
output_tokens`` relative to ``t0``; two adapters are provided:

- :class:`HandleTarget` — drives a ``DeploymentHandle``, streaming
  (chunk-per-token generators, TTFT = first chunk) or unary.
- :class:`HTTPTarget` — drives the HTTP proxy, SSE streaming-aware.
"""

from __future__ import annotations

import dataclasses
import json
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Union

from ray_tpu.loadgen.arrival import LengthSampler, arrival_times
from ray_tpu.loadgen.recorder import SLO, LatencyRecorder, RequestRecord

Target = Callable[[Any, RequestRecord, float], None]


@dataclasses.dataclass
class LoadSpec:
    """One reproducible open-loop workload (seeded end to end)."""

    rate: float = 10.0            # offered requests/s
    duration_s: float = 5.0       # arrival window (drain may run longer)
    clients: int = 8              # concurrent client workers
    arrival: str = "poisson"      # or "constant"
    prompt_len: Union[int, str] = 32    # LengthSampler spec
    output_len: Union[int, str] = 16    # LengthSampler spec (max_tokens)
    prefix_len: int = 0           # common prompt prefix shared by ALL
    #                               requests (exercises prefix caching)
    vocab: int = 500              # prompt token id range [1, vocab)
    seed: int = 0
    stream: bool = True           # streaming responses (real TTFT)
    timeout_s: float = 120.0      # per-request client timeout
    drain_timeout_s: float = 300.0  # wait for in-flight after last arrival
    slo: SLO = dataclasses.field(
        default_factory=lambda: SLO(ttft_s=2.0, e2e_s=30.0))

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["prompt_len"] = str(self.prompt_len)
        d["output_len"] = str(self.output_len)
        return d


def build_payloads(spec: LoadSpec, n: int) -> List[Dict[str, Any]]:
    """Deterministic completion-shaped payloads for ``n`` arrivals.

    Prompt tokens, prompt lengths and output lengths come from three
    independent seeded streams so changing one knob (say the output
    distribution) does not reshuffle the others.
    """
    import random

    prompt_lens = LengthSampler.parse(spec.prompt_len)
    output_lens = LengthSampler.parse(spec.output_len)
    rng_plen = random.Random(f"{spec.seed}:prompt_len")
    rng_olen = random.Random(f"{spec.seed}:output_len")
    rng_toks = random.Random(f"{spec.seed}:tokens")
    prefix = [rng_toks.randint(1, spec.vocab - 1)
              for _ in range(max(0, spec.prefix_len))]
    payloads = []
    for _ in range(n):
        plen = prompt_lens.sample(rng_plen)
        body = [rng_toks.randint(1, spec.vocab - 1) for _ in range(plen)]
        payloads.append({
            "prompt": prefix + body,
            "max_tokens": output_lens.sample(rng_olen),
            "stream": spec.stream,
        })
    return payloads


class HandleTarget:
    """Drive a Serve ``DeploymentHandle`` (the in-cluster data plane)."""

    def __init__(self, handle, stream: bool = True,
                 timeout_s: float = 120.0):
        self._handle = (handle.options(stream=True) if stream
                        else handle)
        self._stream = stream
        self._timeout_s = timeout_s

    def __call__(self, payload, rec: RequestRecord, t0: float) -> None:
        if self._stream:
            gen = self._handle.remote(payload)
            deadline = (time.perf_counter() + self._timeout_s
                        if self._timeout_s else None)
            while True:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"no chunk within timeout_s={self._timeout_s}")
                try:
                    chunk = (gen.next(timeout=remaining)
                             if hasattr(gen, "next") else next(gen))
                except StopIteration:
                    break
                now = time.perf_counter() - t0
                if rec.first_token_at is None:
                    rec.first_token_at = now
                if isinstance(chunk, dict):
                    if chunk.get("done"):
                        continue    # terminal summary chunk, not a token
                    rec.output_tokens += 1
                else:
                    rec.output_tokens += 1
            rec.finished_at = time.perf_counter() - t0
            return
        result = self._handle.remote(payload).result(
            timeout=self._timeout_s)
        now = time.perf_counter() - t0
        rec.first_token_at = now     # unary: first byte == last byte
        rec.finished_at = now
        usage = (result.get("usage")
                 if isinstance(result, dict) else None)
        rec.output_tokens = (int(usage["completion_tokens"])
                             if usage else 1)

    def __repr__(self):
        return f"HandleTarget(stream={self._stream})"


class HTTPTarget:
    """Drive the HTTP proxy; SSE streaming when the payload asks."""

    def __init__(self, host: str, port: int, path: str = "/",
                 timeout_s: float = 120.0):
        self.host, self.port, self.path = host, port, path
        self._timeout_s = timeout_s

    @classmethod
    def from_url(cls, url: str, timeout_s: float = 120.0) -> "HTTPTarget":
        from urllib.parse import urlparse

        p = urlparse(url if "//" in url else f"http://{url}")
        return cls(p.hostname or "127.0.0.1", p.port or 80,
                   p.path or "/", timeout_s)

    def __call__(self, payload, rec: RequestRecord, t0: float) -> None:
        import http.client

        stream = isinstance(payload, dict) and payload.get("stream")
        body = json.dumps(payload)
        headers = {"Content-Type": "application/json"}
        if stream:
            headers["Accept"] = "text/event-stream"
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=self._timeout_s)
        try:
            conn.request("POST", self.path, body=body, headers=headers)
            resp = conn.getresponse()
            if resp.status != 200:
                resp.read()
                raise RuntimeError(f"HTTP {resp.status}")
            if not stream:
                raw = resp.read()
                now = time.perf_counter() - t0
                rec.first_token_at = now
                rec.finished_at = now
                try:
                    usage = json.loads(raw).get("usage")
                    rec.output_tokens = (int(usage["completion_tokens"])
                                         if usage else 1)
                except (ValueError, AttributeError, KeyError):
                    rec.output_tokens = 1
                return
            buf = b""
            while True:
                chunk = resp.read(4096)
                if not chunk:
                    break
                buf += chunk
                now = time.perf_counter() - t0
                while b"\n\n" in buf:
                    event, buf = buf.split(b"\n\n", 1)
                    if not event.startswith(b"data: "):
                        continue
                    data = event[6:]
                    if data == b"[DONE]":
                        continue
                    if rec.first_token_at is None:
                        rec.first_token_at = now
                    try:
                        parsed = json.loads(data)
                    except ValueError:
                        continue
                    if isinstance(parsed, dict) and parsed.get("done"):
                        continue
                    rec.output_tokens += 1
            rec.finished_at = time.perf_counter() - t0
        finally:
            conn.close()

    def __repr__(self):
        return f"HTTPTarget({self.host}:{self.port}{self.path})"


def run_load(target: Target, spec: LoadSpec,
             payloads: Optional[List[Any]] = None) -> Dict[str, Any]:
    """Run one open-loop load against ``target``; returns the report.

    The report is the recorder summary plus run metadata — JSON-
    serializable end to end (the BENCH/CLI contract).
    """
    from ray_tpu.util.metrics import Counter

    times = arrival_times(spec.arrival, spec.rate, spec.duration_s,
                          spec.seed)
    if payloads is None:
        payloads = build_payloads(spec, len(times))
    if len(payloads) < len(times):
        raise ValueError(
            f"{len(payloads)} payloads for {len(times)} arrivals")
    recorder = LatencyRecorder()
    requests_total = Counter(
        "ray_tpu_loadgen_requests_total",
        "loadgen client requests by outcome")
    work: "queue.Queue" = queue.Queue()
    for sched, payload in zip(times, payloads):
        work.put((sched, payload))
    t0 = time.perf_counter()

    def client_worker() -> None:
        while True:
            try:
                sched, payload = work.get_nowait()
            except queue.Empty:
                return
            delay = t0 + sched - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            rec = RequestRecord(scheduled_at=sched)
            rec.sent_at = time.perf_counter() - t0
            try:
                target(payload, rec, t0)
                requests_total.inc(tags={"status": "ok"})
            except Exception as e:
                rec.error = repr(e)[:200]
                rec.finished_at = time.perf_counter() - t0
                requests_total.inc(tags={"status": "error"})
            recorder.add(rec)

    workers = [threading.Thread(target=client_worker, daemon=True,
                                name=f"loadgen-client-{i}")
               for i in range(max(1, spec.clients))]
    for w in workers:
        w.start()
    deadline = time.monotonic() + spec.duration_s + spec.drain_timeout_s
    abandoned = 0
    for w in workers:
        w.join(timeout=max(0.0, deadline - time.monotonic()))
        if w.is_alive():
            abandoned += 1
    wall_s = time.perf_counter() - t0
    report = recorder.summary(slo=spec.slo, wall_s=wall_s)
    report["spec"] = spec.to_dict()
    report["target"] = repr(target)
    report["scheduled_requests"] = len(times)
    if abandoned:
        # loud, not silent: these workers still held a request when the
        # drain window closed — the completed counts under-report load
        report["abandoned_clients"] = abandoned
    return report


def jain_fairness(shares: List[float]) -> float:
    """Jain's fairness index over per-job allocations: ``(Σx)²/(n·Σx²)``
    — 1.0 when every job gets the same (weight-normalized) share, → 1/n
    when one job takes everything."""
    xs = [max(0.0, float(x)) for x in shares]
    if not xs or not any(xs):
        return 0.0
    return (sum(xs) ** 2) / (len(xs) * sum(x * x for x in xs))


def run_multi_job_load(target: Target, spec: LoadSpec, jobs: int = 2,
                       weights: Optional[List[float]] = None,
                       job_prefix: str = "loadgen-job"
                       ) -> Dict[str, Any]:
    """Drive ``jobs`` concurrent tenants through one target.

    Each job runs its own open-loop :func:`run_load` (offered rate
    split evenly, independent arrival/payload seeds) with every request
    wrapped in that job's :func:`ray_tpu.tenancy.job_context` — the
    wrapper re-enters the scope inside the client worker thread because
    contextvars do not cross thread boundaries. The combined report
    carries per-job reports plus a ``multitenancy`` section:

    - ``fairness_index`` — Jain's index over weight-normalized goodput
      (``goodput_j / weight_j``);
    - ``isolation_p99_ratio`` — max/min per-job E2E p99: 1.0 means no
      job's tail is inflated by its neighbors.
    """
    from ray_tpu.tenancy import job_context

    n = max(1, int(jobs))
    ws = [float(w) for w in (weights or [])][:n]
    ws += [1.0] * (n - len(ws))
    reports: Dict[str, Dict[str, Any]] = {}
    errors: List[BaseException] = []

    def one_job(idx: int) -> None:
        name = f"{job_prefix}-{idx}"
        jspec = dataclasses.replace(
            spec, rate=spec.rate / n, seed=spec.seed + 1000 * idx)

        def wrapped(payload, rec, t0, _name=name, _w=ws[idx]):
            with job_context(_name, weight=_w):
                target(payload, rec, t0)

        try:
            reports[name] = run_load(wrapped, jspec)
        except BaseException as e:   # surfaced after join
            errors.append(e)

    runners = [threading.Thread(target=one_job, args=(i,), daemon=True,
                                name=f"loadgen-job-{i}")
               for i in range(n)]
    t0 = time.perf_counter()
    for r in runners:
        r.start()
    for r in runners:
        r.join()
    if errors:
        raise errors[0]
    wall_s = time.perf_counter() - t0

    names = sorted(reports)
    goodput = {
        name: float((reports[name].get("goodput") or {})
                    .get("requests_per_second", 0.0))
        for name in names}
    weights_by_job = {f"{job_prefix}-{i}": ws[i] for i in range(n)}
    shares = [goodput[name] / max(weights_by_job[name], 1e-9)
              for name in names]
    p99s = [float(reports[name]["e2e_s"]["p99"] or 0.0)
            for name in names]
    iso = (max(p99s) / max(min(p99s), 1e-9)) if p99s else 0.0
    return {
        "jobs": reports,
        "wall_s": wall_s,
        "multitenancy": {
            "num_jobs": n,
            "weights": weights_by_job,
            "goodput_per_job": goodput,
            "fairness_index": jain_fairness(shares),
            "isolation_p99_ratio": iso,
        },
        "spec": spec.to_dict(),
        "target": repr(target),
    }


def format_multi_report(report: Dict[str, Any]) -> str:
    """Human-readable summary of a :func:`run_multi_job_load` report."""
    mt = report["multitenancy"]
    lines = ["== loadgen multi-job report =="]
    for name in sorted(report["jobs"]):
        rep = report["jobs"][name]
        req = rep["requests"]
        lines.append(
            f"{name} (w={mt['weights'][name]:g}): "
            f"{req['completed']}/{req['total']} done, "
            f"goodput {mt['goodput_per_job'][name]:.2f} req/s, "
            f"E2E p99 {rep['e2e_s']['p99'] * 1e3:.1f} ms")
    lines.append(
        f"fairness index (Jain, weight-normalized goodput): "
        f"{mt['fairness_index']:.3f}")
    lines.append(
        f"isolation p99 ratio (max/min across jobs): "
        f"{mt['isolation_p99_ratio']:.2f}")
    return "\n".join(lines)


def format_report(report: Dict[str, Any]) -> str:
    """Human-readable summary of a :func:`run_load` report."""
    req = report["requests"]
    lines = [
        "== loadgen report ==",
        f"offered: {report['spec']['arrival']} "
        f"{report['spec']['rate']:g} req/s x "
        f"{report['spec']['duration_s']:g}s, "
        f"{report['spec']['clients']} clients "
        f"({report['scheduled_requests']} requests)",
        f"completed: {req['completed']}/{req['total']} "
        f"({req['errors']} errors) in {report['wall_s']:.2f}s",
        f"requests/s: {report['requests_per_second']:.2f}   "
        f"output tok/s: {report['output_tokens_per_second']:.1f}",
        f"TTFT  p50/p99: {report['ttft_s']['p50'] * 1e3:.1f} / "
        f"{report['ttft_s']['p99'] * 1e3:.1f} ms",
        f"E2E   p50/p99: {report['e2e_s']['p50'] * 1e3:.1f} / "
        f"{report['e2e_s']['p99'] * 1e3:.1f} ms",
        f"TPOT  p50:     {report['tpot_s']['p50'] * 1e3:.2f} ms",
        f"queue p50/p99: {report['queue_s']['p50'] * 1e3:.1f} / "
        f"{report['queue_s']['p99'] * 1e3:.1f} ms",
    ]
    good = report.get("goodput")
    if good:
        slo = good["slo"]
        bounds = ", ".join(
            f"{k}<={v:g}" for k, v in slo.items() if v is not None)
        lines.append(
            f"goodput ({bounds or 'no bounds'}): "
            f"{good['requests_per_second']:.2f} req/s "
            f"({good['fraction'] * 100:.1f}% of completed)")
    if report.get("abandoned_clients"):
        lines.append(
            f"WARNING: {report['abandoned_clients']} client(s) still "
            f"in flight at drain timeout")
    return "\n".join(lines)
