"""ctypes surface over the native C++ wire-protocol client.

Reference capability: the C++ API tier (``cpp/`` — a native program
talking to a Ray cluster without Python). ``native/cpp_client.cc``
speaks the typed msgpack wire directly (head InternalKV, daemon object
plane, daemon_ping); this module is the thin loader + a pythonic wrapper
used by tests to prove cross-language interop (bytes written by Python
read back by C++, and vice versa).
"""

from __future__ import annotations

import ctypes
import threading
from typing import Optional, Tuple

from ray_tpu._private.native_build import load_native_so

_lib = None
_lib_lock = threading.Lock()


def _load():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        lib = load_native_so("cpp_client.cc", "libray_tpu_cpp_client.so",
                             ["-lpthread"])
        if lib is None:
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.rtc_connect.restype = ctypes.c_void_p
        lib.rtc_connect.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.rtc_close.argtypes = [ctypes.c_void_p]
        lib.rtc_free.argtypes = [ctypes.c_void_p]
        lib.rtc_kv_put.restype = ctypes.c_int
        lib.rtc_kv_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_int, ctypes.c_char_p,
                                   ctypes.c_int]
        lib.rtc_kv_get.restype = ctypes.c_int
        lib.rtc_kv_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_int, ctypes.POINTER(u8p),
                                   ctypes.POINTER(ctypes.c_int64)]
        lib.rtc_put_object.restype = ctypes.c_int
        lib.rtc_put_object.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_int, ctypes.c_char_p,
                                       ctypes.c_int64]
        lib.rtc_get_object.restype = ctypes.c_int
        lib.rtc_get_object.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_int, ctypes.POINTER(u8p),
                                       ctypes.POINTER(ctypes.c_int64)]
        lib.rtc_ping.restype = ctypes.c_long
        lib.rtc_ping.argtypes = [ctypes.c_void_p]
        lib.rtc_submit_task.restype = ctypes.c_int
        lib.rtc_submit_task.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                        ctypes.c_char_p, ctypes.c_int,
                                        ctypes.POINTER(u8p),
                                        ctypes.POINTER(ctypes.c_int64)]
        lib.rtc_create_actor.restype = ctypes.c_int
        lib.rtc_create_actor.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                         ctypes.c_char_p, ctypes.c_char_p,
                                         ctypes.c_int]
        lib.rtc_call_actor.restype = ctypes.c_int
        lib.rtc_call_actor.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_char_p, ctypes.c_char_p,
                                       ctypes.c_int,
                                       ctypes.POINTER(u8p),
                                       ctypes.POINTER(ctypes.c_int64)]
        lib.rtc_last_error.restype = ctypes.c_char_p
        lib.rtc_last_error.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


class CppClient:
    """One native TCP connection to a head or daemon."""

    def __init__(self, addr: Tuple[str, int]):
        lib = _load()
        if lib is None:
            raise RuntimeError("native cpp client unavailable "
                               "(g++ missing or build failed)")
        self._lib = lib
        self._h = lib.rtc_connect(addr[0].encode(), int(addr[1]))
        if not self._h:
            raise ConnectionError(f"cpp client: connect to {addr} failed")

    def _handle(self):
        if not self._h:
            raise ValueError("cpp client is closed")
        return self._h

    def _take(self, out, n) -> bytes:
        try:
            return ctypes.string_at(out, n.value)
        finally:
            self._lib.rtc_free(out)

    # head KV ------------------------------------------------------------
    def kv_put(self, key: bytes, value: bytes) -> None:
        rc = self._lib.rtc_kv_put(self._handle(), key, len(key), value,
                                  len(value))
        if rc != 0:
            raise IOError(self.last_error())

    def kv_get(self, key: bytes) -> Optional[bytes]:
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = ctypes.c_int64()
        rc = self._lib.rtc_kv_get(self._handle(), key, len(key),
                                  ctypes.byref(out), ctypes.byref(n))
        if rc == 1:
            return None
        if rc != 0:
            raise IOError(self.last_error())
        return self._take(out, n)

    # daemon object plane -------------------------------------------------
    def put_object(self, oid: bytes, blob: bytes) -> None:
        rc = self._lib.rtc_put_object(self._handle(), oid, len(oid), blob,
                                      len(blob))
        if rc != 0:
            raise IOError(self.last_error())

    def get_object(self, oid: bytes) -> Optional[bytes]:
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = ctypes.c_int64()
        rc = self._lib.rtc_get_object(self._handle(), oid, len(oid),
                                      ctypes.byref(out), ctypes.byref(n))
        if rc == 1:
            return None
        if rc != 0:
            raise IOError(self.last_error())
        return self._take(out, n)

    def ping(self) -> int:
        pid = self._lib.rtc_ping(self._handle())
        if pid < 0:
            raise IOError(self.last_error())
        return int(pid)

    # cross-language tasks/actors (daemon) --------------------------------
    # Python exports by name (ray_tpu.xlang); the NATIVE library speaks
    # the whole protocol — this wrapper only packs/unpacks msgpack args.
    def _xlang_out(self, rc, out, n):
        if rc == -1:
            raise IOError(self.last_error())
        payload = self._take(out, n)
        if rc == 1:
            raise RuntimeError(payload.decode(errors="replace"))
        import msgpack
        return msgpack.unpackb(payload, raw=False)

    def submit_task(self, name: str, *args):
        import msgpack
        blob = msgpack.packb(list(args))
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = ctypes.c_int64()
        rc = self._lib.rtc_submit_task(self._handle(), name.encode(),
                                       blob, len(blob), ctypes.byref(out),
                                       ctypes.byref(n))
        return self._xlang_out(rc, out, n)

    def create_actor(self, cls_name: str, actor_name: str, *args) -> None:
        import msgpack
        blob = msgpack.packb(list(args))
        rc = self._lib.rtc_create_actor(self._handle(), cls_name.encode(),
                                        actor_name.encode(), blob,
                                        len(blob))
        if rc == -1:
            raise IOError(self.last_error())
        if rc == 1:
            raise RuntimeError(self.last_error())

    def call_actor(self, actor_name: str, method: str, *args):
        import msgpack
        blob = msgpack.packb(list(args))
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = ctypes.c_int64()
        rc = self._lib.rtc_call_actor(self._handle(), actor_name.encode(),
                                      method.encode(), blob, len(blob),
                                      ctypes.byref(out), ctypes.byref(n))
        return self._xlang_out(rc, out, n)

    def last_error(self) -> str:
        return self._lib.rtc_last_error(self._h).decode(errors="replace")

    def close(self) -> None:
        if self._h:
            self._lib.rtc_close(self._h)
            self._h = None
