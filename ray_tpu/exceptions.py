"""User-facing exception hierarchy.

Mirrors the capability contract of the reference's ``python/ray/exceptions.py``:
task errors wrap the remote traceback, actor errors carry death cause, object
loss is a distinct recoverable condition (lineage reconstruction may fix it).
"""

from __future__ import annotations

import traceback
from typing import Optional


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A remote task raised an exception.

    Returned to ``get()`` callers; carries the remote traceback string so the
    driver sees the worker-side stack (reference: ``RayTaskError``).
    """

    def __init__(self, cause: BaseException, task_name: str = "",
                 remote_traceback: Optional[str] = None):
        self.cause = cause
        self.task_name = task_name
        self.remote_traceback = remote_traceback or "".join(
            traceback.format_exception(type(cause), cause, cause.__traceback__)
        )
        super().__init__(
            f"Task {task_name or '<unknown>'} failed:\n{self.remote_traceback}"
        )

    def as_instanceof_cause(self) -> BaseException:
        """Return an exception that isinstance-matches the original cause."""
        cause_cls = type(self.cause)
        if cause_cls in (TaskError, ActorError):
            return self.cause
        try:
            class _Wrapped(TaskError, cause_cls):  # type: ignore[misc]
                def __init__(self, te: "TaskError"):
                    self.cause = te.cause
                    self.task_name = te.task_name
                    self.remote_traceback = te.remote_traceback
                    Exception.__init__(self, str(te))

            _Wrapped.__name__ = f"TaskError({cause_cls.__name__})"
            _Wrapped.__qualname__ = _Wrapped.__name__
            return _Wrapped(self)
        except TypeError:
            return self


class ActorError(TaskError):
    """An actor task failed because the actor is dead or dying."""

    def __init__(self, cause: BaseException, task_name: str = "",
                 actor_id=None, remote_traceback: Optional[str] = None):
        self.actor_id = actor_id
        super().__init__(cause, task_name, remote_traceback)


class ActorDiedError(RayTpuError):
    """The actor process is dead; pending and future calls fail."""

    def __init__(self, actor_id=None, cause: str = "actor died"):
        self.actor_id = actor_id
        super().__init__(cause)


class ActorUnavailableError(RayTpuError):
    """The actor is temporarily unreachable (restarting)."""


class ObjectLostError(RayTpuError):
    """An object's value was lost from the store and could not be recovered."""

    def __init__(self, object_id=None, message: str = "object lost"):
        self.object_id = object_id
        super().__init__(message)


class ObjectReconstructionFailedError(ObjectLostError):
    """Lineage reconstruction was attempted but failed (e.g. retries exhausted)."""


class OwnerDiedError(ObjectLostError):
    """The owner process of an object died, so the object is unrecoverable."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """``get()`` exceeded its timeout."""


class TaskCancelledError(RayTpuError):
    """The task was cancelled before or during execution."""

    def __init__(self, task_id=None):
        self.task_id = task_id
        super().__init__("task was cancelled")


class PendingCallsLimitExceeded(RayTpuError):
    """Actor's max_pending_calls limit was hit."""


class RuntimeEnvSetupError(RayTpuError):
    """Runtime environment failed to materialize."""


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died unexpectedly."""


class NodeDiedError(RayTpuError):
    """The node hosting the lease/worker died."""


class OutOfMemoryError(RayTpuError):
    """Object store or host memory exhausted."""


class MemoryPressureError(RayTpuError):
    """A node under HARD memory pressure rejected a new object
    reservation or put (docs/fault_tolerance.md "Memory pressure &
    graceful degradation"). Retriable backpressure signal: the node's
    PressureController is spilling / the memory monitor is preempting,
    so capacity returns — callers ride :class:`RetryPolicy` until the
    level drops, and only then surface the error."""


class PlacementGroupUnschedulableError(RayTpuError):
    """The placement group cannot fit in the cluster."""


class AdmissionRejectedError(RayTpuError):
    """Admission control rejected the submit: the job's bounded pending
    queue (``admission_queue_max``) is full while the job is over its
    quota. Backpressure signal — retry after completions free capacity,
    or raise the job's quota/weight."""
