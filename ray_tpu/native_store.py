"""ctypes binding to the native shared-memory object store.

Builds `native/libray_tpu_native.so` on first use (g++; cached). Falls
back gracefully (``available() == False``) where no compiler exists —
callers keep the pure-Python tier.

Reference parity: plasma client API surface (create/seal/get/release/
delete, zero-copy buffers) — `src/ray/object_manager/plasma/client.h`.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libray_tpu_native.so")

_lib = None
_lib_lock = threading.Lock()


def _load():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        from ray_tpu._private.native_build import load_native_so
        lib = load_native_so("shm_store.cc", "libray_tpu_native.so",
                             ["-lpthread", "-lrt"])
        if lib is None:
            return None
        lib.rtpu_store_open.restype = ctypes.c_void_p
        lib.rtpu_store_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.rtpu_store_close.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.rtpu_store_unlink.argtypes = [ctypes.c_void_p]
        lib.rtpu_store_base.restype = ctypes.c_void_p
        lib.rtpu_store_base.argtypes = [ctypes.c_void_p]
        lib.rtpu_store_capacity.restype = ctypes.c_uint64
        lib.rtpu_store_capacity.argtypes = [ctypes.c_void_p]
        lib.rtpu_store_used.restype = ctypes.c_uint64
        lib.rtpu_store_used.argtypes = [ctypes.c_void_p]
        lib.rtpu_store_num_objects.restype = ctypes.c_uint64
        lib.rtpu_store_num_objects.argtypes = [ctypes.c_void_p]
        u64p = ctypes.POINTER(ctypes.c_uint64)
        lib.rtpu_create.restype = ctypes.c_int
        lib.rtpu_create.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_uint64, u64p]
        lib.rtpu_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rtpu_pin.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rtpu_get.restype = ctypes.c_int
        lib.rtpu_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p, u64p,
                                 u64p]
        lib.rtpu_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rtpu_contains.restype = ctypes.c_int
        lib.rtpu_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rtpu_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rtpu_evict_bytes.restype = ctypes.c_uint64
        lib.rtpu_evict_bytes.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        _lib = lib
        return lib


def available() -> bool:
    return _load() is not None


class ShmStoreFull(Exception):
    pass


class ShmObjectStore:
    """One shared-memory arena; objects are immutable byte buffers."""

    def __init__(self, name: str, capacity_bytes: int):
        lib = _load()
        if lib is None:
            raise RuntimeError("native store unavailable (no g++?)")
        self._lib = lib
        self._handle = lib.rtpu_store_open(
            name.encode(), ctypes.c_uint64(capacity_bytes))
        if not self._handle:
            raise RuntimeError(f"shm_open failed for {name}")
        base = lib.rtpu_store_base(self._handle)
        self._buf = (ctypes.c_char * capacity_bytes).from_address(base)
        self._closed = False

    # -- plasma-like client API -----------------------------------------
    def put(self, object_id: bytes, payload, pin: bool = False) -> None:
        """create + write + seal. With ``pin`` the creator's ref is kept:
        the object is not LRU-evictable until delete (used when a host
        refcounting layer owns the lifetime)."""
        payload = memoryview(payload).cast("B")
        size = payload.nbytes
        off = ctypes.c_uint64()
        rc = self._lib.rtpu_create(self._handle, object_id,
                                   ctypes.c_uint64(size),
                                   ctypes.byref(off))
        if rc == -3:
            raise KeyError(f"object {object_id!r} already exists")
        if rc != 0:
            raise ShmStoreFull(
                f"cannot allocate {size} bytes (rc={rc})")
        dst = np.frombuffer(self._buf, np.uint8, count=size,
                            offset=off.value)
        dst[:] = np.frombuffer(payload, np.uint8)
        self._lib.rtpu_seal(self._handle, object_id)
        if pin:
            # Keep the creator ref and tell the store so that delete()
            # consumes it (instead of deferring deallocation forever).
            self._lib.rtpu_pin(self._handle, object_id)
        else:
            self._lib.rtpu_release(self._handle, object_id)

    def get_view(self, object_id: bytes) -> np.ndarray:
        """Zero-copy read-only view into the shm arena (increfs)."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = self._lib.rtpu_get(self._handle, object_id,
                                ctypes.byref(off), ctypes.byref(size))
        if rc != 0:
            raise KeyError(f"object {object_id!r} not in store (rc={rc})")
        view = np.frombuffer(self._buf, np.uint8, count=size.value,
                             offset=off.value)
        view.flags.writeable = False
        return view

    def get_ref(self, object_id: bytes) -> "tuple[int, int]":
        """(offset, size) of the sealed object, holding a ref so the range
        stays valid until release(). Cross-process clients attach the
        arena by name and read the range directly (the fd-passing role of
        plasma's fling.cc, done via shm_open-by-name)."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = self._lib.rtpu_get(self._handle, object_id,
                                ctypes.byref(off), ctypes.byref(size))
        if rc != 0:
            raise KeyError(f"object {object_id!r} not in store (rc={rc})")
        return off.value, size.value

    def read_range(self, offset: int, size: int) -> memoryview:
        """Read-only view of raw arena bytes (attach-side of get_ref)."""
        view = np.frombuffer(self._buf, np.uint8, count=size, offset=offset)
        view.flags.writeable = False
        return memoryview(view)

    def release(self, object_id: bytes) -> None:
        self._lib.rtpu_release(self._handle, object_id)

    def contains(self, object_id: bytes) -> bool:
        return bool(self._lib.rtpu_contains(self._handle, object_id))

    def delete(self, object_id: bytes) -> None:
        self._lib.rtpu_delete(self._handle, object_id)

    def used_bytes(self) -> int:
        return self._lib.rtpu_store_used(self._handle)

    def capacity(self) -> int:
        return self._lib.rtpu_store_capacity(self._handle)

    def num_objects(self) -> int:
        return self._lib.rtpu_store_num_objects(self._handle)

    def evict(self, nbytes: int) -> int:
        return self._lib.rtpu_evict_bytes(self._handle,
                                          ctypes.c_uint64(nbytes))

    def close(self, unlink: bool = True) -> None:
        if not self._closed:
            self._closed = True
            self._lib.rtpu_store_close(self._handle, 1 if unlink else 0)

    def unlink_only(self) -> None:
        """Remove the /dev/shm name but keep the mapping alive: used at
        shutdown while zero-copy views into the arena are still held by
        user code (munmap would turn them into SIGSEGVs)."""
        if not self._closed:
            self._closed = True
            self._lib.rtpu_store_unlink(self._handle)
