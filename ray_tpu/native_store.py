"""ctypes binding to the native shared-memory object store.

Builds `native/libray_tpu_native.so` on first use (g++; cached). Falls
back gracefully (``available() == False``) where no compiler exists —
callers keep the pure-Python tier.

Reference parity: plasma client API surface (create/seal/get/release/
delete, zero-copy buffers) — `src/ray/object_manager/plasma/client.h`.
Two handle kinds:

- **owner** (``ShmObjectStore(name, capacity)``): creates/initializes
  the segment, owns the metadata (allocator, LRU, object table);
- **attached** (``ShmObjectStore.attach(name)``): maps an existing
  segment by name (plasma's fd-passing role); may only read raw ranges,
  write into reserved ranges (direct put), and take/release the
  process-shared per-object refcounts in the segment's slot table.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional, Tuple

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libray_tpu_native.so")

_lib = None
_lib_lock = threading.Lock()


def _load():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        from ray_tpu._private.native_build import load_native_so
        lib = load_native_so("shm_store.cc", "libray_tpu_native.so",
                             ["-lpthread", "-lrt"])
        if lib is None:
            return None
        lib.rtpu_store_open.restype = ctypes.c_void_p
        lib.rtpu_store_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.rtpu_store_attach.restype = ctypes.c_void_p
        lib.rtpu_store_attach.argtypes = [ctypes.c_char_p]
        lib.rtpu_store_close.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.rtpu_store_unlink.argtypes = [ctypes.c_void_p]
        lib.rtpu_store_base.restype = ctypes.c_void_p
        lib.rtpu_store_base.argtypes = [ctypes.c_void_p]
        lib.rtpu_store_capacity.restype = ctypes.c_uint64
        lib.rtpu_store_capacity.argtypes = [ctypes.c_void_p]
        lib.rtpu_store_data_off.restype = ctypes.c_uint64
        lib.rtpu_store_data_off.argtypes = []
        lib.rtpu_store_used.restype = ctypes.c_uint64
        lib.rtpu_store_used.argtypes = [ctypes.c_void_p]
        lib.rtpu_store_num_objects.restype = ctypes.c_uint64
        lib.rtpu_store_num_objects.argtypes = [ctypes.c_void_p]
        u64p = ctypes.POINTER(ctypes.c_uint64)
        u32p = ctypes.POINTER(ctypes.c_uint32)
        intp = ctypes.POINTER(ctypes.c_int)
        lib.rtpu_create.restype = ctypes.c_int
        lib.rtpu_create.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_uint64, u64p]
        lib.rtpu_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rtpu_stat.restype = ctypes.c_int
        lib.rtpu_stat.argtypes = [ctypes.c_void_p, ctypes.c_char_p, u64p,
                                  u64p, intp]
        lib.rtpu_pin.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rtpu_get.restype = ctypes.c_int
        lib.rtpu_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p, u64p,
                                 u64p]
        lib.rtpu_ext_get.restype = ctypes.c_int
        lib.rtpu_ext_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     u64p, u64p, u32p]
        lib.rtpu_ext_release.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
        try:
            # Bulk decrement (crash reclamation); absent from .so builds
            # that predate the grant ledger — callers fall back per-ref.
            lib.rtpu_ext_release_n.restype = ctypes.c_uint32
            lib.rtpu_ext_release_n.argtypes = [ctypes.c_void_p,
                                               ctypes.c_uint32,
                                               ctypes.c_uint32]
        except AttributeError:
            pass
        lib.rtpu_ext_refs.restype = ctypes.c_uint32
        lib.rtpu_ext_refs.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
        lib.rtpu_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rtpu_contains.restype = ctypes.c_int
        lib.rtpu_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rtpu_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rtpu_evict_bytes.restype = ctypes.c_uint64
        lib.rtpu_evict_bytes.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.rtpu_reap.restype = ctypes.c_uint64
        lib.rtpu_reap.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


def available() -> bool:
    return _load() is not None


class ShmStoreFull(Exception):
    pass


class ShmObjectStore:
    """One shared-memory arena; objects are immutable byte buffers."""

    def __init__(self, name: str, capacity_bytes: int,
                 _handle: Optional[int] = None):
        lib = _load()
        if lib is None:
            raise RuntimeError("native store unavailable (no g++?)")
        self._lib = lib
        self.name = name
        if _handle is not None:         # attach() path
            self._handle = _handle
            self.attached = True
        else:
            self._handle = lib.rtpu_store_open(
                name.encode(), ctypes.c_uint64(capacity_bytes))
            self.attached = False
        if not self._handle:
            raise RuntimeError(f"shm_open failed for {name}")
        capacity = lib.rtpu_store_capacity(self._handle)
        base = lib.rtpu_store_base(self._handle)
        self._buf = (ctypes.c_char * capacity).from_address(base)
        self._capacity = capacity
        self._closed = False

    @classmethod
    def attach(cls, name: str) -> "ShmObjectStore":
        """Map an EXISTING arena by name (never creates). Attached
        handles read ranges, write reserved ranges, and manage slot
        refs — the segment's owner keeps all metadata."""
        lib = _load()
        if lib is None:
            raise RuntimeError("native store unavailable (no g++?)")
        handle = lib.rtpu_store_attach(name.encode())
        if not handle:
            raise RuntimeError(f"no arena named {name!r} to attach")
        return cls(name, 0, _handle=handle)

    # -- plasma-like client API -----------------------------------------
    def put(self, object_id: bytes, payload, pin: bool = False) -> None:
        """create + write + seal. With ``pin`` the creator's ref is kept:
        the object is not LRU-evictable until delete (used when a host
        refcounting layer owns the lifetime)."""
        payload = memoryview(payload).cast("B")
        size = payload.nbytes
        off = ctypes.c_uint64()
        rc = self._lib.rtpu_create(self._handle, object_id,
                                   ctypes.c_uint64(size),
                                   ctypes.byref(off))
        if rc == -3:
            raise KeyError(f"object {object_id!r} already exists")
        if rc != 0:
            raise ShmStoreFull(
                f"cannot allocate {size} bytes (rc={rc})")
        dst = np.frombuffer(self._buf, np.uint8, count=size,
                            offset=off.value)
        dst[:] = np.frombuffer(payload, np.uint8)
        self._lib.rtpu_seal(self._handle, object_id)
        if pin:
            # Keep the creator ref and tell the store so that delete()
            # consumes it (instead of deferring deallocation forever).
            self._lib.rtpu_pin(self._handle, object_id)
        else:
            self._lib.rtpu_release(self._handle, object_id)

    def reserve(self, object_id: bytes, size: int) -> int:
        """Reserve an UNSEALED buffer and return its offset; the writer
        (possibly another process via an attached handle) fills the
        range and then seal()s. Idempotent: a retried reserve of an
        existing entry of the same size returns the original offset."""
        off = ctypes.c_uint64()
        rc = self._lib.rtpu_create(self._handle, object_id,
                                   ctypes.c_uint64(size),
                                   ctypes.byref(off))
        if rc == 0:
            return off.value
        if rc == -3:    # exists: idempotent retry of a lost reply
            size_c = ctypes.c_uint64()
            sealed = ctypes.c_int()
            rc2 = self._lib.rtpu_stat(self._handle, object_id,
                                      ctypes.byref(off),
                                      ctypes.byref(size_c),
                                      ctypes.byref(sealed))
            if rc2 == 0 and size_c.value == size:
                return off.value
            raise KeyError(f"object {object_id!r} already exists "
                           f"with different size")
        raise ShmStoreFull(f"cannot allocate {size} bytes (rc={rc})")

    def seal(self, object_id: bytes, pin: bool = True) -> None:
        """Seal a reserved buffer (idempotent). ``pin`` keeps the
        creator ref so the host refcounting layer owns lifetime,
        matching put(pin=True)."""
        rc = self._lib.rtpu_seal(self._handle, object_id)
        if rc != 0:
            raise KeyError(f"object {object_id!r} not in store (rc={rc})")
        if pin:
            self._lib.rtpu_pin(self._handle, object_id)
        else:
            self._lib.rtpu_release(self._handle, object_id)

    def stat(self, object_id: bytes) -> Tuple[int, int, bool]:
        """(offset, size, sealed) regardless of seal state."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        sealed = ctypes.c_int()
        rc = self._lib.rtpu_stat(self._handle, object_id,
                                 ctypes.byref(off), ctypes.byref(size),
                                 ctypes.byref(sealed))
        if rc != 0:
            raise KeyError(f"object {object_id!r} not in store (rc={rc})")
        return off.value, size.value, bool(sealed.value)

    def get_view(self, object_id: bytes) -> np.ndarray:
        """Zero-copy read-only view into the shm arena (increfs)."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = self._lib.rtpu_get(self._handle, object_id,
                                ctypes.byref(off), ctypes.byref(size))
        if rc != 0:
            raise KeyError(f"object {object_id!r} not in store (rc={rc})")
        view = np.frombuffer(self._buf, np.uint8, count=size.value,
                             offset=off.value)
        view.flags.writeable = False
        return view

    def get_ref(self, object_id: bytes) -> "tuple[int, int]":
        """(offset, size) of the sealed object, holding a ref so the range
        stays valid until release(). Cross-process clients attach the
        arena by name and read the range directly (the fd-passing role of
        plasma's fling.cc, done via shm_open-by-name)."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = self._lib.rtpu_get(self._handle, object_id,
                                ctypes.byref(off), ctypes.byref(size))
        if rc != 0:
            raise KeyError(f"object {object_id!r} not in store (rc={rc})")
        return off.value, size.value

    def get_ext(self, object_id: bytes) -> Tuple[int, int, int]:
        """(offset, size, slot) with the object's PROCESS-SHARED slot
        refcount incremented on the caller's behalf: an attached client
        reads the range through its own mapping and drops the ref with
        ``ext_release(slot)`` — no store round trip, and LRU eviction is
        blocked until the slot count reaches zero. Raises KeyError when
        absent/unsealed/slotless (caller takes the blob path)."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        slot = ctypes.c_uint32()
        rc = self._lib.rtpu_ext_get(self._handle, object_id,
                                    ctypes.byref(off), ctypes.byref(size),
                                    ctypes.byref(slot))
        if rc != 0:
            raise KeyError(f"object {object_id!r} has no ext ref "
                           f"(rc={rc})")
        return off.value, size.value, slot.value

    def ext_release(self, slot: int) -> None:
        if self._closed:
            return  # view finalizer racing close(): never touch a
            #         freed handle (the owner's reap tolerates the
            #         leaked count; a closed client is gone anyway)
        self._lib.rtpu_ext_release(self._handle, ctypes.c_uint32(slot))

    def ext_release_n(self, slot: int, n: int) -> int:
        """Drop up to ``n`` external refs from ``slot`` in one atomic op.

        Returns the count actually dropped (the slot floors at zero, so
        reclaiming a dead client's grants can never wrap the count or
        steal refs that were already released locally).
        """
        if self._closed or n <= 0:
            return 0
        fn = getattr(self._lib, "rtpu_ext_release_n", None)
        if fn is None:           # pre-ledger .so: decrement one at a time
            dropped = 0
            for _ in range(n):
                if self._lib.rtpu_ext_refs(self._handle,
                                           ctypes.c_uint32(slot)) == 0:
                    break
                self._lib.rtpu_ext_release(self._handle,
                                           ctypes.c_uint32(slot))
                dropped += 1
            return dropped
        return int(fn(self._handle, ctypes.c_uint32(slot),
                      ctypes.c_uint32(n)))

    def ext_refs(self, slot: int) -> int:
        if self._closed:
            return 0
        return self._lib.rtpu_ext_refs(self._handle,
                                       ctypes.c_uint32(slot))

    def read_range(self, offset: int, size: int) -> memoryview:
        """Read-only view of raw arena bytes (attach-side of get_ref)."""
        view = np.frombuffer(self._buf, np.uint8, count=size, offset=offset)
        view.flags.writeable = False
        return memoryview(view)

    def view_range(self, offset: int, size: int) -> np.ndarray:
        """Read-only uint8 ndarray over raw arena bytes."""
        view = np.frombuffer(self._buf, np.uint8, count=size,
                             offset=offset)
        view.flags.writeable = False
        return view

    def write_range(self, offset: int, payload) -> None:
        """Fill a reserved (unsealed) range — the direct-put write."""
        payload = memoryview(payload).cast("B")
        size = payload.nbytes
        dst = np.frombuffer(self._buf, np.uint8, count=size,
                            offset=offset)
        dst[:] = np.frombuffer(payload, np.uint8)

    def release(self, object_id: bytes) -> None:
        self._lib.rtpu_release(self._handle, object_id)

    def contains(self, object_id: bytes) -> bool:
        return bool(self._lib.rtpu_contains(self._handle, object_id))

    def delete(self, object_id: bytes) -> None:
        self._lib.rtpu_delete(self._handle, object_id)

    def used_bytes(self) -> int:
        return self._lib.rtpu_store_used(self._handle)

    def capacity(self) -> int:
        return self._capacity

    def num_objects(self) -> int:
        return self._lib.rtpu_store_num_objects(self._handle)

    def evict(self, nbytes: int) -> int:
        return self._lib.rtpu_evict_bytes(self._handle,
                                          ctypes.c_uint64(nbytes))

    def reap(self) -> int:
        """Free deleted entries whose last (internal + external) ref is
        gone — external releases are silent atomic decrements, so the
        owner sweeps periodically."""
        return self._lib.rtpu_reap(self._handle)

    def close(self, unlink: bool = True) -> None:
        if not self._closed:
            self._closed = True
            self._lib.rtpu_store_close(self._handle, 1 if unlink else 0)

    def unlink_only(self) -> None:
        """Remove the /dev/shm name but keep the mapping alive: used at
        shutdown while zero-copy views into the arena are still held by
        user code (munmap would turn them into SIGSEGVs)."""
        if not self._closed:
            self._closed = True
            self._lib.rtpu_store_unlink(self._handle)

    def detach_leak(self) -> None:
        """Attached-handle shutdown while views may still be live:
        deliberately LEAK the mapping (and fd) so outstanding
        np.frombuffer views stay valid — munmap would turn them into
        SIGSEGVs and a freed handle would make a late view finalizer a
        use-after-free. The handle just stops answering."""
        self._closed = True
