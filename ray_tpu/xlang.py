"""Cross-language API: export Python callables for non-Python clients.

Reference capability: `python/ray/cross_language.py` + the C++ API's
task/actor submission (`cpp/include/ray/api.h`). Functions and actor
classes are exported under stable NAMES to the cluster KV; a C++ client
(`native/cpp_client.cc`: rtc_submit_task / rtc_create_actor /
rtc_call_actor) submits by name with msgpack-plain args and receives
msgpack-plain results — no Python pickles ever cross the language
boundary. Execution happens on the daemon's pooled Python workers.
"""

from __future__ import annotations

from typing import Any, Callable

import cloudpickle


def _head_client():
    from ray_tpu._private import worker

    rt = worker.global_runtime()
    if rt is None:
        raise RuntimeError("ray_tpu.init() first")
    backend = getattr(rt, "cluster_backend", None)
    return getattr(backend, "head", None)


def _kv_put(key: str, blob: bytes) -> None:
    head = _head_client()
    if head is not None:          # daemons mode: the KV C++ clients see
        head.kv_put(key.encode(), blob)
        return
    from ray_tpu._private import worker
    worker.global_runtime().gcs.kv_put(key.encode(), blob)


def export_task(name: str, fn: Callable) -> None:
    """Make ``fn`` invocable by name from non-Python clients
    (C++: ``rtc_submit_task(h, name, args_msgpack)``)."""
    _kv_put(f"xlang:fn:{name}", cloudpickle.dumps(fn))


def export_actor_class(name: str, cls: Any) -> None:
    """Make ``cls`` instantiable by name from non-Python clients
    (C++: ``rtc_create_actor(h, cls_name, actor_name, args)`` then
    ``rtc_call_actor(h, actor_name, method, args)``)."""
    _kv_put(f"xlang:actor:{name}", cloudpickle.dumps(cls))
