"""Dataset: lazy distributed data (reference: `python/ray/data/dataset.py`).

Lazy logical plan → optimizer → streaming executor (execution.py). Barrier
ops (shuffle/sort/repartition/aggregate/zip) materialize; map chains
stream. Blocks are Arrow tables in the object store.
"""

from __future__ import annotations

import threading
from typing import (Any, Callable, Dict, Iterator, List, Optional, Tuple,
                    Union as TUnion)

import numpy as np
import pyarrow as pa

import ray_tpu
from ray_tpu.data import logical as L
from ray_tpu.data.aggregate import (AggregateFn, Count, Max, Mean, Min, Std,
                                    Sum)
from ray_tpu.data.block import (Block, BlockAccessor, block_from_batch,
                                block_from_rows, concat_blocks)
from ray_tpu.data.execution import (StreamingExecutor, plan_chain,
                                    run_aggregate, run_all_to_all,
                                    run_join)
from ray_tpu.data.iterator import DataIterator


def _json_default(o):
    """numpy scalars/arrays inside rows -> plain JSON values. bytes are
    REJECTED: lossy replace-decoding would silently corrupt binary
    columns (use write_parquet or write_webdataset for those)."""
    import numpy as _np
    if isinstance(o, _np.integer):
        return int(o)
    if isinstance(o, _np.floating):
        return float(o)
    if isinstance(o, _np.ndarray):
        return o.tolist()
    if isinstance(o, bytes):
        raise TypeError(
            "binary column in write_json — bytes do not round-trip "
            "through JSON; use write_parquet or write_webdataset")
    raise TypeError(f"not JSON serializable: {type(o).__name__}")


class Dataset:
    def __init__(self, root: L.LogicalOp):
        self._root = root
        from ray_tpu.data.context import DatasetStats
        self._stats = DatasetStats()

    # ------------------------------------------------------------------
    # transforms (lazy)
    # ------------------------------------------------------------------
    def _derive(self, op: L.LogicalOp) -> "Dataset":
        return Dataset(op)

    def map(self, fn: Callable[[Dict], Dict]) -> "Dataset":
        return self._derive(L.MapRows("map", [self._root], fn=fn,
                                      kind="map"))

    def filter(self, fn: Callable[[Dict], bool]) -> "Dataset":
        return self._derive(L.MapRows("filter", [self._root], fn=fn,
                                      kind="filter"))

    def flat_map(self, fn: Callable[[Dict], List[Dict]]) -> "Dataset":
        return self._derive(L.MapRows("flat_map", [self._root], fn=fn,
                                      kind="flat_map"))

    def map_batches(self, fn: TUnion[Callable, type], *,
                    batch_format: str = "numpy",
                    batch_size: Optional[int] = None,
                    concurrency: Optional[TUnion[int, Tuple[int, int]]]
                    = None, **kwargs) -> "Dataset":
        if isinstance(fn, type):  # stateful class → actor pool
            conc = (concurrency if isinstance(concurrency, tuple)
                    else (1, concurrency or 2))
            return self._derive(L.MapBatches(
                f"map_batches({fn.__name__})", [self._root], fn=fn,
                fn_constructor=fn, batch_format=batch_format,
                concurrency=conc, batch_size=batch_size))
        return self._derive(L.MapBatches(
            "map_batches", [self._root], fn=fn, batch_format=batch_format,
            batch_size=batch_size))

    def add_column(self, name: str, fn: Callable[[Any], Any]) -> "Dataset":
        def add(batch):
            batch[name] = fn(batch)
            return batch
        return self.map_batches(add)

    def drop_columns(self, cols: List[str]) -> "Dataset":
        def drop(t: pa.Table):
            return t.drop_columns([c for c in cols if c in t.column_names])
        return self.map_batches(drop, batch_format="pyarrow")

    def select_columns(self, cols: List[str]) -> "Dataset":
        return self.map_batches(lambda t: t.select(cols),
                                batch_format="pyarrow")

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        def rename(t: pa.Table):
            return t.rename_columns(
                [mapping.get(c, c) for c in t.column_names])
        return self.map_batches(rename, batch_format="pyarrow")

    def limit(self, n: int) -> "Dataset":
        return self._derive(L.Limit("limit", [self._root], limit=n))

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._derive(L.AllToAll("repartition", [self._root],
                                       kind="repartition",
                                       num_outputs=num_blocks))

    def random_shuffle(self, *, seed: Optional[int] = None,
                       num_blocks: Optional[int] = None) -> "Dataset":
        return self._derive(L.AllToAll("random_shuffle", [self._root],
                                       kind="shuffle", seed=seed,
                                       num_outputs=num_blocks))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        return self._derive(L.AllToAll("sort", [self._root], kind="sort",
                                       key=key, descending=descending))

    def union(self, *others: "Dataset") -> "Dataset":
        return self._derive(L.Union(
            "union", [self._root] + [o._root for o in others]))

    def zip(self, other: "Dataset") -> "Dataset":
        return self._derive(L.Zip("zip", [self._root, other._root]))

    def join(self, other: "Dataset", *, on: str, how: str = "inner",
             num_partitions: Optional[int] = None) -> "Dataset":
        """Distributed hash join (reference: Dataset.join /
        `execution/operators/join.py`)."""
        return self._derive(L.Join("join", [self._root, other._root],
                                   key=on, how=how,
                                   num_partitions=num_partitions))

    def random_sample(self, fraction: float,
                      seed: Optional[int] = None) -> "Dataset":
        rng_seed = seed

        def sample(batch: pa.Table):
            rng = np.random.default_rng(rng_seed)
            keep = rng.random(batch.num_rows) < fraction
            return batch.take(np.nonzero(keep)[0])
        return self.map_batches(sample, batch_format="pyarrow")

    def groupby(self, key: Optional[str]) -> "GroupedData":
        return GroupedData(self, key)

    # ------------------------------------------------------------------
    # execution
    # ------------------------------------------------------------------
    def _execute_refs(self) -> List[Any]:
        """Materialize: run the plan to completion, return block refs."""
        return list(self._stream_refs())

    def _stream_refs(self) -> Iterator[Any]:
        """Streaming execution; barrier prefixes materialize first."""
        root = L.optimize(self._root)
        yield from _stream_node(root, stats=self._stats)

    def materialize(self) -> "Dataset":
        refs = self._execute_refs()
        return Dataset(L.InputData("input", [], block_refs=refs))

    def iter_blocks(self) -> Iterator[Block]:
        for ref in self._stream_refs():
            yield ray_tpu.get(ref)

    # ------------------------------------------------------------------
    # consumption
    # ------------------------------------------------------------------
    def iter_rows(self) -> Iterator[Dict]:
        return DataIterator(self.iter_blocks).iter_rows()

    def iter_batches(self, **kwargs) -> Iterator[Any]:
        return DataIterator(self.iter_blocks).iter_batches(**kwargs)

    def iterator(self) -> DataIterator:
        return DataIterator(self.iter_blocks)

    def to_jax(self, **kwargs) -> Iterator[Any]:
        return DataIterator(self.iter_blocks).to_jax(**kwargs)

    def iter_torch_batches(self, **kwargs) -> Iterator[Any]:
        return DataIterator(self.iter_blocks).iter_torch_batches(**kwargs)

    def take(self, n: int = 20) -> List[Dict]:
        out: List[Dict] = []
        for ref in self.limit(n)._stream_refs():
            out.extend(BlockAccessor(ray_tpu.get(ref)).to_rows())
            if len(out) >= n:
                break
        return out[:n]

    def take_all(self) -> List[Dict]:
        out: List[Dict] = []
        for block in self.iter_blocks():
            out.extend(BlockAccessor(block).to_rows())
        return out

    def count(self) -> int:
        return sum(b.num_rows for b in self.iter_blocks())

    def schema(self) -> Optional[pa.Schema]:
        for block in self.iter_blocks():
            if block.num_rows or block.column_names:
                return block.schema
        return None

    def columns(self) -> List[str]:
        s = self.schema()
        return list(s.names) if s is not None else []

    def num_blocks(self) -> int:
        return len(self._execute_refs())

    def to_pandas(self):
        return concat_blocks(list(self.iter_blocks())).to_pandas()

    def to_arrow_refs(self) -> List[Any]:
        return self._execute_refs()

    def unique(self, column: str) -> List[Any]:
        vals: set = set()
        for block in self.iter_blocks():
            vals.update(
                block.column(column).to_numpy(zero_copy_only=False)
                .tolist())
        return sorted(vals)

    def _scalar_agg(self, agg: AggregateFn):
        table = self.groupby(None).aggregate(agg).take_all()
        return table[0][agg.name] if table else None

    def sum(self, on: Optional[str] = None):
        return self._scalar_agg(Sum(on))

    def min(self, on: Optional[str] = None):
        return self._scalar_agg(Min(on))

    def max(self, on: Optional[str] = None):
        return self._scalar_agg(Max(on))

    def mean(self, on: Optional[str] = None):
        return self._scalar_agg(Mean(on))

    def std(self, on: Optional[str] = None):
        return self._scalar_agg(Std(on))

    # ------------------------------------------------------------------
    # splits
    # ------------------------------------------------------------------
    def split(self, n: int, *, equal: bool = False) -> List["Dataset"]:
        refs = self._execute_refs()
        if equal:
            blocks = [ray_tpu.get(r) for r in refs]
            whole = concat_blocks(blocks)
            total = whole.num_rows
            out = []
            for i in range(n):
                lo, hi = i * total // n, (i + 1) * total // n
                out.append(Dataset(L.InputData(
                    "input", [],
                    block_refs=[ray_tpu.put(whole.slice(lo, hi - lo))])))
            return out
        return [Dataset(L.InputData("input", [], block_refs=refs[i::n]))
                for i in range(n)]

    def streaming_split(self, n: int, *, equal: bool = False,
                        locality_hints=None) -> List[DataIterator]:
        """n iterators fed by ONE shared streaming execution per epoch
        (reference: `dataset.py:1731` — Train ingest, SURVEY.md §8.13).
        Repeated iteration re-executes the plan: when a shard that already
        consumed the current pass asks for a new iterator, a fresh shared
        pass starts (epoch semantics for SPMD training loops)."""
        lock = threading.Lock()
        dataset = self

        class _Gen:
            def __init__(self):
                self.stream = dataset._stream_refs()
                self.queues: List[List] = [[] for _ in range(n)]
                self.next = 0
                self.done = False
                self.joined: set = set()

        state = {"gen_id": 0, "gens": {0: _Gen()}}

        def join(idx: int) -> "_Gen":
            with lock:
                gen = state["gens"][state["gen_id"]]
                if idx in gen.joined:       # this shard starts a new epoch
                    state["gen_id"] += 1
                    gen = state["gens"][state["gen_id"]] = _Gen()
                gen.joined.add(idx)
                return gen

        def pull_for(idx: int) -> Iterator[Block]:
            gen = join(idx)
            while True:
                with lock:
                    if gen.queues[idx]:
                        ref = gen.queues[idx].pop(0)
                    elif gen.done:
                        return
                    else:
                        try:
                            ref = next(gen.stream)
                        except StopIteration:
                            gen.done = True
                            return
                        owner = gen.next % n
                        gen.next += 1
                        if owner != idx:
                            gen.queues[owner].append(ref)
                            continue
                yield ray_tpu.get(ref)

        return [DataIterator(lambda i=i: pull_for(i),
                             pickle_recipe=(self, n, i))
                for i in range(n)]

    def train_test_split(self, test_size: float, *,
                         shuffle: bool = False,
                         seed: Optional[int] = None
                         ) -> Tuple["Dataset", "Dataset"]:
        ds = self.random_shuffle(seed=seed) if shuffle else self
        whole = concat_blocks(list(ds.iter_blocks()))
        n_test = int(whole.num_rows * test_size)
        n_train = whole.num_rows - n_test
        train = Dataset(L.InputData(
            "input", [], block_refs=[ray_tpu.put(whole.slice(0, n_train))]))
        test = Dataset(L.InputData(
            "input", [],
            block_refs=[ray_tpu.put(whole.slice(n_train, n_test))]))
        return train, test

    # ------------------------------------------------------------------
    # writes
    # ------------------------------------------------------------------
    def write_parquet(self, path: str) -> None:
        import pyarrow.parquet as pq

        from ray_tpu.data.filesystem import resolve_filesystem
        fs, local = resolve_filesystem(path)
        fs.makedirs(local)
        for i, block in enumerate(self.iter_blocks()):
            if block.num_rows:
                with fs.open_output(
                        f"{local}/part-{i:05d}.parquet") as f:
                    pq.write_table(block, f)

    def write_csv(self, path: str) -> None:
        import pyarrow.csv as pacsv

        from ray_tpu.data.filesystem import resolve_filesystem
        fs, local = resolve_filesystem(path)
        fs.makedirs(local)
        for i, block in enumerate(self.iter_blocks()):
            if block.num_rows:
                with fs.open_output(f"{local}/part-{i:05d}.csv") as f:
                    pacsv.write_csv(block, f)

    def write_json(self, path: str) -> None:
        """One JSONL file per block (reference: Dataset.write_json)."""
        import json as _json

        from ray_tpu.data.filesystem import resolve_filesystem
        fs, local = resolve_filesystem(path)
        fs.makedirs(local)
        for i, block in enumerate(self.iter_blocks()):
            if block.num_rows:
                lines = "\n".join(
                    _json.dumps(row, default=_json_default)
                    for row in block.to_pylist())
                with fs.open_output(f"{local}/part-{i:05d}.json") as f:
                    f.write((lines + "\n").encode())

    def write_numpy(self, path: str, column: str) -> None:
        """One .npy file per block from ``column`` (reference:
        Dataset.write_numpy)."""
        import io as _io

        import numpy as _np

        from ray_tpu.data.filesystem import resolve_filesystem
        fs, local = resolve_filesystem(path)
        fs.makedirs(local)
        for i, block in enumerate(self.iter_blocks()):
            if block.num_rows:
                arr = _np.asarray(
                    block.column(column).to_numpy(zero_copy_only=False))
                buf = _io.BytesIO()
                _np.save(buf, arr)
                with fs.open_output(f"{local}/part-{i:05d}.npy") as f:
                    f.write(buf.getvalue())

    def write_avro(self, path: str) -> None:
        """One Avro Object Container File per block (reference:
        Dataset.write_avro via fastavro; here data/avro.py's native
        codec, deflate blocks, schema inferred per dataset)."""
        from ray_tpu.data.avro import infer_schema, write_container
        from ray_tpu.data.filesystem import resolve_filesystem
        fs, local = resolve_filesystem(path)
        fs.makedirs(local)
        for i, block in enumerate(self.iter_blocks()):
            if block.num_rows:
                rows = block.to_pylist()
                blob = write_container(infer_schema(rows), rows)
                with fs.open_output(f"{local}/part-{i:05d}.avro") as f:
                    f.write(blob)

    def write_orc(self, path: str) -> None:
        """One ORC file per block (reference: Dataset.write_orc)."""
        from pyarrow import orc as _orc

        from ray_tpu.data.filesystem import resolve_filesystem
        fs, local = resolve_filesystem(path)
        fs.makedirs(local)
        for i, block in enumerate(self.iter_blocks()):
            if block.num_rows:
                with fs.open_output(f"{local}/part-{i:05d}.orc") as f:
                    _orc.write_table(block, f)

    def write_tfrecords(self, path: str) -> None:
        """One TFRecord shard per block, rows as tf.train.Example
        (crc32c-framed; no TensorFlow — data/tfrecords.py)."""
        from ray_tpu.data.filesystem import resolve_filesystem
        from ray_tpu.data.tfrecords import (encode_example,
                                            write_tfrecord_frame)
        fs, local = resolve_filesystem(path)
        fs.makedirs(local)
        for i, block in enumerate(self.iter_blocks()):
            if not block.num_rows:
                continue
            frames = b"".join(
                write_tfrecord_frame(encode_example(row))
                for row in block.to_pylist())
            with fs.open_output(f"{local}/part-{i:05d}.tfrecord") as f:
                f.write(frames)

    def write_webdataset(self, path: str) -> None:
        """One WebDataset tar shard per block: each row becomes a
        sample keyed by its ``__key__`` column (or the row index), with
        every other column written as ``<key>.<column>`` (bytes/str
        raw, everything else JSON — reference: Dataset.write_webdataset)."""
        import io as _io
        import json as _json
        import tarfile

        from ray_tpu.data.filesystem import resolve_filesystem
        fs, local = resolve_filesystem(path)
        fs.makedirs(local)
        for i, block in enumerate(self.iter_blocks()):
            if not block.num_rows:
                continue
            buf = _io.BytesIO()
            with tarfile.open(fileobj=buf, mode="w") as tar:
                for j, row in enumerate(block.to_pylist()):
                    key = str(row.pop("__key__", f"{i:05d}{j:06d}"))
                    for col, val in row.items():
                        if isinstance(val, bytes):
                            payload = val
                        elif isinstance(val, str):
                            payload = val.encode()
                        else:
                            payload = _json.dumps(
                                val, default=_json_default).encode()
                        info = tarfile.TarInfo(f"{key}.{col}")
                        info.size = len(payload)
                        tar.addfile(info, _io.BytesIO(payload))
            with fs.open_output(f"{local}/shard-{i:05d}.tar") as f:
                f.write(buf.getvalue())

    def stats(self) -> str:
        """Execution statistics summary (reference: Dataset.stats())."""
        return self._stats.summary()

    def __repr__(self):
        return f"Dataset(plan={self._root.name})"


# ---------------------------------------------------------------------------
# plan execution helpers
# ---------------------------------------------------------------------------

def _stream_node(node: L.LogicalOp, stats=None) -> Iterator[Any]:
    """Yield block refs for a (possibly barrier-containing) plan node."""
    if isinstance(node, L.Union):
        for inp in node.inputs:
            yield from _stream_node(L.optimize(inp))
        return
    if isinstance(node, L.Zip):
        left = [ray_tpu.get(r) for r in _stream_node(L.optimize(
            node.inputs[0]))]
        right = [ray_tpu.get(r) for r in _stream_node(L.optimize(
            node.inputs[1]))]
        lt, rt = concat_blocks(left), concat_blocks(right)
        if lt.num_rows != rt.num_rows:
            raise ValueError(f"zip row mismatch {lt.num_rows} vs "
                             f"{rt.num_rows}")
        for name in rt.column_names:
            col_name = name
            if col_name in lt.column_names:
                col_name = f"{name}_1"
            lt = lt.append_column(col_name, rt.column(name))
        yield ray_tpu.put(lt)
        return
    if isinstance(node, L.Join):
        left = list(_stream_node(L.optimize(node.inputs[0])))
        right = list(_stream_node(L.optimize(node.inputs[1])))
        yield from run_join(node.key, node.how, left, right,
                            node.num_partitions)
        return
    if isinstance(node, L.AllToAll):
        upstream = list(_stream_node(L.optimize(node.inputs[0])))
        yield from run_all_to_all(node, upstream)
        return
    if isinstance(node, L.Aggregate):
        upstream = list(_stream_node(L.optimize(node.inputs[0])))
        yield from run_aggregate(node, upstream)
        return

    # linear streaming chain; find the deepest barrier, materialize it
    chain = node.chain()
    barrier_idx = None
    for i, op in enumerate(chain):
        if isinstance(op, (L.AllToAll, L.Aggregate, L.Union, L.Zip,
                           L.Join)):
            barrier_idx = i
    if barrier_idx is not None:
        refs = list(_stream_node(chain[barrier_idx]))
        suffix = chain[barrier_idx + 1:]
        if not suffix:
            yield from refs
            return
        source: L.LogicalOp = L.InputData("input", [], block_refs=refs)
        for op in suffix:
            op = _clone_with_input(op, source)
            source = op
        chain = source.chain()
    executor = StreamingExecutor(plan_chain(chain), stats=stats)
    yield from executor.execute()


def _clone_with_input(op: L.LogicalOp, inp: L.LogicalOp) -> L.LogicalOp:
    import copy
    clone = copy.copy(op)
    clone.inputs = [inp]
    return clone


class GroupedData:
    """Reference: `python/ray/data/grouped_data.py`."""

    def __init__(self, ds: Dataset, key: Optional[str]):
        self._ds = ds
        self._key = key

    def aggregate(self, *aggs: AggregateFn) -> Dataset:
        return Dataset(L.Aggregate("aggregate", [self._ds._root],
                                   key=self._key, aggs=list(aggs)))

    def map_groups(self, fn: Callable, *, batch_format: str = "numpy"
                   ) -> Dataset:
        return Dataset(L.Aggregate("map_groups", [self._ds._root],
                                   key=self._key, map_groups_fn=fn,
                                   batch_format=batch_format))

    def count(self) -> Dataset:
        return self.aggregate(Count())

    def sum(self, on: Optional[str] = None) -> Dataset:
        return self.aggregate(Sum(on))

    def min(self, on: Optional[str] = None) -> Dataset:
        return self.aggregate(Min(on))

    def max(self, on: Optional[str] = None) -> Dataset:
        return self.aggregate(Max(on))

    def mean(self, on: Optional[str] = None) -> Dataset:
        return self.aggregate(Mean(on))

    def std(self, on: Optional[str] = None) -> Dataset:
        return self.aggregate(Std(on))
