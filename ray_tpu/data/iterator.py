"""Data iteration: batches with prefetch and HBM double-buffering.

Reference: `python/ray/data/iterator.py:106` (iter_batches with
prefetch_batches, formats, local shuffle). TPU-native addition
(BASELINE.md config 4): ``to_jax`` overlaps host→HBM transfer of batch
N+1 with compute on batch N via ``jax.device_put`` double-buffering.
"""

from __future__ import annotations

import collections
import queue
import threading
from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np

from ray_tpu.data.block import Block, BlockAccessor, concat_blocks


def _batches_from_blocks(block_iter: Iterator[Block], batch_size:
                         Optional[int], batch_format: str,
                         drop_last: bool,
                         shuffle_buffer_size: Optional[int] = None,
                         shuffle_seed: Optional[int] = None):
    """Re-chunk a stream of blocks into fixed-size batches."""
    rng = np.random.default_rng(shuffle_seed)
    buffer: List[Block] = []
    buffered = 0

    def emit(table: Block):
        return BlockAccessor(table).to_batch(batch_format)

    carry: Optional[Block] = None
    for block in block_iter:
        if block.num_rows == 0:
            continue
        if shuffle_buffer_size:
            buffer.append(block)
            buffered += block.num_rows
            if buffered < shuffle_buffer_size:
                continue
            block = concat_blocks(buffer)
            block = block.take(rng.permutation(block.num_rows))
            buffer, buffered = [], 0
        carry = block if carry is None else concat_blocks([carry, block])
        if batch_size is None:
            yield emit(carry)
            carry = None
            continue
        while carry is not None and carry.num_rows >= batch_size:
            yield emit(carry.slice(0, batch_size))
            rest = carry.slice(batch_size, carry.num_rows - batch_size)
            carry = rest if rest.num_rows else None
    if buffer:
        block = concat_blocks(buffer)
        block = block.take(rng.permutation(block.num_rows))
        carry = block if carry is None else concat_blocks([carry, block])
        while (carry is not None and batch_size is not None
               and carry.num_rows >= batch_size):
            yield emit(carry.slice(0, batch_size))
            rest = carry.slice(batch_size, carry.num_rows - batch_size)
            carry = rest if rest.num_rows else None
    if carry is not None and carry.num_rows and not drop_last:
        yield emit(carry)


def _prefetched(it: Iterator, n: int) -> Iterator:
    """Run the upstream iterator in a thread, buffering up to n items."""
    if n <= 0:
        yield from it
        return
    q: queue.Queue = queue.Queue(maxsize=n)
    DONE = object()

    def pump():
        try:
            for item in it:
                q.put(item)
            q.put(DONE)
        except BaseException as e:  # propagate into consumer
            q.put(e)

    t = threading.Thread(target=pump, daemon=True)
    t.start()
    while True:
        item = q.get()
        if item is DONE:
            return
        if isinstance(item, BaseException):
            raise item
        yield item


def _rebuild_strided_iterator(dataset, n: int, index: int
                              ) -> "DataIterator":
    """Pickle-side reconstruction of a streaming_split shard: a
    process-local pass over the blocks whose ARRIVAL index is
    ``index (mod n)``. The in-process shards share ONE execution behind
    a lock; a shard that crossed a process boundary cannot share that
    generator, so it degrades to its own pass over the same disjoint,
    covering strided subset."""
    def pull():
        for j, block in enumerate(dataset.iter_blocks()):
            if j % n == index:
                yield block

    return DataIterator(pull, pickle_recipe=(dataset, n, index))


class DataIterator:
    """Iterator facade over a stream of blocks (one per consumer shard)."""

    def __init__(self, block_iter_factory: Callable[[], Iterator[Block]],
                 pickle_recipe=None):
        self._factory = block_iter_factory
        # (dataset, n, index) for shards that may travel between
        # processes (Tune trials pickle whole Trainers, datasets and
        # shard iterators included); the live shared-pass closure holds
        # a lock and cannot cross the boundary itself
        self._pickle_recipe = pickle_recipe

    def __reduce__(self):
        if self._pickle_recipe is None:
            raise TypeError(
                "this DataIterator wraps a process-local stream and "
                "cannot be pickled; build it from streaming_split for "
                "a transferable shard")
        return (_rebuild_strided_iterator, self._pickle_recipe)

    def iter_blocks(self) -> Iterator[Block]:
        return self._factory()

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for block in self._factory():
            yield from BlockAccessor(block).to_rows()

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "numpy",
                     prefetch_batches: int = 1,
                     drop_last: bool = False,
                     local_shuffle_buffer_size: Optional[int] = None,
                     local_shuffle_seed: Optional[int] = None
                     ) -> Iterator[Any]:
        batches = _batches_from_blocks(
            self._factory(), batch_size, batch_format, drop_last,
            local_shuffle_buffer_size, local_shuffle_seed)
        return _prefetched(batches, prefetch_batches)

    def iter_torch_batches(self, *, batch_size: Optional[int] = 256,
                           dtypes=None, device: str = "cpu",
                           **kwargs) -> Iterator[Any]:
        """Batches as torch tensors (reference: iter_torch_batches)."""
        import torch
        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy", **kwargs):
            out = {}
            for k, v in batch.items():
                t = torch.as_tensor(v)
                if dtypes and k in dtypes:
                    t = t.to(dtypes[k])
                out[k] = t.to(device) if device != "cpu" else t
            yield out

    def to_jax(self, *, batch_size: int, sharding=None,
               prefetch: int = 2, drop_last: bool = True,
               dtypes: Optional[Dict[str, Any]] = None) -> Iterator[Any]:
        """Device-prefetching iterator: batch N+1 is already transferring
        to HBM while batch N computes."""
        import jax

        def to_device(batch: Dict[str, np.ndarray]):
            out = {}
            for k, v in batch.items():
                if dtypes and k in dtypes:
                    v = v.astype(dtypes[k])
                out[k] = (jax.device_put(v, sharding) if sharding is not None
                          else jax.device_put(v))
            return out

        host = self.iter_batches(batch_size=batch_size,
                                 batch_format="numpy",
                                 prefetch_batches=prefetch,
                                 drop_last=drop_last)
        buf: collections.deque = collections.deque()
        for batch in host:
            buf.append(to_device(batch))   # starts async H2D copy
            if len(buf) > prefetch:
                yield buf.popleft()
        while buf:
            yield buf.popleft()
