"""S3 filesystem for the data layer's filesystem seam.

Reference capability: the reference's datasources read/write
``s3://bucket/key`` through pyarrow's S3 filesystem. This build has no
boto/pyarrow-s3; here is a dependency-free implementation over the S3
REST API (stdlib urllib + hmac): AWS Signature V4 signing when
credentials are present, anonymous requests otherwise — so it works
against real S3, MinIO, or the in-repo mock used by tests
(reference test pattern: ``python/ray/data/tests/mock_s3_server.py``).

Activate with::

    from ray_tpu.data.s3_filesystem import S3FileSystem, enable_s3
    enable_s3()                                  # s3:// via env creds
    enable_s3(endpoint_url="http://127.0.0.1:9000")   # MinIO/mock

Paths inside the seam are ``bucket/key...`` (scheme already stripped by
``resolve_filesystem``).
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import io
import os
import urllib.error
import urllib.parse
import urllib.request
from typing import IO, List, Optional, Tuple
from xml.etree import ElementTree

from ray_tpu.data.filesystem import FileSystem, register_filesystem


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


class S3FileSystem(FileSystem):
    scheme = "s3"

    def __init__(self, endpoint_url: Optional[str] = None,
                 region: str = "us-east-1",
                 access_key: Optional[str] = None,
                 secret_key: Optional[str] = None):
        self.endpoint = (endpoint_url
                         or f"https://s3.{region}.amazonaws.com").rstrip("/")
        self.region = region
        self.access_key = access_key or os.environ.get("AWS_ACCESS_KEY_ID")
        self.secret_key = secret_key or os.environ.get(
            "AWS_SECRET_ACCESS_KEY")

    # -- request plumbing -------------------------------------------------
    def _sign(self, method: str, path: str, query: str,
              payload: bytes) -> dict:
        """AWS SigV4 headers (anonymous when no credentials)."""
        host = urllib.parse.urlparse(self.endpoint).netloc
        now = datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        datestamp = now.strftime("%Y%m%d")
        payload_hash = _sha256(payload)
        headers = {"host": host, "x-amz-date": amz_date,
                   "x-amz-content-sha256": payload_hash}
        if not (self.access_key and self.secret_key):
            headers.pop("x-amz-content-sha256")
            return headers
        signed = ";".join(sorted(headers))
        canonical = "\n".join([
            method, urllib.parse.quote(path), query,
            "".join(f"{k}:{headers[k]}\n" for k in sorted(headers)),
            signed, payload_hash])
        scope = f"{datestamp}/{self.region}/s3/aws4_request"
        to_sign = "\n".join(["AWS4-HMAC-SHA256", amz_date, scope,
                             _sha256(canonical.encode())])
        key = _hmac(_hmac(_hmac(_hmac(
            ("AWS4" + self.secret_key).encode(), datestamp),
            self.region), "s3"), "aws4_request")
        signature = hmac.new(key, to_sign.encode(),
                             hashlib.sha256).hexdigest()
        headers["Authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
            f"SignedHeaders={signed}, Signature={signature}")
        return headers

    def _request(self, method: str, path: str, query: str = "",
                 payload: bytes = b"") -> Tuple[int, bytes]:
        url = self.endpoint + urllib.parse.quote(path)
        if query:
            url += "?" + query
        req = urllib.request.Request(
            url, data=payload if method in ("PUT", "POST") else None,
            method=method,
            headers=self._sign(method, path, query, payload))
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                return resp.status, resp.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()

    @staticmethod
    def _split(path: str) -> Tuple[str, str]:
        bucket, _, key = path.partition("/")
        return bucket, key

    # -- FileSystem protocol ----------------------------------------------
    def open_input(self, path: str) -> IO[bytes]:
        bucket, key = self._split(path)
        status, body = self._request("GET", f"/{bucket}/{key}")
        if status == 404:
            raise FileNotFoundError(f"s3://{path}")
        if status != 200:
            raise IOError(f"s3 GET {path}: HTTP {status}: {body[:200]!r}")
        return io.BytesIO(body)

    def open_output(self, path: str) -> IO[bytes]:
        fs = self

        class _Writer(io.BytesIO):
            _aborted = False

            def __exit__(self, exc_type, exc, tb):
                # an exception inside the `with` block must NOT upload
                # the partial buffer (a truncated object would corrupt
                # the dataset) nor mask the original error
                if exc_type is not None:
                    self._aborted = True
                return super().__exit__(exc_type, exc, tb)

            def close(self) -> None:
                if self.closed:
                    return
                aborted = self._aborted
                data = self.getvalue()
                super().close()
                if aborted:
                    return
                bucket, key = fs._split(path)
                status, body = fs._request("PUT", f"/{bucket}/{key}",
                                           payload=data)
                if status not in (200, 201):
                    raise IOError(f"s3 PUT {path}: HTTP {status}: "
                                  f"{body[:200]!r}")

        return _Writer()

    def exists(self, path: str) -> bool:
        bucket, key = self._split(path)
        if not key:
            return True
        status, _ = self._request("HEAD", f"/{bucket}/{key}")
        if status == 200:
            return True
        return bool(self._list(bucket, key.rstrip("/") + "/",
                               max_keys=1)[0])

    def isdir(self, path: str) -> bool:
        bucket, key = self._split(path)
        if not key:
            return True
        status, _ = self._request("HEAD", f"/{bucket}/{key}")
        if status == 200 and not key.endswith("/"):
            return False
        return bool(self._list(bucket, key.rstrip("/") + "/",
                               max_keys=1)[0])

    def _list(self, bucket: str, prefix: str, delimiter: str = "",
              max_keys: int = 1000) -> Tuple[List[str], List[str]]:
        # canonical (SigV4) form: sorted pairs, %-encoding with the
        # AWS-unreserved charset — the same string is signed and sent
        params = {"list-type": "2", "prefix": prefix,
                  "max-keys": str(max_keys),
                  **({"delimiter": delimiter} if delimiter else {})}
        query = "&".join(
            f"{urllib.parse.quote(k, safe='-_.~')}="
            f"{urllib.parse.quote(str(v), safe='-_.~')}"
            for k, v in sorted(params.items()))
        status, body = self._request("GET", f"/{bucket}", query=query)
        if status != 200:
            raise IOError(f"s3 LIST {bucket}/{prefix}: HTTP {status}")
        ns = "{http://s3.amazonaws.com/doc/2006-03-01/}"
        root = ElementTree.fromstring(body)
        keys = [el.findtext(f"{ns}Key") for el in root.iter(f"{ns}Contents")]
        prefixes = [el.findtext(f"{ns}Prefix")
                    for el in root.iter(f"{ns}CommonPrefixes")]
        return [k for k in keys if k], [p for p in prefixes if p]

    def listdir(self, path: str) -> List[str]:
        bucket, key = self._split(path)
        prefix = key.rstrip("/") + "/" if key else ""
        keys, prefixes = self._list(bucket, prefix, delimiter="/")
        out = [f"{bucket}/{k}" for k in keys if k != prefix]
        out += [f"{bucket}/{p.rstrip('/')}" for p in prefixes]
        return sorted(out)

    def glob(self, pattern: str) -> List[str]:
        import fnmatch

        bucket, key = self._split(pattern)
        prefix = key.split("*", 1)[0]
        keys, _ = self._list(bucket, prefix)
        return sorted(f"{bucket}/{k}" for k in keys
                      if fnmatch.fnmatch(k, key))

    def makedirs(self, path: str) -> None:
        pass   # S3 has no directories


def enable_s3(**kwargs) -> S3FileSystem:
    """Register s3:// with the data layer (register_filesystem seam)."""
    fs = S3FileSystem(**kwargs)
    register_filesystem("s3", fs)
    return fs


class GcsFileSystem(S3FileSystem):
    """Google Cloud Storage via its documented XML interoperability API
    (reference capability: gs:// datasources through pyarrow's GcsFileSystem).

    GCS's interop mode speaks the same XML protocol and SigV4 HMAC
    signing as S3 (https://cloud.google.com/storage/docs/interoperability),
    so this is the S3 implementation pointed at
    ``storage.googleapis.com`` with GCS HMAC credentials
    (``GS_ACCESS_KEY_ID``/``GS_SECRET_ACCESS_KEY``, falling back to the
    AWS names for mocks/MinIO-style endpoints). Anonymous requests work
    for public buckets and test servers."""

    scheme = "gs"

    def __init__(self, endpoint_url: Optional[str] = None,
                 region: str = "auto",
                 access_key: Optional[str] = None,
                 secret_key: Optional[str] = None):
        super().__init__(
            endpoint_url=endpoint_url or "https://storage.googleapis.com",
            region=region,
            access_key=access_key or os.environ.get("GS_ACCESS_KEY_ID"),
            secret_key=secret_key or os.environ.get(
                "GS_SECRET_ACCESS_KEY"))


def enable_gs(**kwargs) -> GcsFileSystem:
    """Register gs:// (and gcs://) with the data layer."""
    fs = GcsFileSystem(**kwargs)
    register_filesystem("gs", fs)
    register_filesystem("gcs", fs)
    return fs
