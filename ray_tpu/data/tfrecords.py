"""TFRecord + tf.train.Example codec, dependency-free.

Reference capability: `python/ray/data/read_api.py` read_tfrecords /
`datasource/tfrecords_datasource.py` (which imports TensorFlow). This
image has no TF, and the formats are simple enough to speak directly:

- TFRecord framing: ``u64 length | u32 masked-crc32c(length) | payload
  | u32 masked-crc32c(payload)`` (crc32c = Castagnoli polynomial, NOT
  zlib's crc32; mask = ((crc >> 15 | crc << 17) + 0xa282ead8)).
- tf.train.Example proto: ``features.feature`` map of name ->
  Feature{ bytes_list=1 | float_list=2 | int64_list=3 }, hand-decoded
  with a minimal varint/length-delimited parser (floats are packed or
  unpacked fixed32, int64s packed or unpacked varints).

Scalars unwrap to plain values; multi-element lists stay lists.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, Iterator, List, Tuple

# ---------------------------------------------------------------------------
# crc32c (Castagnoli), table-driven
# ---------------------------------------------------------------------------

_CRC_TABLE: List[int] = []


def _crc_table() -> List[int]:
    global _CRC_TABLE
    if not _CRC_TABLE:
        poly = 0x82F63B78
        table = []
        for n in range(256):
            c = n
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            table.append(c)
        _CRC_TABLE = table
    return _CRC_TABLE


def crc32c(data: bytes) -> int:
    table = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# TFRecord framing
# ---------------------------------------------------------------------------

def read_tfrecord_frames(blob: bytes) -> Iterator[bytes]:
    off = 0
    n = len(blob)
    while off < n:
        if off + 12 > n:
            raise ValueError("truncated TFRecord header")
        (length,) = struct.unpack_from("<Q", blob, off)
        (len_crc,) = struct.unpack_from("<I", blob, off + 8)
        if _masked_crc(blob[off:off + 8]) != len_crc:
            raise ValueError("TFRecord length crc mismatch")
        start = off + 12
        if start + length + 4 > n:
            raise ValueError("truncated TFRecord payload")
        payload = blob[start:start + length]
        (data_crc,) = struct.unpack_from("<I", blob, start + length)
        if _masked_crc(payload) != data_crc:
            raise ValueError("TFRecord data crc mismatch")
        yield payload
        off = start + length + 4


def write_tfrecord_frame(payload: bytes) -> bytes:
    header = struct.pack("<Q", len(payload))
    return (header + struct.pack("<I", _masked_crc(header)) + payload
            + struct.pack("<I", _masked_crc(payload)))


# ---------------------------------------------------------------------------
# minimal protobuf wire codec (the subset tf.train.Example uses)
# ---------------------------------------------------------------------------

def _read_varint(buf: bytes, off: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[off]
        off += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, off
        shift += 7


def _write_varint(value: int) -> bytes:
    out = bytearray()
    while True:
        bits = value & 0x7F
        value >>= 7
        if value:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def _fields(buf: bytes) -> Iterator[Tuple[int, int, Any]]:
    """(field_number, wire_type, value) over one message."""
    off = 0
    n = len(buf)
    while off < n:
        key, off = _read_varint(buf, off)
        field, wt = key >> 3, key & 7
        if wt == 0:                     # varint
            val, off = _read_varint(buf, off)
        elif wt == 2:                   # length-delimited
            ln, off = _read_varint(buf, off)
            val = buf[off:off + ln]
            off += ln
        elif wt == 5:                   # fixed32
            (val,) = struct.unpack_from("<I", buf, off)
            off += 4
        elif wt == 1:                   # fixed64
            (val,) = struct.unpack_from("<Q", buf, off)
            off += 8
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield field, wt, val


def _decode_feature(buf: bytes) -> List[Any]:
    for field, wt, val in _fields(buf):
        if field == 1:                  # BytesList { repeated bytes 1 }
            return [v for f, _, v in _fields(val) if f == 1]
        if field == 2:                  # FloatList { repeated float 1 }
            out: List[float] = []
            for f, w, v in _fields(val):
                if f != 1:
                    continue
                if w == 2:              # packed
                    out.extend(struct.unpack(f"<{len(v) // 4}f", v))
                else:                   # unpacked fixed32
                    out.append(struct.unpack("<f",
                                             struct.pack("<I", v))[0])
            return out
        if field == 3:                  # Int64List { repeated int64 1 }
            out = []
            for f, w, v in _fields(val):
                if f != 1:
                    continue
                if w == 2:              # packed varints
                    off = 0
                    while off < len(v):
                        x, off = _read_varint(v, off)
                        out.append(x - (1 << 64) if x >= 1 << 63 else x)
                else:
                    out.append(v - (1 << 64) if v >= 1 << 63 else v)
            return out
    return []


def decode_example(payload: bytes) -> Dict[str, Any]:
    """tf.train.Example bytes -> {name: list}. Features are ALWAYS
    lists here (proto semantics); per-COLUMN scalar unwrapping is the
    reader's job — a per-row unwrap would mix scalars and lists in one
    column when lengths vary ([5] vs [1, 2])."""
    row: Dict[str, Any] = {}
    for field, _, val in _fields(payload):
        if field != 1:                  # Example.features
            continue
        for f2, _, fmap in _fields(val):
            if f2 != 1:                 # Features.feature map entry
                continue
            name = b""
            feat: List[Any] = []
            for f3, _, v3 in _fields(fmap):
                if f3 == 1:
                    name = v3
                elif f3 == 2:
                    feat = _decode_feature(v3)
            row[name.decode()] = feat
    return row




def _ld(field: int, payload: bytes) -> bytes:
    return _write_varint(field << 3 | 2) + _write_varint(
        len(payload)) + payload


def encode_example(row: Dict[str, Any]) -> bytes:
    """{name: value} -> tf.train.Example bytes. bytes/str -> BytesList,
    float -> FloatList, int/bool -> Int64List (lists of same kind ok)."""
    entries = b""
    for name, value in row.items():
        vals = value if isinstance(value, (list, tuple)) else [value]
        if all(isinstance(v, (bytes, str)) for v in vals):
            inner = b"".join(
                _ld(1, v.encode() if isinstance(v, str) else v)
                for v in vals)
            feature = _ld(1, inner)
        elif all(isinstance(v, bool) or isinstance(v, int)
                 for v in vals):
            for v in vals:
                if not -(1 << 63) <= int(v) < (1 << 63):
                    raise ValueError(
                        f"feature {name!r}: {v} outside int64 range "
                        f"(would wrap silently on round-trip)")
            packed = b"".join(_write_varint(int(v) & ((1 << 64) - 1))
                              for v in vals)
            feature = _ld(3, _ld(1, packed))
        elif all(isinstance(v, (int, float)) for v in vals):
            packed = struct.pack(f"<{len(vals)}f",
                                 *[float(v) for v in vals])
            feature = _ld(2, _ld(1, packed))
        else:
            raise TypeError(
                f"feature {name!r}: unsupported value types "
                f"{[type(v).__name__ for v in vals]}")
        entry = _ld(1, name.encode()) + _ld(2, feature)
        entries += _ld(1, entry)
    return _ld(1, entries)              # Example.features
