"""Blocks: the unit of distributed data.

Reference: `python/ray/data/block.py:51` — a Block is an Arrow table (or
pandas) stored in the object store; BlockAccessor adapts formats. Here the
canonical block is a ``pyarrow.Table``; batches convert to numpy dicts /
pandas / arrow on demand. TPU relevance: numpy-dict batches feed
``jax.device_put`` zero-copy (arrow→numpy is zero-copy for primitive
types).
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Union

import numpy as np
import pyarrow as pa

Block = pa.Table
Row = Dict[str, Any]
Batch = Union[Dict[str, np.ndarray], "pa.Table", Any]


def block_from_rows(rows: List[Row]) -> Block:
    if not rows:
        return pa.table({})
    if not isinstance(rows[0], dict):
        rows = [{"item": r} for r in rows]
    cols: Dict[str, List] = {k: [] for k in rows[0]}
    for r in rows:
        for k in cols:
            cols[k].append(r.get(k))
    return pa.table({k: pa.array(v) for k, v in cols.items()})


def _column_from_numpy(v) -> "pa.Array":
    arr = np.asarray(v)
    if arr.ndim > 1 and arr.dtype != object:
        if any(s == 0 for s in arr.strides) or not arr.flags.c_contiguous:
            # Arrow's tensor import rejects degenerate strides (numpy uses
            # stride 0 for size-1 dims even on contiguous arrays); rebuild
            # with canonical strides.
            fixed = np.empty(arr.shape, arr.dtype)
            fixed[...] = arr
            arr = fixed
        # fixed-shape tensor column: preserves dtype/shape, zero-copy both
        # ways (reference: ray.data ArrowTensorArray extension type)
        return pa.FixedShapeTensorArray.from_numpy_ndarray(arr)
    return pa.array(v)


def block_from_batch(batch: Batch) -> Block:
    if isinstance(batch, pa.Table):
        return batch
    if isinstance(batch, dict):
        return pa.table({k: _column_from_numpy(v)
                         for k, v in batch.items()})
    try:
        import pandas as pd
        if isinstance(batch, pd.DataFrame):
            return pa.Table.from_pandas(batch, preserve_index=False)
    except ImportError:
        pass
    raise TypeError(f"cannot convert {type(batch)} to a block")


class BlockAccessor:
    """Format adapter over a block (reference: BlockAccessor.for_block)."""

    def __init__(self, block: Block):
        self.block = block

    @staticmethod
    def for_block(block: Block) -> "BlockAccessor":
        return BlockAccessor(block)

    def num_rows(self) -> int:
        return self.block.num_rows

    def size_bytes(self) -> int:
        return self.block.nbytes

    def schema(self):
        return self.block.schema

    def to_rows(self) -> List[Row]:
        return self.block.to_pylist()

    def to_batch(self, batch_format: str = "numpy") -> Batch:
        if batch_format in ("numpy", "dict"):
            out: Dict[str, np.ndarray] = {}
            for name in self.block.column_names:
                col = self.block.column(name)
                chunked = col.combine_chunks() if isinstance(
                    col, pa.ChunkedArray) else col
                if isinstance(chunked.type, pa.FixedShapeTensorType):
                    out[name] = chunked.to_numpy_ndarray()
                    continue
                try:
                    out[name] = col.to_numpy(zero_copy_only=False)
                except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
                    out[name] = np.asarray(col.to_pylist(), dtype=object)
                if out[name].dtype == object and len(out[name]) and \
                        isinstance(out[name][0], (list, np.ndarray)):
                    try:
                        out[name] = np.stack(
                            [np.asarray(x) for x in out[name]])
                    except ValueError:
                        pass  # ragged: keep object array
            return out
        if batch_format in ("pyarrow", "arrow"):
            return self.block
        if batch_format == "pandas":
            return self.block.to_pandas()
        raise ValueError(f"unknown batch_format {batch_format!r}")

    def slice(self, start: int, end: int) -> Block:
        return self.block.slice(start, end - start)

    def take_rows(self, indices: np.ndarray) -> Block:
        return self.block.take(pa.array(indices))


def concat_blocks(blocks: Iterable[Block]) -> Block:
    blocks = list(blocks)
    nonempty = [b for b in blocks if b.num_rows > 0]
    if not nonempty:
        for b in blocks:           # all empty: keep a schema if any block
            if b.column_names:     # has one (joins/aggregates need it)
                return b.slice(0, 0)
        return pa.table({})
    return pa.concat_tables(nonempty, promote_options="default")


def split_block(block: Block, num_splits: int) -> List[Block]:
    n = block.num_rows
    out = []
    for i in range(num_splits):
        lo = i * n // num_splits
        hi = (i + 1) * n // num_splits
        out.append(block.slice(lo, hi - lo))
    return out
