"""Actor-based streaming hash shuffle.

Reference capability: `python/ray/data/_internal/execution/operators/
hash_shuffle.py:339` — stateful aggregator actors receive partition
shards AS THEY STREAM from the map side and finalize each partition,
instead of a barrier reduce task that takes every map's output as one
call's arguments.

Shape here: ``n_aggregators`` actors each own ``n_out / n_aggregators``
partitions. Every upstream block runs one partition task; each of its
``n_out`` shards is immediately forwarded to the owning aggregator
(``add_shard``), so accumulation overlaps with the remaining partition
work and no task ever materializes O(num_blocks) arguments. Actor calls
execute in submission order, so a ``finalize`` submitted after all
``add_shard`` calls sees the complete partition. Aggregators are killed
once every finalized partition has materialized.

All shuffle-family operators ride this path: repartition, random
shuffle, sort (after the sampling pass picks range bounds), hash
aggregate, and hash join (two tagged input sides into the same
aggregators).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Sequence, Tuple


class ShuffleAggregator:
    """Stateful reducer actor: accumulates shards per (partition, tag)
    and finalizes one partition at a time."""

    def __init__(self):
        self._shards: Dict[Tuple[int, str], List[Any]] = {}
        self._rows_in = 0

    def add_shard(self, part: int, tag: str, shard) -> int:
        self._shards.setdefault((part, tag), []).append(shard)
        self._rows_in += shard.num_rows
        return shard.num_rows

    def finalize(self, part: int, fin: Callable, args: tuple, *deps):
        """Reduce everything received for ``part``. ``fin`` gets a
        {tag: [blocks]} dict (tags matter only for joins). ``deps`` are
        the partition's add_shard results — passing them as ARGUMENTS
        makes the dataflow explicit, so finalize cannot run until every
        shard for this partition has been delivered (actor submission
        order alone does not gate on calls whose args are in flight)."""
        mine = {tag: blocks
                for (p, tag), blocks in self._shards.items() if p == part}
        for tag in mine:
            del self._shards[(part, tag)]
        return fin(mine, *args)

    def stats(self) -> Dict[str, int]:
        return {"rows_in": self._rows_in,
                "pending_partitions": len(self._shards)}


def run_streaming_shuffle(
        sides: Sequence[Tuple[str, Sequence[Any], Callable, tuple]],
        n_out: int,
        finalize_fn: Callable,
        finalize_args: Callable[[int], tuple],
        num_aggregators: int = 8) -> List[Any]:
    """Drive a full streaming shuffle.

    sides: [(tag, block_refs, partition_task_fn, partition_args)] —
        one entry for most operators, two for joins. The partition task
        is called as ``fn(block, *partition_args)`` and must return
        ``n_out`` blocks (or one when n_out == 1).
    finalize_fn(shards_by_tag, *finalize_args(p)) -> Block.
    Returns one output ref per partition, in partition order.
    """
    import ray_tpu

    n_agg = max(1, min(num_aggregators, n_out))
    agg_cls = ray_tpu.remote(ShuffleAggregator)
    actors = [agg_cls.remote() for _ in range(n_agg)]

    def owner(p: int):
        return actors[p % n_agg]

    adds: List[List[Any]] = [[] for _ in range(n_out)]
    for tag, refs, ptask, pargs in sides:
        remote_p = ray_tpu.remote(ptask)
        for r in refs:
            parts = remote_p.options(num_returns=n_out).remote(r, *pargs)
            if not isinstance(parts, list):
                parts = [parts]
            for p, shard in enumerate(parts):
                adds[p].append(owner(p).add_shard.remote(p, tag, shard))
    outs = [owner(p).finalize.remote(p, finalize_fn, finalize_args(p),
                                     *adds[p])
            for p in range(n_out)]
    _kill_when_done(actors, list(outs))
    return outs


def _kill_when_done(actors: List[Any], outs: List[Any]) -> None:
    """Reap the per-shuffle aggregator actors once every finalized
    partition block has materialized (results live in the object store
    independently of the actor)."""
    import ray_tpu

    def reap():
        # kill ONLY once every output has actually materialized — a
        # shuffle slower than any fixed timeout must never lose its
        # aggregators mid-computation. (Daemon thread: abandoned runs
        # die with the process.)
        pending = list(outs)
        while pending:
            try:
                done, pending = ray_tpu.wait(
                    pending, num_returns=len(pending), timeout=60)
            except Exception:
                return   # runtime shut down: actors are gone anyway
        for a in actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass

    threading.Thread(target=reap, daemon=True,
                     name="shuffle-aggregator-reaper").start()
