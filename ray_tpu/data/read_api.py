"""Dataset creation (reference: `python/ray/data/read_api.py` — 41
datasources; here: range/items/numpy/pandas/arrow + parquet/csv/json/text/
binary files, each file a parallel read task)."""

from __future__ import annotations

import glob as _glob
import os
from typing import Any, Callable, Dict, List, Optional

import numpy as np
import pyarrow as pa

import ray_tpu
from ray_tpu.data import logical as L
from ray_tpu.data.block import block_from_batch, block_from_rows
from ray_tpu.data.dataset import Dataset

DEFAULT_BLOCK_ROWS = 1000


def _from_blocks(blocks: List[pa.Table]) -> Dataset:
    refs = [ray_tpu.put(b) for b in blocks]
    return Dataset(L.InputData("input", [], block_refs=refs))


def range(n: int, *, parallelism: int = -1) -> Dataset:  # noqa: A001
    import builtins
    if parallelism <= 0:
        parallelism = max(1, min(64, n // DEFAULT_BLOCK_ROWS or 1))
    tasks = []
    for i in builtins.range(parallelism):
        lo = i * n // parallelism
        hi = (i + 1) * n // parallelism
        tasks.append(lambda lo=lo, hi=hi: pa.table(
            {"id": pa.array(np.arange(lo, hi))}))
    return Dataset(L.Read("read_range", [], read_tasks=tasks))


def from_items(items: List[Any], *, parallelism: int = 4) -> Dataset:
    import builtins
    if not items:
        return _from_blocks([pa.table({})])
    rows = [it if isinstance(it, dict) else {"item": it} for it in items]
    n = len(rows)
    parallelism = max(1, min(parallelism, n))
    blocks = []
    for i in builtins.range(parallelism):
        lo, hi = i * n // parallelism, (i + 1) * n // parallelism
        blocks.append(block_from_rows(rows[lo:hi]))
    return _from_blocks(blocks)


def from_numpy(arr: np.ndarray, column: str = "data") -> Dataset:
    return _from_blocks([block_from_batch({column: arr})])


def from_pandas(df) -> Dataset:
    return _from_blocks([pa.Table.from_pandas(df, preserve_index=False)])


def from_arrow(table: pa.Table) -> Dataset:
    return _from_blocks([table])


def _expand_paths(paths, suffix: str) -> List[str]:
    from ray_tpu.data.filesystem import expand_paths
    return expand_paths(paths, suffix)


def _file_read_dataset(paths, suffix: str, reader: Callable,
                       name: str) -> Dataset:
    from ray_tpu.data import filesystem as fsmod

    files = _expand_paths(paths, suffix)
    # Read tasks execute in worker processes: ship the driver's
    # registered filesystems with the task so s3://-style schemes
    # resolve there too (reference: the fs object travels with the
    # read task, not via global state).
    registry = dict(fsmod._REGISTRY)

    def run(f):
        for scheme, fs in registry.items():
            # Overwrite, never setdefault: pooled workers OUTLIVE a
            # driver-side re-registration (e.g. a new S3 endpoint), and a
            # stale entry would shadow the one this task shipped with.
            fsmod._REGISTRY[scheme] = fs
        return reader(f)

    tasks = [lambda f=f: run(f) for f in files]
    return Dataset(L.Read(name, [], read_tasks=tasks))


def _seam_open(f):
    """Open one (possibly scheme-qualified) path through the filesystem
    seam so every reader works on any registered fs (s3://, ...)."""
    from ray_tpu.data.filesystem import resolve_filesystem
    fs, local = resolve_filesystem(f)
    return fs.open_input(local)


def read_parquet(paths) -> Dataset:
    import pyarrow.parquet as pq
    return _file_read_dataset(paths, ".parquet",
                              lambda f: pq.read_table(_seam_open(f)),
                              "read_parquet")


def read_csv(paths) -> Dataset:
    import pyarrow.csv as pacsv
    return _file_read_dataset(paths, ".csv",
                              lambda f: pacsv.read_csv(_seam_open(f)),
                              "read_csv")


def read_json(paths) -> Dataset:
    import pyarrow.json as pajson
    return _file_read_dataset(paths, ".json",
                              lambda f: pajson.read_json(_seam_open(f)),
                              "read_json")


def read_text(paths) -> Dataset:
    def reader(f):
        with _seam_open(f) as fh:
            text = fh.read().decode()
        return block_from_rows(
            [{"text": line} for line in text.splitlines()])
    return _file_read_dataset(paths, ".txt", reader, "read_text")


def read_binary_files(paths) -> Dataset:
    def reader(f):
        from ray_tpu.data.filesystem import resolve_filesystem
        fs, local = resolve_filesystem(f)
        with fs.open_input(local) as fh:
            return block_from_rows([{"bytes": fh.read(), "path": f}])
    return _file_read_dataset(paths, "", reader, "read_binary_files")


_IMAGE_SUFFIXES = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp")


def read_images(paths, *, size=None, mode: str = "RGB") -> Dataset:
    """Decode image files into an ``image`` tensor column (HWC uint8),
    optionally resizing to ``size=(h, w)`` (reference:
    ``data/read_api.py read_images`` / image datasource)."""
    def reader(f):
        import numpy as _np
        from PIL import Image

        from ray_tpu.data.filesystem import resolve_filesystem
        fs, local = resolve_filesystem(f)
        with fs.open_input(local) as fh:
            img = Image.open(fh)
            img.load()
        if mode:
            img = img.convert(mode)
        if size is not None:
            img = img.resize((size[1], size[0]))
        arr = _np.ascontiguousarray(img)
        return block_from_batch(
            {"image": _np.ascontiguousarray(arr[None, ...]),
             "path": _np.asarray([f])})

    def _is_file(f):
        from ray_tpu.data.filesystem import resolve_filesystem
        fs, local = resolve_filesystem(f)
        return fs.exists(local) and not fs.isdir(local)

    files = [f for f in _expand_paths(paths, "")
             if f.lower().endswith(_IMAGE_SUFFIXES) and _is_file(f)]
    tasks = [lambda f=f: reader(f) for f in files]
    return Dataset(L.Read("read_images", [], read_tasks=tasks))


def read_numpy(paths, column: str = "data") -> Dataset:
    """One block per .npy file."""
    def reader(f):
        from ray_tpu.data.filesystem import resolve_filesystem
        fs, local = resolve_filesystem(f)
        with fs.open_input(local) as fh:
            arr = np.load(fh)
        return block_from_batch({column: arr})
    return _file_read_dataset(paths, ".npy", reader, "read_numpy")


def read_sql(sql: str, connection_factory, *,
             parallelism: int = 1) -> Dataset:
    """Rows of a SQL query as a dataset (reference: `data/read_api.py`
    read_sql / SQLDatasource). ``connection_factory`` returns a DB-API
    connection (e.g. ``lambda: sqlite3.connect(path)``) — it is called
    INSIDE each read task, so the dataset ships the factory, never a
    live connection. ``parallelism > 1`` pages the result set with
    ORDER BY 1 + LIMIT/OFFSET across independent query executions: the
    query's FIRST column must be a stable (ideally unique) key or rows
    may repeat/drop across pages."""
    def run_query(q: str):
        conn = connection_factory()
        try:
            cur = conn.cursor()
            cur.execute(q)
            cols = [d[0] for d in cur.description]
            rows = [dict(zip(cols, r)) for r in cur.fetchall()]
            return block_from_rows(rows)
        finally:
            conn.close()

    def read_page(p: int, n: int):
        # each task counts then reads its page: the count is redundant
        # across tasks, but the DRIVER never touches the database — a
        # DB reachable only from workers (private subnet, worker-held
        # credentials) still works, and the count subquery is cheap
        # next to the page pull
        conn = connection_factory()
        try:
            cur = conn.cursor()
            cur.execute(f"SELECT COUNT(*) FROM ({sql}) AS __sub")
            total = cur.fetchone()[0]
        finally:
            conn.close()
        per = max(1, (total + n - 1) // n)
        return run_query(
            f"SELECT * FROM ({sql}) AS __sub ORDER BY 1 "
            f"LIMIT {per} OFFSET {p * per}")

    import builtins
    n = max(1, parallelism)
    if n == 1:
        tasks = [lambda: run_query(sql)]
    else:
        tasks = [lambda p=p: read_page(p, n) for p in builtins.range(n)]
    return Dataset(L.Read("read_sql", [], read_tasks=tasks))


def read_tfrecords(paths) -> Dataset:
    """TFRecord shards of tf.train.Example protos, WITHOUT TensorFlow
    (reference: `data/read_api.py` read_tfrecords imports TF; this
    image has none — `data/tfrecords.py` speaks the framing + proto
    wire format directly, crc-checked)."""
    from ray_tpu.data.tfrecords import (decode_example,
                                        read_tfrecord_frames)

    def reader(f):
        with _seam_open(f) as fh:
            blob = fh.read()
        rows = [decode_example(p) for p in read_tfrecord_frames(blob)]
        # features stay LISTS (proto semantics): any unwrap heuristic
        # is per-file and would disagree across shards of one dataset
        all_cols = {c for r in rows for c in r}
        for r in rows:
            for c in all_cols:
                r.setdefault(c, None)
        return block_from_rows(rows)

    return _file_read_dataset(paths, ".tfrecord", reader,
                              "read_tfrecords")


def read_webdataset(paths) -> Dataset:
    """WebDataset tar shards: files grouped by basename stem into one
    row per sample, keyed by extension (reference: `data/read_api.py`
    read_webdataset). E.g. ``000.jpg`` + ``000.cls`` -> one row
    ``{"__key__": "000", "jpg": b..., "cls": b...}``."""
    import io
    import tarfile

    def reader(f):
        with _seam_open(f) as fh:
            data = fh.read()
        samples: dict = {}
        order: list = []
        with tarfile.open(fileobj=io.BytesIO(data)) as tar:
            for member in tar.getmembers():
                if not member.isfile():
                    continue
                # WebDataset convention: key = path up to the FIRST dot
                # of the basename; everything after is the (possibly
                # multi-part) extension, e.g. 000.seg.png -> ("000",
                # "seg.png")
                prefix, _, base = member.name.rpartition("/")
                stem, _, ext = base.partition(".")
                key = f"{prefix}/{stem}" if prefix else stem
                if key not in samples:
                    samples[key] = {"__key__": key}
                    order.append(key)
                samples[key][ext] = tar.extractfile(member).read()
        rows = [samples[k] for k in order]
        # uniform column set: a sample missing an extension seen in
        # others gets None (block_from_rows keys off the first row)
        all_cols = {c for r in rows for c in r}
        for r in rows:
            for c in all_cols:
                r.setdefault(c, None)
        return block_from_rows(rows)

    return _file_read_dataset(paths, ".tar", reader, "read_webdataset")


def read_avro(paths) -> Dataset:
    """Avro Object Container Files (reference: `data/read_api.py`
    read_avro via fastavro; this image has no avro wheel —
    `data/avro.py` speaks the container framing + binary encoding
    directly, null/deflate codecs)."""
    from ray_tpu.data.avro import read_container

    def reader(f):
        with _seam_open(f) as fh:
            blob = fh.read()
        _, records = read_container(blob)
        rows = [r if isinstance(r, dict) else {"value": r}
                for r in records]
        all_cols = {c for r in rows for c in r}
        for r in rows:
            for c in all_cols:
                r.setdefault(c, None)
        return block_from_rows(rows)

    return _file_read_dataset(paths, ".avro", reader, "read_avro")


def read_delta(path: str, *, version: Optional[int] = None) -> Dataset:
    """Delta Lake table (reference: `data/read_api.py` read_delta via
    deltalake; that wheel is absent, so this speaks the open Delta
    transaction-log protocol directly): replay `_delta_log/*.json`
    commits (add/remove actions) up to ``version``, then read the
    surviving parquet data files in parallel. Checkpoint parquet files
    are also honored as the replay base when present."""
    import json as _json

    from ray_tpu.data.filesystem import resolve_filesystem
    fs, local = resolve_filesystem(path)
    log_dir = f"{local.rstrip('/')}/_delta_log"

    entries = sorted(
        p for p in fs.listdir(log_dir)
        if p.endswith(".json")
        and p.rsplit("/", 1)[-1].split(".")[0].isdigit())
    live: Dict[str, bool] = {}
    base_version = -1
    # checkpoint base: the newest usable checkpoint VERSION — reading
    # EVERY part of it (the spec allows multi-part checkpoints,
    # N.checkpoint.<part>.<parts>.parquet; one part alone silently
    # drops files)
    by_version: Dict[int, List[str]] = {}
    for p in fs.listdir(log_dir):
        name = p.rsplit("/", 1)[-1]
        if ".checkpoint." in name and name.endswith(".parquet"):
            head = name.split(".")[0]
            if head.isdigit():
                by_version.setdefault(int(head), []).append(p)
    usable = [v for v in by_version
              if version is None or v <= version]
    if usable:
        import pyarrow.parquet as pq
        ck_version = max(usable)
        for part in sorted(by_version[ck_version]):
            with fs.open_input(part) as f:
                table = pq.read_table(f)
            for row in table.to_pylist():
                add = row.get("add")
                if add and add.get("path"):
                    live[add["path"]] = True
                rm = row.get("remove")
                if rm and rm.get("path"):
                    live.pop(rm["path"], None)
        base_version = ck_version
    for entry in entries:
        v = int(entry.rsplit("/", 1)[-1].split(".")[0])
        if v <= base_version or (version is not None and v > version):
            continue
        with fs.open_input(entry) as f:
            for line in f.read().decode().splitlines():
                if not line.strip():
                    continue
                action = _json.loads(line)
                if "add" in action:
                    live[action["add"]["path"]] = True
                elif "remove" in action:
                    live.pop(action["remove"]["path"], None)

    scheme = path.split("://", 1)[0] + "://" if "://" in path else ""
    files = [f"{scheme}{local.rstrip('/')}/{rel}"
             for rel in sorted(live)]
    if not files:
        return _from_blocks([pa.table({})])

    def reader(f):
        import pyarrow.parquet as pq
        with _seam_open(f) as fh:
            return pq.read_table(fh)

    return _file_read_dataset(files, ".parquet", reader, "read_delta")


def read_orc(paths) -> Dataset:
    """Apache ORC files (reference: `data/read_api.py` read_orc via
    pyarrow.orc — available in this image's pyarrow)."""
    def reader(f):
        import io as _io

        from pyarrow import orc as _orc
        with _seam_open(f) as fh:
            data = fh.read()
        return _orc.ORCFile(_io.BytesIO(data)).read()

    return _file_read_dataset(paths, ".orc", reader, "read_orc")


def from_torch(torch_dataset, *, parallelism: int = 4) -> Dataset:
    """Materialize a torch.utils.data.Dataset (map-style or iterable)
    into blocks (reference: `data/read_api.py` from_torch; torch is CPU
    -only in this image, which is exactly the ingest role)."""
    try:
        n = len(torch_dataset)
        items = [torch_dataset[i] for i in builtins_range(n)]
    except TypeError:
        items = list(torch_dataset)     # iterable-style

    def to_row(item):
        import numpy as _np
        try:
            import torch as _torch
            is_tensor = isinstance(item, _torch.Tensor)
        except ImportError:
            is_tensor = False
        if is_tensor:
            return {"item": _np.asarray(item)}
        if isinstance(item, dict):
            return item
        if isinstance(item, (tuple, list)):
            return {f"field_{i}": (_np.asarray(v)
                                   if hasattr(v, "numpy") else v)
                    for i, v in enumerate(item)}
        return {"item": item}

    return from_items([to_row(it) for it in items],
                      parallelism=parallelism)


import builtins as _builtins

builtins_range = _builtins.range
