"""Logical plan + rule-based optimizer.

Reference: `python/ray/data/_internal/logical/` — lazy Dataset builds a
LogicalPlan DAG; rules (notably operator fusion) rewrite it before the
planner produces physical operators (`planner/planner.py:171`,
`logical/optimizers.py`).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple


@dataclasses.dataclass
class LogicalOp:
    name: str
    inputs: List["LogicalOp"]

    def chain(self) -> List["LogicalOp"]:
        """Linear chains only (union/zip handled by the planner)."""
        out: List[LogicalOp] = []
        node: Optional[LogicalOp] = self
        while node is not None:
            out.append(node)
            node = node.inputs[0] if node.inputs else None
        return list(reversed(out))


@dataclasses.dataclass
class InputData(LogicalOp):
    """Materialized input block refs."""
    block_refs: List[Any] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class Read(LogicalOp):
    """Datasource read: list of zero-arg task fns, each producing a block."""
    read_tasks: List[Callable] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class MapBatches(LogicalOp):
    fn: Callable = None
    batch_format: str = "numpy"
    fn_constructor: Optional[Callable] = None   # actor-pool (stateful) map
    concurrency: Optional[Tuple[int, int]] = None
    batch_size: Optional[int] = None


@dataclasses.dataclass
class MapRows(LogicalOp):
    fn: Callable = None
    kind: str = "map"          # map | filter | flat_map


@dataclasses.dataclass
class AllToAll(LogicalOp):
    kind: str = "repartition"  # repartition | shuffle | sort
    num_outputs: Optional[int] = None
    key: Optional[str] = None
    descending: bool = False
    seed: Optional[int] = None


@dataclasses.dataclass
class Limit(LogicalOp):
    limit: int = 0


@dataclasses.dataclass
class Union(LogicalOp):
    pass


@dataclasses.dataclass
class Zip(LogicalOp):
    pass


@dataclasses.dataclass
class Join(LogicalOp):
    key: Optional[str] = None
    how: str = "inner"
    num_partitions: Optional[int] = None


@dataclasses.dataclass
class Aggregate(LogicalOp):
    key: Optional[str] = None
    aggs: List[Any] = dataclasses.field(default_factory=list)
    map_groups_fn: Optional[Callable] = None
    batch_format: str = "numpy"


# ---------------------------------------------------------------------------
# Optimizer
# ---------------------------------------------------------------------------

def optimize(root: LogicalOp) -> LogicalOp:
    """Apply rewrite rules bottom-up. Today: row-op → batch-op lowering is
    done in the planner; the key rule here is map fusion (reference
    `logical/rules/operator_fusion.py`): adjacent stateless maps execute as
    one task, halving object-store traffic."""
    root = _fuse_maps(root)
    return root


def _fusable(op: LogicalOp) -> bool:
    return (isinstance(op, (MapRows,))
            or (isinstance(op, MapBatches) and op.fn_constructor is None))


def _fuse_maps(op: LogicalOp) -> LogicalOp:
    if op.inputs:
        op.inputs = [_fuse_maps(i) for i in op.inputs]
    child = op.inputs[0] if op.inputs else None
    if child is not None and _fusable(op) and _fusable(child):
        fused = FusedMap(
            name=f"{child.name}->{op.name}", inputs=child.inputs,
            stages=(_stages(child) + _stages(op)))
        return fused
    return op


@dataclasses.dataclass
class FusedMap(LogicalOp):
    stages: List[LogicalOp] = dataclasses.field(default_factory=list)


def _stages(op: LogicalOp) -> List[LogicalOp]:
    return list(op.stages) if isinstance(op, FusedMap) else [op]
