"""Streaming execution: physical operators over ray_tpu tasks/actors.

Reference: `data/_internal/execution/streaming_executor.py:52,99,271,325`
(scheduling loop, `select_operator_to_run` hot loop
`streaming_executor_state.py:643`, backpressure policies, actor-pool map
operator). The shape is preserved — pull-based streaming topology with
per-operator in-flight caps and bounded output queues — on ray_tpu tasks;
all-to-all ops (shuffle/sort/repartition/aggregate) are materialization
barriers exactly as in the reference.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data.block import (Block, BlockAccessor, block_from_batch,
                                block_from_rows, concat_blocks, split_block)
from ray_tpu.data import logical as L

DEFAULT_MAX_IN_FLIGHT = 8       # concurrent tasks per operator
DEFAULT_MAX_OUT_QUEUE = 16      # blocks buffered between operators


# ---------------------------------------------------------------------------
# Block transform payloads (run inside remote tasks; must be picklable)
# ---------------------------------------------------------------------------

def _apply_stage(block: Block, stage) -> Block:
    acc = BlockAccessor(block)
    if isinstance(stage, L.MapBatches):
        batch = acc.to_batch(stage.batch_format)
        out = stage.fn(batch)
        return block_from_batch(out)
    if isinstance(stage, L.MapRows):
        rows = acc.to_rows()
        if stage.kind == "map":
            return block_from_rows([stage.fn(r) for r in rows])
        if stage.kind == "filter":
            return block_from_rows([r for r in rows if stage.fn(r)])
        if stage.kind == "flat_map":
            return block_from_rows(
                [o for r in rows for o in stage.fn(r)])
    raise TypeError(f"unknown stage {stage!r}")


def _map_block_task(block: Block, stages) -> Block:
    for stage in stages:
        block = _apply_stage(block, stage)
    return block


def _read_task(read_fn: Callable) -> Block:
    return read_fn()


class _MapWorker:
    """Actor for stateful (fn_constructor) map_batches."""

    def __init__(self, ctor, batch_format: str):
        self.fn = ctor()
        self.batch_format = batch_format

    def apply(self, block: Block) -> Block:
        batch = BlockAccessor(block).to_batch(self.batch_format)
        return block_from_batch(self.fn(batch))


# ---------------------------------------------------------------------------
# Physical operators
# ---------------------------------------------------------------------------

class PhysicalOperator:
    """One stage of the streaming topology."""

    def __init__(self, name: str, max_in_flight: int = DEFAULT_MAX_IN_FLIGHT):
        self.name = name
        self.inqueue: collections.deque = collections.deque()
        self.outqueue: collections.deque = collections.deque()
        self.active: Dict[Any, bool] = {}   # ref -> True
        self.max_in_flight = max_in_flight
        self.inputs_done = False
        self.downstream: Optional[PhysicalOperator] = None

    # -- scheduling hooks --
    def can_launch(self, max_out: int) -> bool:
        return (bool(self.inqueue) and len(self.active) < self.max_in_flight
                and len(self.outqueue) + len(self.active) < max_out)

    def launch(self) -> None:
        raise NotImplementedError

    def on_task_done(self, ref, error: Optional[Exception],
                     value: Any = None) -> None:
        self.active.pop(ref, None)
        if error is not None:
            raise error
        self.outqueue.append(ref)

    def maybe_autoscale(self) -> None:
        """Hook: operators with elastic resources resize here per tick."""

    def done(self) -> bool:
        return (self.inputs_done and not self.inqueue and not self.active)

    def shutdown(self) -> None:
        pass


class SourceOperator(PhysicalOperator):
    """Feeds read tasks / pre-materialized refs."""

    def __init__(self, name: str, read_fns: List[Callable] = None,
                 refs: List[Any] = None, owner=None):
        super().__init__(name)
        self._read_fns = list(read_fns or [])
        self._refs = list(refs or [])
        self.inqueue.extend(range(len(self._read_fns)) if self._read_fns
                            else [])
        if not self._read_fns:
            self.outqueue.extend(self._refs)
        self.inputs_done = True
        self._task = ray_tpu.remote(_read_task)

    def can_launch(self, max_out: int) -> bool:
        return (bool(self.inqueue) and len(self.active) < self.max_in_flight
                and len(self.outqueue) + len(self.active) < max_out)

    def launch(self) -> None:
        idx = self.inqueue.popleft()
        ref = self._task.remote(self._read_fns[idx])
        self.active[ref] = True


class MapOperator(PhysicalOperator):
    def __init__(self, name: str, stages: List[L.LogicalOp]):
        super().__init__(name)
        self.stages = stages
        self._task = ray_tpu.remote(_map_block_task)

    def launch(self) -> None:
        block_ref = self.inqueue.popleft()
        ref = self._task.remote(block_ref, self.stages)
        self.active[ref] = True


class ActorPoolMapOperator(PhysicalOperator):
    """Stateful map over an ELASTIC pool of actors (reference:
    `execution/operators/actor_pool_map_operator.py` + per-op actor-pool
    autoscaling): concurrency=(min, max) or n. The pool grows while the
    input queue outruns the workers and shrinks (idle kill) when input
    dries up — per-operator dynamic sizing, not a static cap."""

    _IDLE_TICKS_BEFORE_SHRINK = 40

    def __init__(self, name: str, op: L.MapBatches):
        if op.concurrency:
            self.min_size, self.max_size = op.concurrency
        else:
            self.min_size, self.max_size = 2, 2
        super().__init__(name, max_in_flight=self.max_size)
        self._op = op
        self._worker_cls = ray_tpu.remote(_MapWorker)
        self.workers = [self._make_worker()
                        for _ in range(self.min_size)]
        self._next = 0
        self._idle_ticks = 0

    def _make_worker(self):
        return self._worker_cls.remote(self._op.fn_constructor,
                                       self._op.batch_format)

    def launch(self) -> None:
        block_ref = self.inqueue.popleft()
        w = self._next % len(self.workers)
        self._next += 1
        worker = self.workers[w]
        ref = worker.apply.remote(block_ref)
        self.active[ref] = worker   # ref -> owning worker (shrink safety)

    def can_launch(self, max_out: int) -> bool:
        return (bool(self.inqueue)
                and len(self.active) < len(self.workers)
                and len(self.outqueue) + len(self.active) < max_out)

    def maybe_autoscale(self) -> None:
        backlog = len(self.inqueue)
        busy = len(self.active)
        if (backlog > 0 and busy == len(self.workers)
                and len(self.workers) < self.max_size):
            # the POOL is the binding constraint (all workers busy and
            # work queuing — not a downstream-backpressure veto): grow
            self.workers.append(self._make_worker())
            self._idle_ticks = 0
            return
        if backlog == 0 and busy < len(self.workers):
            self._idle_ticks += 1
            if (self._idle_ticks >= self._IDLE_TICKS_BEFORE_SHRINK
                    and len(self.workers) > self.min_size):
                # only shrink a worker with NO in-flight task
                busy_workers = set(id(w) for w in self.active.values())
                for i in range(len(self.workers) - 1, -1, -1):
                    if id(self.workers[i]) not in busy_workers:
                        victim = self.workers.pop(i)
                        self._idle_ticks = 0
                        try:
                            ray_tpu.kill(victim)
                        except Exception:
                            pass
                        break
        else:
            self._idle_ticks = 0

    def shutdown(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass


def _limit_slice_task(block: Block, remaining: int):
    n = block.num_rows
    taken = min(n, remaining)
    out = block if taken == n else block.slice(0, taken)
    return out, taken


class LimitOperator(PhysicalOperator):
    """Streaming limit WITHOUT blocking the scheduling loop: each block
    is sliced by a remote task (num_returns=2: block + rows-taken); the
    loop learns the consumed count from the tiny inline second return.
    Sequential (max_in_flight=1) so the budget is exact."""

    def __init__(self, limit: int):
        super().__init__(f"limit={limit}", max_in_flight=1)
        self.remaining = limit
        self._slice = ray_tpu.remote(_limit_slice_task).options(
            num_returns=2)
        self._taken_refs: Dict[Any, Any] = {}

    def can_launch(self, max_out: int) -> bool:
        return (bool(self.inqueue) and not self.active
                and self.remaining > 0)

    def launch(self) -> None:
        ref = self.inqueue.popleft()
        block_ref, taken_ref = self._slice.remote(ref, self.remaining)
        self.active[block_ref] = True
        self._taken_refs[block_ref] = taken_ref

    def on_task_done(self, ref, error: Optional[Exception],
                     value: Any = None) -> None:
        self.active.pop(ref, None)
        taken_ref = self._taken_refs.pop(ref, None)
        if error is not None:
            raise error
        if taken_ref is not None:
            self.remaining -= int(ray_tpu.get(taken_ref))
        self.outqueue.append(ref)

    def done(self) -> bool:
        return super().done() or (self.remaining <= 0
                                  and not self.active)


# ---------------------------------------------------------------------------
# All-to-all barriers (materializing)
# ---------------------------------------------------------------------------

def _split_task(block: Block, n: int):
    out = split_block(block, n)
    return out if n > 1 else out[0]


def _sort_block_task(block: Block, key: str, descending: bool) -> Block:
    return block.sort_by([(key, "descending" if descending
                           else "ascending")])


def _range_partition_task(block: Block, key: str, bounds: List,
                          descending: bool) -> List[Block]:
    col = block.column(key).to_numpy(zero_copy_only=False)
    idx = np.searchsorted(np.asarray(bounds), col, side="right")
    out = [block.take(np.nonzero(idx == p)[0])
           for p in range(len(bounds) + 1)]
    return out if len(out) > 1 else out[0]


def _stable_hash(x) -> int:
    """Deterministic across interpreters/hosts (builtin hash() is salted
    by PYTHONHASHSEED for str/bytes — two join sides running in different
    worker processes would partition differently and drop matches).

    Preserves builtin hash()'s equality invariant for keys that compare
    equal across numeric types: True == 1 == 1.0 and -0.0 == 0.0 must all
    land in the same partition."""
    import math
    import zlib
    if isinstance(x, bytes):
        b = x
    elif isinstance(x, str):
        b = x.encode()
    elif isinstance(x, (bool, np.bool_, int, np.integer)) or (
            isinstance(x, (float, np.floating)) and math.isfinite(x)
            and float(x).is_integer() and abs(x) < 2**63):
        # one canonical encoding for all integral numerics (incl. -0.0)
        b = int(x).to_bytes(16, "little", signed=True)
    elif isinstance(x, (float, np.floating)):
        b = np.float64(x).tobytes()
    else:
        b = repr(x).encode()
    return zlib.crc32(b)


def _hash_partition_task(block: Block, key: str, n: int) -> List[Block]:
    col = block.column(key).to_numpy(zero_copy_only=False)
    h = np.asarray([_stable_hash(x) % n for x in col], np.int64)
    out = [block.take(np.nonzero(h == p)[0]) for p in range(n)]
    return out if n > 1 else out[0]


def _perm_partition_task(block: Block, n: int, seed) -> List[Block]:
    rng = np.random.default_rng(seed)
    idx = rng.integers(0, n, block.num_rows)
    out = [block.take(np.nonzero(idx == p)[0]) for p in range(n)]
    return out if n > 1 else out[0]


def _shuffle_reduce_task(seed, part_idx, *blocks: Block) -> Block:
    block = concat_blocks(blocks)
    rng = np.random.default_rng(None if seed is None else seed + part_idx)
    return block.take(rng.permutation(block.num_rows))


def _fin_concat(shards: Dict) -> Block:
    return concat_blocks(shards.get("d", []))


def _fin_shuffle(shards: Dict, seed, part_idx: int) -> Block:
    return _shuffle_reduce_task(seed, part_idx, *shards.get("d", []))


def _fin_sort(shards: Dict, key: str, descending: bool) -> Block:
    return _sort_block_task(concat_blocks(shards.get("d", [])),
                            key, descending)


def run_all_to_all(op: L.AllToAll, block_refs: List[Any]) -> List[Any]:
    """Execute an all-to-all over already-computed blocks: partition
    shards stream into stateful aggregator actors as they land
    (data/hash_shuffle.py), no barrier reduce with O(blocks) args."""
    from ray_tpu.data.hash_shuffle import run_streaming_shuffle
    if not block_refs:
        return []
    n_out = op.num_outputs or len(block_refs)
    n_out = max(1, n_out)

    if op.kind == "repartition":
        return run_streaming_shuffle(
            [("d", block_refs, _split_task, (n_out,))], n_out,
            _fin_concat, lambda p: ())

    if op.kind == "shuffle":
        return run_streaming_shuffle(
            [("d", block_refs, _perm_partition_task, (n_out, op.seed))],
            n_out, _fin_shuffle, lambda p: (op.seed, p))

    if op.kind == "sort":
        # Sample → pick boundaries → stream range partitions into
        # per-range aggregators that sort on finalize.
        blocks = ray_tpu.get(list(block_refs))
        col = np.concatenate([
            b.column(op.key).to_numpy(zero_copy_only=False)
            for b in blocks if b.num_rows > 0])
        if col.size == 0:
            return block_refs
        quantiles = np.linspace(0, 1, n_out + 1)[1:-1]
        bounds = list(np.quantile(col, quantiles, method="nearest"))
        nparts = len(bounds) + 1
        out = run_streaming_shuffle(
            [("d", block_refs, _range_partition_task,
              (op.key, bounds, op.descending))], nparts,
            _fin_sort, lambda p: (op.key, op.descending))
        return out[::-1] if op.descending else out

    raise ValueError(f"unknown all-to-all kind {op.kind!r}")


def _agg_partition_task(key, aggs, map_groups_fn, batch_format,
                        *blocks: Block) -> Block:
    """Reduce one hash partition: group rows by key, apply aggs/fn."""
    block = concat_blocks(blocks)
    if block.num_rows == 0:
        return block
    if key is None:
        groups = {None: block}
    else:
        col = block.column(key).to_numpy(zero_copy_only=False)
        groups = {}
        for val in np.unique(col):
            groups[val] = block.take(np.nonzero(col == val)[0])
    rows = []
    for val, sub in sorted(groups.items(),
                           key=lambda kv: (kv[0] is None, kv[0])):
        if map_groups_fn is not None:
            out = map_groups_fn(
                BlockAccessor(sub).to_batch(batch_format))
            rows.extend(block_from_batch(out).to_pylist())
            continue
        row = {} if key is None else {key: val}
        for agg in aggs:
            row[agg.name] = agg.compute(sub)
        rows.append(row)
    return block_from_rows(rows)


def _join_partition_task(key: str, how: str, n_left: int,
                         *blocks: Block) -> Block:
    """Join one hash partition: first n_left blocks are the left side."""
    left = concat_blocks(blocks[:n_left])
    right = concat_blocks(blocks[n_left:])
    if left.num_rows == 0 and right.num_rows == 0:
        return left
    if left.num_rows == 0:
        left = left.cast(left.schema)
    return left.join(right, keys=key, join_type=how,
                     right_suffix="_r")


def _fin_join(shards: Dict, key: str, how: str) -> Block:
    left = shards.get("l", [])
    right = shards.get("r", [])
    return _join_partition_task(key, how, len(left), *left, *right)


def _fin_agg(shards: Dict, key, aggs, map_groups_fn,
             batch_format) -> Block:
    return _agg_partition_task(key, aggs, map_groups_fn, batch_format,
                               *shards.get("d", []))


def run_join(key: str, how: str, left_refs: List[Any],
             right_refs: List[Any],
             num_partitions: Optional[int] = None) -> List[Any]:
    """Streaming hash join (reference: `data/_internal/execution/
    operators/join.py` — both sides hash-partition into the SAME
    aggregator actors, tagged, each partition joined on finalize)."""
    from ray_tpu.data.hash_shuffle import run_streaming_shuffle
    nparts = num_partitions or max(1, min(
        8, max(len(left_refs), len(right_refs))))
    return run_streaming_shuffle(
        [("l", left_refs, _hash_partition_task, (key, nparts)),
         ("r", right_refs, _hash_partition_task, (key, nparts))],
        nparts, _fin_join, lambda p: (key, how))


def run_aggregate(op: L.Aggregate, block_refs: List[Any],
                  num_partitions: Optional[int] = None) -> List[Any]:
    """Streaming hash-shuffle aggregation (reference: SURVEY.md §8.7 —
    `hash_shuffle.py` partition shards stream into stateful
    aggregators that reduce on finalize)."""
    from ray_tpu.data.hash_shuffle import run_streaming_shuffle
    if not block_refs:
        return []
    if op.key is None:
        agg = ray_tpu.remote(_agg_partition_task)
        return [agg.remote(None, op.aggs, op.map_groups_fn, op.batch_format,
                           *block_refs)]
    nparts = num_partitions or min(len(block_refs), 8)
    return run_streaming_shuffle(
        [("d", block_refs, _hash_partition_task, (op.key, nparts))],
        nparts, _fin_agg,
        lambda p: (op.key, op.aggs, op.map_groups_fn, op.batch_format))


# ---------------------------------------------------------------------------
# Streaming executor
# ---------------------------------------------------------------------------

class StreamingExecutor:
    """Drives a linear operator topology; yields output block refs as they
    become available (true streaming: a downstream consumer sees block 0
    while upstream still reads block N)."""

    def __init__(self, operators: List[PhysicalOperator],
                 max_out_queue: Optional[int] = None, stats=None,
                 backpressure_policies=None):
        from ray_tpu.data.backpressure_policy import default_policies
        from ray_tpu.data.context import DataContext
        ctx = DataContext.get_current()
        self.ops = operators
        self.max_out_queue = (max_out_queue if max_out_queue is not None
                              else ctx.max_operator_output_queue)
        self.stats = stats
        self.policies = (backpressure_policies
                         if backpressure_policies is not None
                         else default_policies())
        for op in operators:
            op.max_in_flight = min(op.max_in_flight,
                                   ctx.max_in_flight_tasks_per_operator)
        for a, b in zip(operators[:-1], operators[1:]):
            a.downstream = b

    def _admit(self, op: PhysicalOperator) -> bool:
        return (op.can_launch(self.max_out_queue)
                and all(p.can_launch(op, self) for p in self.policies))

    def execute(self) -> Iterator[Any]:
        ops = self.ops
        sink = ops[-1]
        try:
            while True:
                # route outputs downstream
                for op in ops[:-1]:
                    while op.outqueue:
                        op.downstream.inqueue.append(op.outqueue.popleft())
                    if op.done():
                        op.downstream.inputs_done = True
                # yield whatever reached the sink
                while sink.outqueue:
                    yield sink.outqueue.popleft()
                if all(op.done() for op in ops):
                    break
                # launch work: prefer operators furthest downstream
                # (select_operator_to_run heuristic — drain before read)
                launched = False
                for op in reversed(ops):
                    while self._admit(op):
                        op.launch()
                        launched = True
                    op.maybe_autoscale()
                # poll in-flight tasks
                in_flight = [r for op in ops for r in op.active]
                if in_flight:
                    done, _ = ray_tpu.wait(
                        in_flight, num_returns=1, timeout=0.5)
                    for ref in done:
                        owner = next(o for o in ops if ref in o.active)
                        try:
                            value = ray_tpu.get(ref)
                            owner.on_task_done(ref, None, value=value)
                            if self.stats is not None:
                                self.stats.record(owner.name, blocks=1)
                        except Exception as e:
                            owner.active.pop(ref, None)
                            raise
                elif not launched and not any(
                        op.outqueue for op in ops[:-1]):
                    # nothing running, nothing to move: avoid spin
                    if all(op.done() for op in ops):
                        break
            while sink.outqueue:
                yield sink.outqueue.popleft()
        finally:
            for op in ops:
                op.shutdown()


def plan_chain(chain: List[L.LogicalOp]) -> List[PhysicalOperator]:
    """Lower a logical chain to physical operators."""
    phys: List[PhysicalOperator] = []
    for op in chain:
        if isinstance(op, L.InputData):
            phys.append(SourceOperator("input", refs=op.block_refs))
        elif isinstance(op, L.Read):
            phys.append(SourceOperator("read", read_fns=op.read_tasks))
        elif isinstance(op, L.FusedMap):
            phys.append(MapOperator(op.name, op.stages))
        elif isinstance(op, L.MapBatches) and op.fn_constructor is not None:
            phys.append(ActorPoolMapOperator(op.name, op))
        elif isinstance(op, (L.MapBatches, L.MapRows)):
            phys.append(MapOperator(op.name, [op]))
        elif isinstance(op, L.Limit):
            phys.append(LimitOperator(op.limit))
        else:
            raise TypeError(f"cannot stream {op!r}; requires materialization")
    return phys
