"""One filesystem seam for every datasource.

Reference capability: the reference's 41 datasources all resolve paths
through one ``pyarrow.fs``-shaped abstraction
(``python/ray/data/read_api.py`` / ``datasource/path_util.py``); readers
and writers never touch ``open()`` directly. Same seam here: a tiny
protocol (open/list/exists/makedirs) with a local implementation, scheme
dispatch (``s3://``, ``gs://`` raise an actionable error in this
zero-egress build — the seam is where a cloud impl plugs in), and glob/
directory expansion shared by all ``read_*``/``write_*`` APIs.
"""

from __future__ import annotations

import glob as _glob
import os
from typing import IO, List, Sequence, Union

Paths = Union[str, Sequence[str]]


class FileSystem:
    """Minimal filesystem protocol (pyarrow.fs-shaped)."""

    scheme = ""

    def open_input(self, path: str) -> IO[bytes]:
        raise NotImplementedError

    def open_output(self, path: str) -> IO[bytes]:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def isdir(self, path: str) -> bool:
        raise NotImplementedError

    def listdir(self, path: str) -> List[str]:
        raise NotImplementedError

    def glob(self, pattern: str) -> List[str]:
        raise NotImplementedError

    def makedirs(self, path: str) -> None:
        raise NotImplementedError


class LocalFileSystem(FileSystem):
    scheme = "file"

    def open_input(self, path: str) -> IO[bytes]:
        return open(path, "rb")

    def open_output(self, path: str) -> IO[bytes]:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        return open(path, "wb")

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def isdir(self, path: str) -> bool:
        return os.path.isdir(path)

    def listdir(self, path: str) -> List[str]:
        return sorted(os.path.join(path, f) for f in os.listdir(path))

    def glob(self, pattern: str) -> List[str]:
        return sorted(_glob.glob(pattern))

    def makedirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)


_CLOUD_SCHEMES = {
    "s3": "S3 (install/enable an S3 filesystem implementation and "
          "register it with register_filesystem('s3', fs))",
    "gs": "GCS (register_filesystem('gs', fs))",
    "gcs": "GCS (register_filesystem('gcs', fs))",
    "hdfs": "HDFS (register_filesystem('hdfs', fs))",
}

_REGISTRY = {"": LocalFileSystem(), "file": LocalFileSystem()}


def register_filesystem(scheme: str, fs: FileSystem) -> None:
    """Plug in a filesystem implementation for a URI scheme."""
    _REGISTRY[scheme] = fs


def resolve_filesystem(path: str) -> "tuple[FileSystem, str]":
    """(filesystem, path-without-scheme) for one path."""
    if "://" in path:
        scheme, rest = path.split("://", 1)
        fs = _REGISTRY.get(scheme)
        if fs is not None:
            return fs, rest
        hint = _CLOUD_SCHEMES.get(
            scheme, f"unknown scheme {scheme!r}")
        raise NotImplementedError(
            f"no filesystem registered for {scheme}:// — {hint}")
    return _REGISTRY[""], path


def expand_paths(paths: Paths, suffix: str = "") -> List[str]:
    """Expand files/dirs/globs into a sorted file list (scheme-aware).
    Results KEEP their URI scheme so downstream readers resolve to the
    same filesystem."""
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        scheme = p.split("://", 1)[0] + "://" if "://" in p else ""
        fs, local = resolve_filesystem(p)
        if fs.exists(local) and fs.isdir(local):
            out.extend(scheme + f for f in fs.listdir(local)
                       if not suffix or f.endswith(suffix))
        elif "*" in local:
            out.extend(scheme + f for f in fs.glob(local))
        else:
            out.append(p)
    return out
