"""Avro Object Container File codec (no external avro library).

Reference capability: ``python/ray/data/datasource/avro_datasource.py``
(reads Avro via the `fastavro` wheel). That wheel is not in this image,
so this is a native implementation of the parts the datasource needs:
the 1.11 container-file framing (magic, metadata map, sync-marker
delimited blocks, null/deflate codecs) and the binary encoding for the
standard types — null, boolean, int/long (zigzag varint), float,
double, bytes, string, record, enum, array, map, union, fixed.

Writer support covers the schemas :func:`infer_schema` produces from
Arrow-typed rows (the ``write_avro`` path); the reader handles any
spec-compliant file.
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, BinaryIO, Dict, Iterator, List, Optional, Tuple

MAGIC = b"Obj\x01"
SYNC_SIZE = 16


# ---------------------------------------------------------------------------
# primitive binary encoding
# ---------------------------------------------------------------------------

def _zigzag_encode(n: int) -> int:
    return (n << 1) ^ (n >> 63) if n < 0 else n << 1


def _zigzag_decode(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def write_long(out: io.BytesIO, n: int) -> None:
    z = _zigzag_encode(n)
    while True:
        b = z & 0x7F
        z >>= 7
        if z:
            out.write(bytes([b | 0x80]))
        else:
            out.write(bytes([b]))
            return


def read_long(buf: BinaryIO) -> int:
    shift = 0
    accum = 0
    while True:
        byte = buf.read(1)
        if not byte:
            raise EOFError("truncated varint")
        b = byte[0]
        accum |= (b & 0x7F) << shift
        if not b & 0x80:
            return _zigzag_decode(accum)
        shift += 7


def write_bytes(out: io.BytesIO, b: bytes) -> None:
    write_long(out, len(b))
    out.write(b)


def read_n(buf: BinaryIO, n: int) -> bytes:
    data = buf.read(n)
    if len(data) != n:
        raise EOFError("truncated data")
    return data


# ---------------------------------------------------------------------------
# schema-driven encode/decode
# ---------------------------------------------------------------------------

def _named(schema: Any) -> Any:
    """Resolve {'type': X, ...} wrappers to X for primitive checks."""
    if isinstance(schema, dict) and isinstance(schema.get("type"), str) \
            and schema["type"] in _PRIMITIVES and len(schema) == 1:
        return schema["type"]
    return schema


_PRIMITIVES = ("null", "boolean", "int", "long", "float", "double",
               "bytes", "string")


def encode(out: io.BytesIO, schema: Any, value: Any,
           names: Optional[Dict[str, Any]] = None) -> None:
    names = names if names is not None else {}
    schema = _named(schema)
    if isinstance(schema, str):
        if schema in names:
            encode(out, names[schema], value, names)
        elif schema == "null":
            pass
        elif schema == "boolean":
            out.write(b"\x01" if value else b"\x00")
        elif schema in ("int", "long"):
            write_long(out, int(value))
        elif schema == "float":
            out.write(struct.pack("<f", float(value)))
        elif schema == "double":
            out.write(struct.pack("<d", float(value)))
        elif schema == "bytes":
            write_bytes(out, bytes(value))
        elif schema == "string":
            write_bytes(out, str(value).encode())
        else:
            raise ValueError(f"unknown schema {schema!r}")
        return
    if isinstance(schema, list):                     # union
        for i, branch in enumerate(schema):
            if _matches(branch, value, names):
                write_long(out, i)
                encode(out, branch, value, names)
                return
        raise ValueError(f"no union branch for {type(value)}")
    t = schema["type"]
    if t == "record":
        names[schema["name"]] = schema
        for field in schema["fields"]:
            encode(out, field["type"], value.get(field["name"]), names)
    elif t == "enum":
        names[schema["name"]] = schema
        write_long(out, schema["symbols"].index(value))
    elif t == "fixed":
        names[schema["name"]] = schema
        out.write(bytes(value))
    elif t == "array":
        if value:
            write_long(out, len(value))
            for item in value:
                encode(out, schema["items"], item, names)
        write_long(out, 0)
    elif t == "map":
        if value:
            write_long(out, len(value))
            for k, v in value.items():
                write_bytes(out, str(k).encode())
                encode(out, schema["values"], v, names)
        write_long(out, 0)
    else:
        encode(out, t, value, names)


def _matches(schema: Any, value: Any, names: Dict[str, Any]) -> bool:
    schema = _named(schema)
    if isinstance(schema, str):
        if schema in names:
            return _matches(names[schema], value, names)
        return {
            "null": value is None,
            "boolean": isinstance(value, bool),
            "int": isinstance(value, int) and not isinstance(value, bool),
            "long": isinstance(value, int) and not isinstance(value, bool),
            "float": isinstance(value, float),
            "double": isinstance(value, float),
            "bytes": isinstance(value, (bytes, bytearray)),
            "string": isinstance(value, str),
        }.get(schema, False)
    if isinstance(schema, list):
        return any(_matches(b, value, names) for b in schema)
    t = schema.get("type")
    if t == "record":
        return isinstance(value, dict)
    if t == "enum":
        return isinstance(value, str) and value in schema["symbols"]
    if t == "array":
        return isinstance(value, list)
    if t == "map":
        return isinstance(value, dict)
    if t == "fixed":
        return isinstance(value, (bytes, bytearray))
    return _matches(t, value, names)


def decode(buf: BinaryIO, schema: Any,
           names: Optional[Dict[str, Any]] = None) -> Any:
    names = names if names is not None else {}
    schema = _named(schema)
    if isinstance(schema, str):
        if schema in names:
            return decode(buf, names[schema], names)
        if schema == "null":
            return None
        if schema == "boolean":
            return read_n(buf, 1) == b"\x01"
        if schema in ("int", "long"):
            return read_long(buf)
        if schema == "float":
            return struct.unpack("<f", read_n(buf, 4))[0]
        if schema == "double":
            return struct.unpack("<d", read_n(buf, 8))[0]
        if schema == "bytes":
            return read_n(buf, read_long(buf))
        if schema == "string":
            return read_n(buf, read_long(buf)).decode()
        raise ValueError(f"unknown schema {schema!r}")
    if isinstance(schema, list):                     # union
        return decode(buf, schema[read_long(buf)], names)
    t = schema["type"]
    if t == "record":
        names[schema["name"]] = schema
        return {f["name"]: decode(buf, f["type"], names)
                for f in schema["fields"]}
    if t == "enum":
        names[schema["name"]] = schema
        return schema["symbols"][read_long(buf)]
    if t == "fixed":
        names[schema["name"]] = schema
        return read_n(buf, schema["size"])
    if t == "array":
        out: List[Any] = []
        while True:
            n = read_long(buf)
            if n == 0:
                return out
            if n < 0:            # block with byte-size prefix
                read_long(buf)
                n = -n
            for _ in range(n):
                out.append(decode(buf, schema["items"], names))
    if t == "map":
        m: Dict[str, Any] = {}
        while True:
            n = read_long(buf)
            if n == 0:
                return m
            if n < 0:
                read_long(buf)
                n = -n
            for _ in range(n):
                key = read_n(buf, read_long(buf)).decode()
                m[key] = decode(buf, schema["values"], names)
    return decode(buf, t, names)


# ---------------------------------------------------------------------------
# container file
# ---------------------------------------------------------------------------

def read_container(data: bytes) -> Tuple[Any, List[Any]]:
    """Parse one Object Container File; returns (schema, records)."""
    buf = io.BytesIO(data)
    if read_n(buf, 4) != MAGIC:
        raise ValueError("not an Avro object container file")
    meta: Dict[str, bytes] = {}
    while True:
        n = read_long(buf)
        if n == 0:
            break
        if n < 0:
            read_long(buf)
            n = -n
        for _ in range(n):
            key = read_n(buf, read_long(buf)).decode()
            meta[key] = read_n(buf, read_long(buf))
    schema = json.loads(meta["avro.schema"].decode())
    codec = meta.get("avro.codec", b"null").decode()
    if codec not in ("null", "deflate"):
        raise ValueError(f"unsupported avro codec {codec!r}")
    sync = read_n(buf, SYNC_SIZE)
    records: List[Any] = []
    while True:
        probe = buf.read(1)
        if not probe:
            break
        buf.seek(-1, os.SEEK_CUR)
        count = read_long(buf)
        nbytes = read_long(buf)
        payload = read_n(buf, nbytes)
        if codec == "deflate":
            payload = zlib.decompress(payload, -15)
        block = io.BytesIO(payload)
        for _ in range(count):
            records.append(decode(block, schema))
        if read_n(buf, SYNC_SIZE) != sync:
            raise ValueError("sync marker mismatch")
    return schema, records


def write_container(schema: Any, records: List[Any], *,
                    codec: str = "deflate",
                    records_per_block: int = 4096) -> bytes:
    """Serialize records into one Object Container File."""
    if codec not in ("null", "deflate"):
        raise ValueError(f"unsupported avro codec {codec!r}")
    out = io.BytesIO()
    out.write(MAGIC)
    meta = {"avro.schema": json.dumps(schema).encode(),
            "avro.codec": codec.encode()}
    write_long(out, len(meta))
    for k, v in meta.items():
        write_bytes(out, k.encode())
        write_bytes(out, v)
    write_long(out, 0)
    # deterministic sync marker from content is fine (spec: any 16 bytes)
    import hashlib
    sync = hashlib.md5(json.dumps(schema).encode()).digest()
    out.write(sync)
    for lo in range(0, len(records), records_per_block):
        chunk = records[lo:lo + records_per_block]
        payload_buf = io.BytesIO()
        for rec in chunk:
            encode(payload_buf, schema, rec)
        payload = payload_buf.getvalue()
        if codec == "deflate":
            cobj = zlib.compressobj(9, zlib.DEFLATED, -15)
            payload = cobj.compress(payload) + cobj.flush()
        write_long(out, len(chunk))
        write_long(out, len(payload))
        out.write(payload)
        out.write(sync)
    return out.getvalue()


def infer_schema(rows: List[Dict[str, Any]],
                 name: str = "row") -> Dict[str, Any]:
    """Record schema from python rows (None -> nullable union)."""
    fields: Dict[str, set] = {}
    for row in rows:
        for k, v in row.items():
            fields.setdefault(k, set()).add(_pytype_to_avro(v))
    out_fields = []
    for k, types in fields.items():
        types.discard(None)
        tl = sorted(types)
        if not tl:
            ftype: Any = "null"
        elif len(tl) == 1:
            ftype = tl[0]
        else:
            ftype = tl
        # null-pad: any row missing the key (or None) needs the union —
        # unless the column is all-null already ("null" alone is valid;
        # ["null","null"] is a spec-forbidden duplicate-branch union)
        if ftype != "null" and any(
                k not in row or row[k] is None for row in rows):
            ftype = (["null", ftype] if isinstance(ftype, str)
                     else ["null", *ftype])
        out_fields.append({"name": k, "type": ftype})
    return {"type": "record", "name": name, "fields": out_fields}


def _pytype_to_avro(v: Any) -> Optional[str]:
    import numpy as np
    if v is None:
        return None
    if isinstance(v, bool) or isinstance(v, np.bool_):
        return "boolean"
    if isinstance(v, (int, np.integer)):
        return "long"
    if isinstance(v, (float, np.floating)):
        return "double"
    if isinstance(v, (bytes, bytearray)):
        return "bytes"
    if isinstance(v, str):
        return "string"
    raise TypeError(f"cannot map {type(v)} to an Avro type")
