"""Aggregation functions (reference: `python/ray/data/aggregate.py`)."""

from __future__ import annotations

from typing import Optional

import numpy as np


class AggregateFn:
    name: str = "agg"

    def compute(self, block) -> float:
        raise NotImplementedError

    def _col(self, block, on: Optional[str]):
        if on is None:
            on = block.column_names[0]
        return block.column(on).to_numpy(zero_copy_only=False)


class Count(AggregateFn):
    def __init__(self, on: Optional[str] = None):
        self.on = on
        self.name = "count()"

    def compute(self, block):
        return int(block.num_rows)


class Sum(AggregateFn):
    def __init__(self, on: Optional[str] = None):
        self.on = on
        self.name = f"sum({on or ''})"

    def compute(self, block):
        return self._col(block, self.on).sum()


class Min(AggregateFn):
    def __init__(self, on: Optional[str] = None):
        self.on = on
        self.name = f"min({on or ''})"

    def compute(self, block):
        return self._col(block, self.on).min()


class Max(AggregateFn):
    def __init__(self, on: Optional[str] = None):
        self.on = on
        self.name = f"max({on or ''})"

    def compute(self, block):
        return self._col(block, self.on).max()


class Mean(AggregateFn):
    def __init__(self, on: Optional[str] = None):
        self.on = on
        self.name = f"mean({on or ''})"

    def compute(self, block):
        return float(self._col(block, self.on).mean())


class Std(AggregateFn):
    def __init__(self, on: Optional[str] = None, ddof: int = 1):
        self.on = on
        self.ddof = ddof
        self.name = f"std({on or ''})"

    def compute(self, block):
        return float(np.std(self._col(block, self.on), ddof=self.ddof))
