"""Streaming-executor backpressure policies.

Reference: ``data/_internal/execution/backpressure_policy/`` — pluggable
policies consulted by the scheduling loop before admitting new work to an
operator (``ConcurrencyCapBackpressurePolicy``,
``StreamingOutputBackpressurePolicy``); plus a store-usage policy that
throttles UPSTREAM operators when the object store fills, so the pipeline
drains instead of spilling (the role of the reference's resource-manager
budgets in ``streaming_executor_state.py``).

A policy's ``can_launch(op, executor)`` returns False to veto launching
one more task on ``op`` this tick.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, List

if TYPE_CHECKING:
    from ray_tpu.data.execution import PhysicalOperator, StreamingExecutor


class BackpressurePolicy:
    def can_launch(self, op: "PhysicalOperator",
                   executor: "StreamingExecutor") -> bool:
        return True


class ConcurrencyCapBackpressurePolicy(BackpressurePolicy):
    """At most ``max_in_flight`` concurrent tasks per operator."""

    def can_launch(self, op, executor) -> bool:
        return len(op.active) < op.max_in_flight


class StreamingOutputBackpressurePolicy(BackpressurePolicy):
    """Bound each operator's output queue: a slow consumer stalls its
    producer instead of buffering unboundedly."""

    def can_launch(self, op, executor) -> bool:
        return (len(op.outqueue) + len(op.active)
                < executor.max_out_queue)


class ObjectStoreMemoryBackpressurePolicy(BackpressurePolicy):
    """Throttle upstream work when the cluster object stores pass a
    usage fraction: only the most-downstream runnable operator may launch
    (draining makes room; reading makes pressure)."""

    def __init__(self, threshold: float = 0.8):
        self.threshold = threshold

    def _store_pressure(self) -> float:
        from ray_tpu._private import worker

        rt = worker.global_runtime()
        if rt is None:
            return 0.0
        used = cap = 0
        for node in rt.alive_nodes():
            store = node.store
            try:
                used += store.used_bytes()
                cap += getattr(store, "capacity_bytes", 0)
            except Exception:
                continue
        return used / cap if cap else 0.0

    def can_launch(self, op, executor) -> bool:
        if self._store_pressure() < self.threshold:
            return True
        # under pressure: permit only the most-downstream op with input
        for candidate in reversed(executor.ops):
            if candidate.inqueue and len(candidate.active) \
                    < candidate.max_in_flight:
                return candidate is op
        return True


def default_policies() -> List[BackpressurePolicy]:
    from ray_tpu.data.context import DataContext

    ctx = DataContext.get_current()
    threshold = getattr(ctx, "object_store_backpressure_threshold", 0.8)
    return [ConcurrencyCapBackpressurePolicy(),
            StreamingOutputBackpressurePolicy(),
            ObjectStoreMemoryBackpressurePolicy(threshold)]
