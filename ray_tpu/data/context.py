"""DataContext (reference: `python/ray/data/context.py` — thread-inherited
execution configuration propagated into tasks) + execution stats
(reference: `data/_internal/stats.py`)."""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class DataContext:
    target_max_block_size: int = 128 * 1024 * 1024
    max_in_flight_tasks_per_operator: int = 8
    max_operator_output_queue: int = 16
    default_batch_size: int = 256
    enable_progress_bars: bool = False
    eager_free: bool = True
    # store-usage fraction above which upstream operators are throttled
    # (backpressure_policy.ObjectStoreMemoryBackpressurePolicy)
    object_store_backpressure_threshold: float = 0.8

    _local = threading.local()

    @classmethod
    def get_current(cls) -> "DataContext":
        ctx = getattr(cls._local, "ctx", None)
        if ctx is None:
            ctx = cls()
            cls._local.ctx = ctx
        return ctx

    @classmethod
    def _set_current(cls, ctx: "DataContext") -> None:
        cls._local.ctx = ctx


class DatasetStats:
    """Per-dataset execution statistics (operator timings, block counts).

    Every instance also registers in a process-global ring so the
    dashboard can surface live per-dataset operator metrics (reference:
    `data/_internal/stats.py` StatsManager -> dashboard data module)."""

    _RECENT: "List[DatasetStats]" = []
    _RECENT_CAP = 50

    def __init__(self):
        self._lock = threading.Lock()
        self.operators: Dict[str, Dict[str, float]] = {}
        self.created_at = time.time()
        self._registered = False

    # Datasets travel inside Trainers (Tune trials pickle the whole
    # trainer, datasets included — reference: train+tune integration);
    # stats are per-process observability, so the lock/ring membership
    # stay out of the pickle and a fresh lock is minted on arrival.
    def __getstate__(self):
        state = dict(self.__dict__)
        state.pop("_lock", None)
        state["_registered"] = False
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()

    def _register(self) -> None:
        # ring membership starts at the FIRST record(): every lazy
        # transform builds a Dataset (and stats) that never executes —
        # registering at __init__ would evict the executed ones
        if not self._registered:
            self._registered = True
            DatasetStats._RECENT.append(self)
            del DatasetStats._RECENT[:-DatasetStats._RECENT_CAP]

    @classmethod
    def recent(cls) -> List[Dict[str, Any]]:
        out = []
        for s in list(cls._RECENT):
            with s._lock:                       # snapshot: record() may
                ops = {k: dict(v)               # be mutating mid-dump
                       for k, v in s.operators.items()}
            if ops:
                out.append({"created_at": s.created_at,
                            "operators": ops})
        return out

    def record(self, op_name: str, *, blocks: int = 0, rows: int = 0,
               seconds: float = 0.0) -> None:
        self._register()
        with self._lock:
            entry = self.operators.setdefault(
                op_name, {"blocks": 0, "rows": 0, "seconds": 0.0})
            entry["blocks"] += blocks
            entry["rows"] += rows
            entry["seconds"] += seconds

    def summary(self) -> str:
        with self._lock:
            lines = ["Dataset execution stats:"]
            for name, e in self.operators.items():
                lines.append(
                    f"  {name}: {int(e['blocks'])} blocks, "
                    f"{int(e['rows'])} rows, {e['seconds']:.3f}s")
            return "\n".join(lines)
