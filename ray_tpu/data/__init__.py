"""ray_tpu.data — streaming distributed datasets.

Reference: Ray Data (`python/ray/data`, SURVEY.md §2.2, §3.6): lazy
Dataset → logical plan → rule optimizer → physical operators →
streaming executor with backpressure; Arrow blocks in the object store.
TPU-native extension: ``DataIterator.to_jax`` double-buffers batches into
HBM (device_put overlap), the ingest path of BASELINE.md config 4.
"""

from ray_tpu.data.aggregate import Count, Max, Mean, Min, Std, Sum
from ray_tpu.data.dataset import Dataset, GroupedData
from ray_tpu.data.iterator import DataIterator
from ray_tpu.data.read_api import (from_arrow, from_items, from_numpy,
                                   from_pandas, from_torch, range, read_avro, read_delta,
                                   read_binary_files,
                                   read_csv, read_images, read_json,
                                   read_numpy, read_orc, read_parquet, read_sql,
                                   read_text, read_tfrecords,
                                   read_webdataset)

__all__ = [
    "Dataset", "GroupedData", "DataIterator",
    "range", "from_items", "from_numpy", "from_pandas", "from_arrow", "from_torch",
    "read_parquet", "read_csv", "read_json", "read_text",
    "read_binary_files",
    "read_images",
    "read_numpy",
    "read_sql",
    "read_avro",
    "read_delta",
    "read_orc",
    "read_tfrecords",
    "read_webdataset",
    "Count", "Sum", "Min", "Max", "Mean", "Std",
]
