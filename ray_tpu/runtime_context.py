"""Runtime context — the reference's import path (`ray.runtime_context`)
re-exporting the canonical implementation."""

from ray_tpu._private.runtime_context import (RuntimeContext,
                                              get_runtime_context)

__all__ = ["RuntimeContext", "get_runtime_context"]
