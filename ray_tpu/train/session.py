"""Per-worker train session: the `ray.train.report` / get_context surface.

Reference: `python/ray/train/_internal/session.py` + `train/context.py`
(`get_context().get_world_rank()` etc). Thread-local because virtual
workers share a process in tests.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint

_local = threading.local()


class TrainContext:
    def __init__(self, world_rank: int, world_size: int,
                 local_rank: int = 0, node_rank: int = 0,
                 mesh_spec=None, experiment_name: str = "",
                 latest_checkpoint: Optional[Checkpoint] = None,
                 dataset_shards: Optional[Dict[str, Any]] = None):
        self._world_rank = world_rank
        self._world_size = world_size
        self._local_rank = local_rank
        self._node_rank = node_rank
        self._mesh_spec = mesh_spec
        self._experiment_name = experiment_name
        self._latest_checkpoint = latest_checkpoint
        self._dataset_shards = dataset_shards or {}
        self._reported: list = []
        self._report_cb = None
        self._stop_event = threading.Event()

    def get_world_rank(self) -> int:
        return self._world_rank

    def get_world_size(self) -> int:
        return self._world_size

    def get_local_rank(self) -> int:
        return self._local_rank

    def get_node_rank(self) -> int:
        return self._node_rank

    def get_experiment_name(self) -> str:
        return self._experiment_name

    def get_mesh_spec(self):
        return self._mesh_spec


def get_context() -> TrainContext:
    ctx = getattr(_local, "ctx", None)
    if ctx is None:
        raise RuntimeError("ray_tpu.train session: not inside a train "
                           "worker (get_context() called outside fit())")
    return ctx


def _set_context(ctx: Optional[TrainContext]) -> None:
    _local.ctx = ctx


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    """Report metrics (+ optional checkpoint) from a train worker.

    Reference semantics (`ray.train.report`): all workers call it each
    iteration; rank-0's checkpoint is persisted.
    """
    ctx = get_context()
    entry = {"metrics": dict(metrics), "checkpoint": checkpoint,
             "rank": ctx._world_rank}
    ctx._reported.append(entry)
    if ctx._report_cb is not None:
        ctx._report_cb(entry)
    if ctx._stop_event.is_set():
        raise StopIteration("train run stopped by controller")


def get_checkpoint() -> Optional[Checkpoint]:
    """Latest persisted checkpoint (for resume inside the train fn)."""
    return get_context()._latest_checkpoint


def get_dataset_shard(name: str = "train"):
    """Per-worker dataset shard (reference: train/_internal/data_config.py
    streaming_split ingest, SURVEY.md §8.13)."""
    ctx = get_context()
    shard = ctx._dataset_shards.get(name)
    if shard is None:
        raise KeyError(f"no dataset shard named {name!r} "
                       f"(have {list(ctx._dataset_shards)})")
    return shard
