"""JaxTrainer: the controller loop (reference: Train v2
`v2/_internal/execution/controller/controller.py:94,369,462` +
`v2/api/data_parallel_trainer.py:108`).

Control loop: start worker group → poll → persist rank-0 checkpoints →
on failure consult FailureConfig → restart group from latest checkpoint
(elastic group-level recovery) → Result.

TPU-native difference from the reference: workers don't wrap torch DDP —
each rank runs the same jitted SPMD program; in a real pod every host-rank
drives its slice of the same mesh (jax multi-host SPMD), so "restart the
group" is exactly "re-form the mesh".
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.worker_group import WorkerGroup


@dataclasses.dataclass
class Result:
    metrics: Dict[str, Any]
    checkpoint: Optional[Checkpoint]
    path: str
    metrics_history: List[Dict[str, Any]]
    error: Optional[str] = None


class JaxTrainer:
    """Data-parallel-style trainer: runs ``train_loop_per_worker`` on
    ``scaling_config.num_workers`` gang-scheduled workers."""

    def __init__(self, train_loop_per_worker: Callable,
                 *, train_loop_config: Optional[Dict] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None,
                 scaling_policy=None):
        from ray_tpu.train.scaling_policy import resolve_policy
        self.train_loop = train_loop_per_worker
        self.train_loop_config = train_loop_config
        self.scaling = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.resume_from_checkpoint = resume_from_checkpoint
        self.scaling_policy = resolve_policy(self.scaling, scaling_policy)

    # ------------------------------------------------------------------
    def fit(self) -> Result:
        import ray_tpu

        # Multi-host pods: bring up the jax coordination service so the
        # mesh spans every host's devices (SURVEY §5.8 plane 3 — the
        # rendezvous role Train plays in the reference). This runs at
        # the DRIVER layer deliberately: Train workers are _in_process
        # SPMD actors (threads of this mesh-owning process, see
        # worker_process.py's TPU-first placement rule), so the driver
        # IS the per-host jax process that must join the coordination
        # service. Single host is a no-op.
        from ray_tpu.parallel.multihost import initialize_multihost
        try:
            initialize_multihost()
        except Exception as e:  # pod env present but rendezvous failed
            raise RuntimeError(
                f"multi-host initialization failed: {e}") from e

        from ray_tpu._private.export_events import emit_export
        from ray_tpu.train.callbacks import invoke as _cb
        emit_export("TRAIN_RUN", name=self.run_config.name or "train_run",
                    state="RUNNING",
                    num_workers=self.scaling_policy.initial_size())
        path = self.run_config.resolved_storage_path()
        _cb(self.run_config.callbacks, "on_run_start",
            self.run_config.name or "train_run", self.train_loop_config)
        ckpt_cfg = self.run_config.checkpoint_config
        manager = CheckpointManager(
            path, num_to_keep=ckpt_cfg.num_to_keep,
            score_attribute=ckpt_cfg.checkpoint_score_attribute,
            score_order=ckpt_cfg.checkpoint_score_order)

        latest = self.resume_from_checkpoint
        history: List[Dict[str, Any]] = []
        last_metrics: Dict[str, Any] = {}
        failures = 0
        max_failures = self.run_config.failure_config.max_failures
        error: Optional[str] = None

        from ray_tpu import exceptions as _exc
        from ray_tpu.train.scaling_policy import ElasticScalingPolicy
        placement_timeout = self.scaling.placement_timeout_s
        if placement_timeout is None and isinstance(
                self.scaling_policy, ElasticScalingPolicy):
            # elastic promises failure-not-hang for unplaceable gangs
            placement_timeout = 120.0
        world_size = self.scaling_policy.initial_size()
        while True:
            try:
                group = WorkerGroup(
                    world_size, self.scaling.worker_resources(),
                    placement_strategy=self.scaling.placement_strategy,
                    experiment_name=self.run_config.name or "train_run",
                    placement_timeout_s=placement_timeout)
            except _exc.GetTimeoutError as e:
                # placement timed out — everything else (actor-creation
                # bugs etc.) propagates to the caller as before
                failures += 1
                if max_failures >= 0 and failures > max_failures:
                    error = f"worker group unplaceable: {e!r}"
                    break
                decision = self.scaling_policy.on_recovery(
                    world_size, self.scaling.worker_resources(),
                    failures)
                world_size = decision.num_workers
                continue
            shards = self._split_datasets(world_size)
            run_refs = group.start_run(
                self.train_loop, self.train_loop_config,
                latest_checkpoint=latest, dataset_shards=shards)
            outcome, err = self._poll_until_done(
                ray_tpu, group, run_refs, manager, history)
            if history:
                last_metrics = history[-1]["metrics"]
            latest = manager.latest_checkpoint() or latest
            group.shutdown()

            if outcome == "finished":
                break
            failures += 1
            if max_failures >= 0 and failures > max_failures:
                error = err or "train workers failed"
                break
            # elastic retry: the policy picks the NEXT world size (e.g.
            # the surviving hosts after a node death) and the group
            # re-forms from the latest checkpoint at that size — the
            # SPMD program re-shards its state onto the smaller mesh at
            # restore time
            decision = self.scaling_policy.on_recovery(
                world_size, self.scaling.worker_resources(), failures)
            world_size = decision.num_workers

        emit_export("TRAIN_RUN", name=self.run_config.name or "train_run",
                    state="ERRORED" if error else "FINISHED",
                    error=error)
        result = Result(metrics=last_metrics, checkpoint=latest, path=path,
                        metrics_history=history, error=error)
        _cb(self.run_config.callbacks, "on_run_end", result, error)
        return result

    # ------------------------------------------------------------------
    def _split_datasets(self, n: int):
        if not self.datasets:
            return None
        shards: List[Dict[str, Any]] = [dict() for _ in range(n)]
        for name, ds in self.datasets.items():
            if hasattr(ds, "streaming_split"):
                for i, piece in enumerate(ds.streaming_split(n)):
                    shards[i][name] = piece
            else:  # static sequence: strided split
                for i in range(n):
                    shards[i][name] = ds[i::n]
        return shards

    def _poll_until_done(self, ray_tpu, group, run_refs, manager, history):
        """Drain reports until all ranks finish or any fails.

        Returns ("finished" | "failed", error)."""
        pending = list(run_refs)
        while True:
            # Drain worker report buffers; persist rank-0 checkpoints.
            from ray_tpu.train.callbacks import invoke as _cb
            for status in group.poll():
                for entry in status["reports"]:
                    history.append(entry)
                    _cb(self.run_config.callbacks, "on_report",
                        entry["metrics"], len(history),
                        rank=entry["rank"])
                    if entry["rank"] == 0 and entry["checkpoint"] is not None:
                        manager.register(entry["checkpoint"],
                                         entry["metrics"])
                        _cb(self.run_config.callbacks, "on_checkpoint",
                            entry["checkpoint"], len(history))
            if not pending:
                return "finished", None
            done, pending = ray_tpu.wait(
                pending, num_returns=len(pending), timeout=0.2)
            for ref in done:
                try:
                    ray_tpu.get(ref)
                except Exception as e:
                    # One dead rank fails the gang (SPMD mesh semantics).
                    self._drain(group, manager, history)
                    return "failed", repr(e)

    def _drain(self, group, manager, history):
        try:
            for status in group.poll():
                for entry in status["reports"]:
                    history.append(entry)
                    if entry["rank"] == 0 and entry["checkpoint"] is not None:
                        manager.register(entry["checkpoint"],
                                         entry["metrics"])
        except Exception:
            pass
