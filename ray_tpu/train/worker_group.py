"""Worker group: gang-scheduled train workers polled for health/results.

Reference: `train/v2/_internal/execution/worker_group/worker_group.py:99`
(start :236, poll_status :443) — actors in a placement group, each running
the user train fn on a thread while the controller polls. Here workers are
``max_concurrency=2`` actors: one lane runs the train fn, the other serves
``poll()``.
"""

from __future__ import annotations

import time
import traceback
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train.session import TrainContext, _set_context
from ray_tpu.util.placement_group import (placement_group,
                                          remove_placement_group)


class _TrainWorker:
    """Actor hosting one rank of the train fn."""

    def __init__(self, rank: int, world_size: int, experiment_name: str):
        self.rank = rank
        self.world_size = world_size
        self.experiment_name = experiment_name
        self._buffer: List[Dict] = []
        self._status = "idle"
        self._error: Optional[str] = None

    def run(self, fn: Callable, config: Optional[Dict],
            latest_checkpoint=None, dataset_shards=None) -> str:
        ctx = TrainContext(
            world_rank=self.rank, world_size=self.world_size,
            experiment_name=self.experiment_name,
            latest_checkpoint=latest_checkpoint,
            dataset_shards=dataset_shards)
        # Late-bound: poll() swaps self._buffer out, so the callback must
        # resolve the attribute at call time, not capture the list object.
        ctx._report_cb = lambda entry: self._buffer.append(entry)
        _set_context(ctx)
        self._status = "running"
        try:
            import inspect
            takes_config = bool(inspect.signature(fn).parameters)
            if takes_config:
                fn(config if config is not None else {})
            else:
                fn()
            self._status = "finished"
            return "finished"
        except StopIteration:
            self._status = "finished"
            return "stopped"
        except Exception:
            self._status = "failed"
            self._error = traceback.format_exc()
            raise
        finally:
            _set_context(None)

    def poll(self) -> Dict[str, Any]:
        drained, self._buffer = self._buffer, []
        return {"rank": self.rank, "status": self._status,
                "reports": drained, "error": self._error}

    def ping(self) -> bool:
        return True


class WorkerGroup:
    def __init__(self, num_workers: int, resources_per_worker: Dict,
                 placement_strategy: str = "PACK",
                 experiment_name: str = "",
                 placement_timeout_s: Optional[float] = None):
        self.num_workers = num_workers
        self.experiment_name = experiment_name
        self.pg = placement_group(
            [dict(resources_per_worker) for _ in range(num_workers)],
            strategy=placement_strategy)
        try:
            ray_tpu.get(self.pg.ready(), timeout=placement_timeout_s)
        except Exception:
            # unplaceable gang: release the pending PG request so the
            # caller's retry (possibly at a smaller size) starts clean
            try:
                remove_placement_group(self.pg)
            except Exception:
                pass
            raise
        worker_cls = ray_tpu.remote(_TrainWorker)
        from ray_tpu._private.task_spec import PlacementGroupSchedulingStrategy
        self.workers = [
            worker_cls.options(
                # SPMD mesh actors: each rank drives jitted device work;
                # the chip/mesh is owned by the host process and XLA
                # releases the GIL, so these stay in-process (TPU-first
                # placement rule; see worker_process.py docstring).
                _in_process=True,
                max_concurrency=2,
                num_cpus=resources_per_worker.get("CPU", 1),
                resources={k: v for k, v in resources_per_worker.items()
                           if k != "CPU"},
                scheduling_strategy=PlacementGroupSchedulingStrategy(
                    placement_group=self.pg, placement_group_bundle_index=i),
            ).remote(i, num_workers, experiment_name)
            for i in range(num_workers)]
        ray_tpu.get([w.ping.remote() for w in self.workers])

    def start_run(self, fn: Callable, config: Optional[Dict],
                  latest_checkpoint=None,
                  dataset_shards: Optional[List[Dict]] = None):
        """Kick off the train fn on every rank; returns completion refs."""
        return [
            w.run.remote(fn, config, latest_checkpoint,
                         dataset_shards[i] if dataset_shards else None)
            for i, w in enumerate(self.workers)]

    def poll(self) -> List[Dict[str, Any]]:
        out = []
        for w in self.workers:
            try:
                out.append(ray_tpu.get(w.poll.remote(), timeout=30))
            except Exception as e:
                out.append({"rank": None, "status": "dead",
                            "reports": [], "error": repr(e)})
        return out

    def shutdown(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        try:
            remove_placement_group(self.pg)
        except Exception:
            pass
