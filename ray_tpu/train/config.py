"""Train/run configuration (reference: `python/ray/air/config.py` —
ScalingConfig/RunConfig/FailureConfig/CheckpointConfig — re-shaped for
meshes: scaling is (workers × mesh axes), not just a worker count)."""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, Optional

from ray_tpu.parallel.mesh import MeshSpec


@dataclasses.dataclass
class ScalingConfig:
    """How much hardware, and how it is meshed.

    num_workers = host-level orchestration workers (one per host in a real
    pod; N virtual workers in tests). ``mesh`` describes the device mesh the
    SPMD program runs over — the TPU-native generalization of
    use_gpu/resources_per_worker.
    """

    num_workers: int = 1
    resources_per_worker: Optional[Dict[str, float]] = None
    use_tpu: bool = False
    mesh: Optional[MeshSpec] = None
    placement_strategy: str = "PACK"
    # (min, max): recover from worker failure by re-forming the group at
    # the surviving capacity within this range instead of waiting for
    # max hardware (reference: train v2 scaling_policy.py; see
    # ray_tpu/train/scaling_policy.py)
    elastic: Optional[tuple] = None
    # bound worker-group placement: a gang that cannot place within this
    # window FAILS the attempt (counts against FailureConfig) instead of
    # hanging. None = wait forever (fixed-size default); elastic runs
    # default to 120s in the trainer.
    placement_timeout_s: Optional[float] = None

    def worker_resources(self) -> Dict[str, float]:
        if self.resources_per_worker:
            return dict(self.resources_per_worker)
        res = {"CPU": 1.0}
        if self.use_tpu:
            res["TPU"] = 1.0
        return res


@dataclasses.dataclass
class FailureConfig:
    """max_failures: <0 = infinite retries (reference air.FailureConfig)."""

    max_failures: int = 0


@dataclasses.dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"


@dataclasses.dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = dataclasses.field(
        default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = dataclasses.field(
        default_factory=CheckpointConfig)
    # logger/integration callbacks (reference: air callbacks + tune
    # logger callbacks; see ray_tpu/train/callbacks.py)
    callbacks: list = dataclasses.field(default_factory=list)

    def resolved_storage_path(self) -> str:
        base = self.storage_path or os.path.join(
            os.path.expanduser("~"), "ray_tpu_results")
        name = self.name or "train_run"
        return os.path.join(base, name)
