"""Train worker-group collectives (reference:
`train/collective/collectives.py:20,82` — barrier / broadcast_from_rank /
allreduce across the worker group, rendezvoused through the control
plane).

These are HOST-level collectives (config exchange, barriers, metric
reduction). Tensor collectives run inside jitted SPMD programs over ICI —
nothing to rendezvous there.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train.session import get_context


class _Rendezvous:
    """Actor: collects world_size contributions per (op, seq) key."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self._slots: Dict[str, Dict[int, Any]] = {}
        self._done: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def contribute(self, key: str, rank: int, value: Any) -> None:
        with self._lock:
            slot = self._slots.setdefault(key, {})
            slot[rank] = value

    def poll(self, key: str, reducer: str) -> Any:
        """Returns (ready, result)."""
        with self._lock:
            if key in self._done:
                return True, self._done[key]
            slot = self._slots.get(key, {})
            if len(slot) < self.world_size:
                return False, None
            values = [slot[r] for r in sorted(slot)]
            if reducer == "list":
                out = values
            elif reducer == "sum":
                out = values[0]
                for v in values[1:]:
                    out = out + v
            elif reducer == "max":
                out = max(values)
            elif reducer == "min":
                out = min(values)
            elif reducer.startswith("rank:"):
                out = slot[int(reducer.split(":")[1])]
            else:
                raise ValueError(f"unknown reducer {reducer}")
            self._done[key] = out
            del self._slots[key]
            return True, out


_local = threading.local()


def _rendezvous(name: str = "train_collective"):
    ctx = get_context()
    handle = getattr(_local, "rdv", None)
    if handle is None:
        full_name = f"{name}_{ctx.get_experiment_name()}"
        try:
            handle = ray_tpu.get_actor(full_name)
        except Exception:
            cls = ray_tpu.remote(_Rendezvous)
            try:
                handle = cls.options(name=full_name,
                                     get_if_exists=True,
                                     max_concurrency=64).remote(
                    ctx.get_world_size())
            except Exception:
                handle = ray_tpu.get_actor(full_name)
        _local.rdv = handle
    return handle


def _collective(op: str, value: Any, reducer: str,
                timeout: float = 120.0) -> Any:
    ctx = get_context()
    seq = getattr(_local, "seq", {})
    _local.seq = seq
    seq[op] = seq.get(op, 0) + 1
    key = f"{op}:{seq[op]}"
    rdv = _rendezvous()
    ray_tpu.get(rdv.contribute.remote(key, ctx.get_world_rank(), value))
    deadline = time.time() + timeout
    while time.time() < deadline:
        ready, result = ray_tpu.get(rdv.poll.remote(key, reducer))
        if ready:
            return result
        time.sleep(0.005)
    raise TimeoutError(f"collective {key} timed out "
                       f"({ctx.get_world_size()} ranks expected)")


def barrier(timeout: float = 120.0) -> None:
    """All ranks block until every rank arrives."""
    _collective("barrier", None, "list", timeout)


def broadcast_from_rank_zero(value: Any = None,
                             timeout: float = 120.0) -> Any:
    """Rank 0's value is returned on every rank."""
    return _collective("broadcast", value, "rank:0", timeout)


def allreduce(value: Any, op: str = "sum", timeout: float = 120.0) -> Any:
    """Reduce a (numeric / numpy) value across ranks."""
    return _collective("allreduce", value, op, timeout)


def allgather(value: Any, timeout: float = 120.0) -> List[Any]:
    """Every rank receives the rank-ordered list of contributions."""
    return _collective("allgather", value, "list", timeout)
