"""SPMD train step: the TPU-native replacement for DDP/FSDP wrappers.

Reference capability: Train v1 wraps torch DDP/FSDP (`train/torch/
train_loop_utils.py`, `train/torch/config.py:66` init_process_group). Here
sharded data parallelism IS the compiler's job: params get NamedShardings
from logical axes, batches shard over (dp, fsdp), and jit emits the
all-reduce / reduce-scatter / all-gather over ICI.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec


@dataclasses.dataclass
class TrainStep:
    """A compiled sharded train step plus its companion state tools."""

    step_fn: Callable          # (params, opt_state, batch) -> (p, o, metrics)
    init_fn: Callable          # (rng) -> (params, opt_state) [sharded]
    mesh: Any
    param_shardings: Any
    batch_sharding: Any


def make_train_step(model, optimizer: Optional[optax.GradientTransformation]
                    = None, mesh=None, *, donate: bool = True,
                    batch_axes=("dp", "fsdp")) -> TrainStep:
    """Build a jitted sharded train step for a model exposing
    ``init(rng)``, ``loss(params, *batch)`` and (optionally)
    ``param_shardings()``.

    With ``mesh=None`` runs single-device (bench path on one real chip).
    """
    if optimizer is None:
        optimizer = optax.adamw(3e-4, weight_decay=0.1)

    if mesh is not None and hasattr(model, "param_shardings"):
        p_sh = model.param_shardings()
        batch_sh = NamedSharding(mesh, PartitionSpec(batch_axes))
    else:
        p_sh = batch_sh = None

    def init_fn(rng):
        params = model.init(rng)
        opt_state = optimizer.init(params)
        return params, opt_state

    def loss_fn(params, batch):
        return model.loss(params, *batch)

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(updates=grads,
                                              state=opt_state,
                                              params=params)
        params = optax.apply_updates(params, updates)
        gnorm = optax.global_norm(grads)
        return params, opt_state, {"loss": loss, "grad_norm": gnorm}

    if mesh is not None and p_sh is not None:
        # jit the whole init with sharded out_shardings so every leaf is
        # CREATED already sharded — a model that needs fsdp/tp sharding
        # must never materialize unsharded on one device.
        def sharded_init(rng):
            shapes = jax.eval_shape(init_fn, rng)
            o_sh = _mirror_shardings(shapes[1], shapes[0], p_sh, mesh)
            return jax.jit(init_fn, out_shardings=(p_sh, o_sh))(rng)

        step_fn = jax.jit(step, donate_argnums=(0, 1) if donate else ())
        return TrainStep(step_fn=step_fn, init_fn=sharded_init, mesh=mesh,
                         param_shardings=p_sh, batch_sharding=batch_sh)

    step_fn = jax.jit(step, donate_argnums=(0, 1) if donate else ())
    return TrainStep(step_fn=step_fn, init_fn=init_fn, mesh=None,
                     param_shardings=None, batch_sharding=None)


def _mirror_shardings(opt_state, params, p_sh, mesh):
    """Give optimizer-state leaves the sharding of the param they mirror
    (same shape) or replicate them."""
    repl = NamedSharding(mesh, PartitionSpec())
    shape_to_sh = {}
    for p_leaf, sh in zip(jax.tree.leaves(params), jax.tree.leaves(p_sh)):
        shape_to_sh.setdefault(p_leaf.shape, sh)

    def pick(leaf):
        if hasattr(leaf, "shape") and leaf.shape in shape_to_sh:
            return shape_to_sh[leaf.shape]
        return repl
    return jax.tree.map(pick, opt_state)


def shard_batch(batch, train_step: TrainStep):
    """Place a host batch onto the mesh with (dp, fsdp) batch sharding."""
    if train_step.batch_sharding is None:
        return jax.device_put(batch)
    return jax.tree.map(
        lambda x: jax.device_put(x, train_step.batch_sharding), batch)
