"""Checkpoints: directory handles + jax-pytree (de)serialization + top-K
retention.

Reference: `python/ray/train/_checkpoint.py:56` (Checkpoint as a directory
on a fs URI, from_directory/to_directory :179,:190) and
`train/_internal/checkpoint_manager.py` (top-K by score). TPU-native
addition: first-class pytree save/restore — params arrive sharded
(jax.Array over a mesh); saving gathers to host per-leaf, restoring
re-places onto the target sharding without a full-replica host copy per
device.
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np


class Checkpoint:
    """A handle to a checkpoint directory."""

    # async-save state (set by AsyncCheckpointer.save)
    _pending = None
    _pending_error: Optional[BaseException] = None

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    def result(self, timeout: Optional[float] = None) -> "Checkpoint":
        """Wait for a pending async write; raises its error if it
        failed. Synchronous checkpoints return immediately."""
        if self._pending is not None:
            if not self._pending.wait(timeout):
                raise TimeoutError(
                    f"checkpoint write to {self.path} still pending")
            if self._pending_error is not None:
                raise self._pending_error
        return self

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def to_directory(self, path: Optional[str] = None) -> str:
        if path is None or os.path.abspath(path) == self.path:
            return self.path
        os.makedirs(path, exist_ok=True)
        shutil.copytree(self.path, path, dirs_exist_ok=True)
        return path

    def __repr__(self):
        return f"Checkpoint({self.path})"

    # -- pytree payloads ----------------------------------------------------
    @staticmethod
    def _gather_to_host(tree: Any):
        """Device->host copy (the only part that must block the train
        step — after it, params may be donated/mutated freely)."""
        import jax

        leaves, treedef = jax.tree.flatten(tree)
        arrays = {}
        scalars: Dict[str, Any] = {}
        dtypes: Dict[str, str] = {}
        for i, leaf in enumerate(leaves):
            if hasattr(leaf, "shape"):
                # jax.device_get gathers sharded arrays to host once.
                arr = np.asarray(jax.device_get(leaf))
                # np.savez silently stores ml_dtypes leaves (bfloat16/fp8,
                # the common TPU dtypes) as raw void — record the dtype
                # name + shape and save raw bytes, re-viewing on load.
                if arr.dtype.type.__module__ != "numpy":
                    dtypes[f"a{i}"] = (arr.dtype.name, arr.shape)
                    arr = np.frombuffer(arr.tobytes(), np.uint8)
                arrays[f"a{i}"] = arr
            else:
                scalars[f"a{i}"] = leaf
        return arrays, {"treedef": treedef, "scalars": scalars,
                        "dtypes": dtypes, "n_leaves": len(leaves)}

    @staticmethod
    def _write(path: str, arrays, meta) -> None:
        os.makedirs(path, exist_ok=True)
        np.savez(os.path.join(path, "leaves.npz"), **arrays)
        with open(os.path.join(path, "treedef.pkl"), "wb") as f:
            pickle.dump(meta, f)

    @staticmethod
    def from_pytree(tree: Any, path: Optional[str] = None) -> "Checkpoint":
        """Save a jax/np pytree (params, opt state, ...) to a directory."""
        path = path or tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
        arrays, meta = Checkpoint._gather_to_host(tree)
        Checkpoint._write(path, arrays, meta)
        return Checkpoint(path)

    def to_pytree(self, shardings: Any = None) -> Any:
        """Restore; with ``shardings`` (matching pytree of NamedSharding)
        leaves are placed sharded directly. A pending async write is
        joined first — never reads half-written files."""
        import jax

        self.result()

        with open(os.path.join(self.path, "treedef.pkl"), "rb") as f:
            meta = pickle.load(f)
        data = np.load(os.path.join(self.path, "leaves.npz"))
        dtypes = meta.get("dtypes", {})
        leaves: List[Any] = []
        for i in range(meta["n_leaves"]):
            key = f"a{i}"
            if key in meta["scalars"]:
                leaves.append(meta["scalars"][key])
            elif key in dtypes:
                name, shape = dtypes[key]
                leaves.append(np.frombuffer(
                    data[key].tobytes(), np.dtype(name)).reshape(shape))
            else:
                leaves.append(data[key])
        tree = jax.tree.unflatten(meta["treedef"], leaves)
        if shardings is not None:
            tree = jax.tree.map(
                lambda x, s: jax.device_put(x, s) if hasattr(x, "shape")
                else x, tree, shardings)
        return tree


class CheckpointManager:
    """Top-K checkpoint retention with score-based eviction."""

    def __init__(self, root: str, num_to_keep: Optional[int] = None,
                 score_attribute: Optional[str] = None,
                 score_order: str = "max"):
        self.root = root
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.score_order = score_order
        os.makedirs(root, exist_ok=True)
        self._entries: List[Tuple[float, str, Dict]] = []
        self._counter = 0

    def register(self, checkpoint: Checkpoint,
                 metrics: Optional[Dict] = None) -> str:
        """Copy a checkpoint under management; returns the managed path."""
        metrics = metrics or {}
        self._counter += 1
        dest = os.path.join(self.root, f"checkpoint_{self._counter:06d}")
        checkpoint.to_directory(dest)
        with open(os.path.join(dest, "_metrics.json"), "w") as f:
            json.dump({k: v for k, v in metrics.items()
                       if isinstance(v, (int, float, str))}, f)
        score = self._score(metrics)
        self._entries.append((score, dest, metrics))
        self._evict()
        return dest

    def _score(self, metrics: Dict) -> float:
        if self.score_attribute and self.score_attribute in metrics:
            val = float(metrics[self.score_attribute])
            return val if self.score_order == "max" else -val
        return float(self._counter)  # FIFO: newest kept

    def _evict(self) -> None:
        # Entries stay in registration order (latest_checkpoint() relies
        # on it); the victim is selected with min(), not by sorting.
        if self.num_to_keep is None:
            return
        while len(self._entries) > self.num_to_keep:
            victim = min(self._entries, key=lambda e: e[0])
            self._entries.remove(victim)
            shutil.rmtree(victim[1], ignore_errors=True)

    def best_checkpoint(self) -> Optional[Checkpoint]:
        if not self._entries:
            return None
        return Checkpoint(max(self._entries, key=lambda e: e[0])[1])

    def latest_checkpoint(self) -> Optional[Checkpoint]:
        if not self._entries:
            return None
        return Checkpoint(self._entries[-1][1])

    @staticmethod
    def find_latest(root: str) -> Optional[Checkpoint]:
        """Resume support: newest checkpoint dir under ``root``."""
        if not os.path.isdir(root):
            return None
        dirs = sorted(d for d in os.listdir(root)
                      if d.startswith("checkpoint_"))
        return Checkpoint(os.path.join(root, dirs[-1])) if dirs else None


class AsyncCheckpointer:
    """Async checkpoint saves (reference capability: ray.train's
    orbax-style async checkpointing / `AsyncCheckpointer`): ``save``
    blocks ONLY for the device->host gather — the params may be donated
    to the next step immediately — while serialization + disk IO run on
    a background writer thread. ``wait_until_finished`` joins pending
    writes (call before shutdown or before trusting the files); errors
    surface there and on the returned checkpoint's ``result()``.
    """

    def __init__(self, max_pending: int = 2):
        import queue as _queue
        import threading as _threading

        self._q: "_queue.Queue" = _queue.Queue(maxsize=max_pending)
        self._errors: list = []
        # pending counter under one lock — no Event/empty() TOCTOU:
        # wait_until_finished must never vouch for an unwritten ckpt
        self._cond = _threading.Condition()
        self._pending_count = 0

        def writer():
            while True:
                item = self._q.get()
                if item is None:
                    return
                ckpt, arrays, meta = item
                try:
                    Checkpoint._write(ckpt.path, arrays, meta)
                except BaseException as e:  # noqa: BLE001 — surfaced
                    ckpt._pending_error = e
                    with self._cond:
                        self._errors.append(e)
                finally:
                    ckpt._pending.set()
                    with self._cond:
                        self._pending_count -= 1
                        self._cond.notify_all()

        self._thread = _threading.Thread(target=writer, daemon=True,
                                         name="async-ckpt-writer")
        self._thread.start()

    def save(self, tree, path: Optional[str] = None) -> Checkpoint:
        """Gather to host synchronously, enqueue the write, return the
        (pending) checkpoint handle immediately."""
        import tempfile as _tempfile
        import threading as _threading

        path = path or _tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
        arrays, meta = Checkpoint._gather_to_host(tree)
        ckpt = Checkpoint(path)
        ckpt._pending = _threading.Event()
        with self._cond:
            self._pending_count += 1
        self._q.put((ckpt, arrays, meta))
        return ckpt

    def wait_until_finished(self, timeout: Optional[float] = None) -> None:
        """Join all writes enqueued so far; raises the FIRST error since
        the last call (then clears it — a later successful save is not
        poisoned by an old failure)."""
        with self._cond:
            if not self._cond.wait_for(
                    lambda: self._pending_count == 0, timeout):
                raise TimeoutError(
                    "async checkpoint writes still pending")
            if self._errors:
                err = self._errors[0]
                self._errors.clear()
                raise err

    def close(self) -> None:
        self._q.put(None)
        self._thread.join(timeout=10)
