"""Run callbacks: logger + experiment-tracking integrations.

Reference capability: ``ray.air`` integration callbacks
(``air/integrations/{wandb,mlflow,comet}.py``) and Tune's logger
callbacks (``tune/logger/{json,csv,tensorboardx}.py``) — hooks invoked
on run start / every report / checkpoint / run end. The tracking
libraries are not in this image, so those adapters import-guard with an
actionable error; the file-based loggers are fully functional.

Attach via ``RunConfig(callbacks=[...])`` — honored by JaxTrainer and
(per-trial) by Tune.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional


class Callback:
    """Hook interface (reference: ray.tune.Callback shape, run-scoped)."""

    def on_run_start(self, run_name: str,
                     config: Optional[Dict[str, Any]] = None) -> None:
        pass

    def on_report(self, metrics: Dict[str, Any], iteration: int,
                  rank: int = 0, trial_id: str = "") -> None:
        pass

    def on_checkpoint(self, checkpoint: Any, iteration: int) -> None:
        pass

    def on_run_end(self, result: Any = None,
                   error: Optional[str] = None) -> None:
        pass


class JsonLoggerCallback(Callback):
    """Append every report to ``<dir>/result.json`` (JSON lines;
    reference: ``tune/logger/json.py``)."""

    def __init__(self, log_dir: str):
        self.log_dir = log_dir
        self._f = None

    def on_run_start(self, run_name, config=None):
        os.makedirs(self.log_dir, exist_ok=True)
        self._f = open(os.path.join(self.log_dir, "result.json"), "a")
        if config:
            with open(os.path.join(self.log_dir, "params.json"),
                      "w") as pf:
                json.dump(config, pf, default=str)

    def on_report(self, metrics, iteration, rank=0, trial_id=""):
        if self._f is None:
            return
        record = {"iteration": iteration, "rank": rank,
                  "timestamp": time.time(), **metrics}
        if trial_id:
            record["trial_id"] = trial_id
        self._f.write(json.dumps(record, default=str) + "\n")
        self._f.flush()

    def on_run_end(self, result=None, error=None):
        if self._f is not None:
            self._f.close()
            self._f = None


class CSVLoggerCallback(Callback):
    """``<dir>/progress.csv`` (reference: ``tune/logger/csv.py``).
    Columns fixed by the first report; later extra keys are dropped.
    stdlib csv handles quoting; the header is written only when the
    file is empty (append mode across runs stays parseable)."""

    def __init__(self, log_dir: str):
        self.log_dir = log_dir
        self._f = None
        self._writer = None
        self._columns: Optional[List[str]] = None

    def on_run_start(self, run_name, config=None):
        os.makedirs(self.log_dir, exist_ok=True)
        self._f = open(os.path.join(self.log_dir, "progress.csv"),
                       "a", newline="")

    def on_report(self, metrics, iteration, rank=0, trial_id=""):
        if self._f is None:
            return
        import csv

        row = {"iteration": iteration, **metrics}
        if self._writer is None:
            self._columns = list(row)
            self._writer = csv.DictWriter(
                self._f, fieldnames=self._columns, extrasaction="ignore")
            if self._f.tell() == 0:
                self._writer.writeheader()
        self._writer.writerow({c: row.get(c, "") for c in self._columns})
        self._f.flush()

    def on_run_end(self, result=None, error=None):
        if self._f is not None:
            self._f.close()
            self._f = None
            self._writer = None


class WandbLoggerCallback(Callback):
    """Weights & Biases (reference: air/integrations/wandb.py)."""

    def __init__(self, project: str, **init_kwargs):
        try:
            import wandb  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "WandbLoggerCallback requires the `wandb` package, which "
                "is not in this image; use JsonLoggerCallback/"
                "CSVLoggerCallback or install wandb.") from e
        self._project = project
        self._init_kwargs = init_kwargs
        self._run = None

    def on_run_start(self, run_name, config=None):
        import wandb

        self._run = wandb.init(project=self._project, name=run_name,
                               config=config, **self._init_kwargs)

    def on_report(self, metrics, iteration, rank=0, trial_id=""):
        if self._run is not None and rank == 0:
            self._run.log(metrics, step=iteration)

    def on_run_end(self, result=None, error=None):
        if self._run is not None:
            self._run.finish()
            self._run = None


class MLflowLoggerCallback(Callback):
    """MLflow (reference: air/integrations/mlflow.py)."""

    def __init__(self, tracking_uri: Optional[str] = None,
                 experiment_name: str = "ray_tpu"):
        try:
            import mlflow  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "MLflowLoggerCallback requires the `mlflow` package, "
                "which is not in this image; use JsonLoggerCallback/"
                "CSVLoggerCallback or install mlflow.") from e
        self._tracking_uri = tracking_uri
        self._experiment_name = experiment_name

    def on_run_start(self, run_name, config=None):
        import mlflow

        if self._tracking_uri:
            mlflow.set_tracking_uri(self._tracking_uri)
        mlflow.set_experiment(self._experiment_name)
        mlflow.start_run(run_name=run_name)
        if config:
            mlflow.log_params(config)

    def on_report(self, metrics, iteration, rank=0, trial_id=""):
        import mlflow

        if rank == 0:
            mlflow.log_metrics(
                {k: v for k, v in metrics.items()
                 if isinstance(v, (int, float))}, step=iteration)

    def on_run_end(self, result=None, error=None):
        import mlflow

        mlflow.end_run()


def invoke(callbacks, hook: str, *args, **kwargs) -> None:
    """Fire one hook on every callback; a broken callback never takes
    the run down (reference semantics: logging is best-effort)."""
    for cb in callbacks or ():
        try:
            getattr(cb, hook)(*args, **kwargs)
        except Exception:
            pass
