"""Scaling policies: how the trainer sizes each worker-group (re)start.

Reference capability: `python/ray/train/v2/_internal/execution/
scaling_policy/scaling_policy.py` (ScalingPolicy → NoopDecision /
ResizeDecision, with FixedScalingPolicy the default and elastic policies
deciding a new world size after failures). TPU-native shape: the
decision is a plain target WORLD SIZE — re-forming the group at size W
re-forms the device mesh at W hosts, and the SPMD program re-shards its
checkpointed state onto the smaller/larger mesh at restore (the "re-form
a smaller mesh" hard part of SURVEY §7).
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Dict, Optional


@dataclasses.dataclass
class ResizeDecision:
    """Re-form the group at ``num_workers`` (== NoopDecision when it
    matches the current size)."""

    num_workers: int


class ScalingPolicy:
    """Decides the world size for every (re)start of the worker group."""

    def initial_size(self) -> int:
        raise NotImplementedError

    def on_recovery(self, current_size: int,
                    resources_per_worker: Dict[str, float],
                    attempt: int) -> ResizeDecision:
        """Called after a worker-group failure, before the retry."""
        raise NotImplementedError


class FixedScalingPolicy(ScalingPolicy):
    """Always the configured size — a retry waits for the full gang to
    be placeable again (the Train v1 behavior)."""

    def __init__(self, num_workers: int):
        self.num_workers = num_workers

    def initial_size(self) -> int:
        return self.num_workers

    def on_recovery(self, current_size, resources_per_worker, attempt):
        return ResizeDecision(self.num_workers)


class ElasticScalingPolicy(ScalingPolicy):
    """Re-form at the surviving capacity: after a failure, size the next
    group to what the cluster can actually place NOW, clamped to
    [min_workers, max_workers]. Training continues on the survivors from
    the latest checkpoint instead of waiting for replacement hardware.

    ``wait_s``: how long to wait for capacity >= min_workers before
    handing the trainer a group it may still not place. The trainer
    bounds group placement with ScalingConfig.placement_timeout_s
    (elastic default 120s) so an unplaceable group FAILS and counts
    against FailureConfig instead of hanging forever.
    """

    def __init__(self, min_workers: int, max_workers: int,
                 wait_s: float = 10.0, poll_interval_s: float = 0.25,
                 initial_workers: Optional[int] = None):
        if not 1 <= min_workers <= max_workers:
            raise ValueError(
                f"need 1 <= min_workers <= max_workers, got "
                f"[{min_workers}, {max_workers}]")
        if initial_workers is not None and not (
                min_workers <= initial_workers <= max_workers):
            raise ValueError(
                f"initial_workers={initial_workers} outside "
                f"[{min_workers}, {max_workers}]")
        self.min_workers = min_workers
        self.max_workers = max_workers
        self.initial_workers = initial_workers
        self.wait_s = wait_s
        self.poll_interval_s = poll_interval_s

    def initial_size(self) -> int:
        return self.initial_workers or self.max_workers

    def _placeable_workers(self, resources_per_worker) -> int:
        import ray_tpu

        avail = ray_tpu.available_resources()
        fits = math.inf
        for key, per in resources_per_worker.items():
            if per <= 0:
                continue
            fits = min(fits, avail.get(key, 0.0) // per)
        return int(fits) if fits is not math.inf else self.max_workers

    def on_recovery(self, current_size, resources_per_worker, attempt):
        deadline = time.monotonic() + self.wait_s
        while True:
            n = self._placeable_workers(resources_per_worker)
            if n >= self.min_workers or time.monotonic() >= deadline:
                break
            time.sleep(self.poll_interval_s)
        n = max(self.min_workers, min(self.max_workers, n))
        return ResizeDecision(n)


def resolve_policy(scaling_config,
                   policy: Optional[ScalingPolicy]) -> ScalingPolicy:
    """Explicit policy wins; ``ScalingConfig(elastic=(min, max))``
    builds an elastic one (starting at num_workers when it falls in the
    range, else at max); otherwise fixed at num_workers."""
    if policy is not None:
        return policy
    elastic = getattr(scaling_config, "elastic", None)
    if elastic:
        lo, hi = elastic
        n = scaling_config.num_workers
        # num_workers=1 is the dataclass default, i.e. "unset" — an
        # elastic run then starts at max. An explicit initial size of 1
        # is still expressible via ElasticScalingPolicy(initial_workers=1).
        explicit = n != 1 and lo <= n <= hi
        return ElasticScalingPolicy(
            lo, hi, initial_workers=n if explicit else None)
    return FixedScalingPolicy(scaling_config.num_workers)
