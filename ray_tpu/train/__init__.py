"""ray_tpu.train — distributed training orchestration, TPU-native.

Reference: Ray Train (`python/ray/train`, SURVEY.md §2.2) — TorchTrainer /
worker-group actors / NCCL process groups. Here the unit of distributed
work is a jitted SPMD program over a named mesh: the worker group exists
for *host* orchestration (data ingest, checkpoints, elasticity), while
gradient communication is XLA collectives over ICI, not NCCL.
"""

from ray_tpu.train.spmd import TrainStep, make_train_step

__all__ = ["TrainStep", "make_train_step"]
