"""ray_tpu.train — distributed training orchestration, TPU-native.

Reference: Ray Train (`python/ray/train`, SURVEY.md §2.2) — TorchTrainer /
worker-group actors / NCCL process groups. Here the unit of distributed
work is a jitted SPMD program over a named mesh: the worker group exists
for *host* orchestration (data ingest, checkpoints, elasticity), while
gradient communication is XLA collectives over ICI, not NCCL.
"""

from ray_tpu.train.checkpoint import (AsyncCheckpointer, Checkpoint,
                                      CheckpointManager)
from ray_tpu.train.config import (CheckpointConfig, FailureConfig, RunConfig,
                                  ScalingConfig)
from ray_tpu.train.scaling_policy import (ElasticScalingPolicy,
                                          FixedScalingPolicy,
                                          ResizeDecision, ScalingPolicy)
from ray_tpu.train.session import (get_checkpoint, get_context,
                                   get_dataset_shard, report)
from ray_tpu.train.spmd import TrainStep, make_train_step, shard_batch
from ray_tpu.train.trainer import JaxTrainer, Result

__all__ = [
    "TrainStep", "make_train_step", "shard_batch",
    "Checkpoint", "CheckpointManager", "AsyncCheckpointer",
    "ScalingConfig", "RunConfig", "FailureConfig", "CheckpointConfig",
    "report", "get_context", "get_checkpoint", "get_dataset_shard",
    "JaxTrainer", "Result",
    "ScalingPolicy", "FixedScalingPolicy", "ElasticScalingPolicy",
    "ResizeDecision",
]
