"""Admission control: submit-time verdicts and cluster federation.

Every driver submit gets a verdict against the fair-share ledger:

- ``ADMITTED`` — within caps, flows straight to dispatch;
- ``QUEUED``   — over a hard cap (or decision path degraded): the task
  still enters the node backlog but the dispatch-side quota gate holds
  it until the job's own completions free headroom — over-cap work is
  delayed, never lost;
- ``REJECTED`` — the job's bounded pending queue
  (``admission_queue_max``) is full: surfaces as
  :class:`ray_tpu.exceptions.AdmissionRejectedError` in the submitting
  driver — the backpressure signal.

Failpoint seams: ``admission.verdict`` (drop ⇒ decision lost, fail
OPEN to admitted; error ⇒ decision path failed, degrade to QUEUED) and
``tenancy.quota_sync`` (drop/error ⇒ this federation tick is skipped,
records stay dirty and retry next tick).

The manager owns the driver-side view; when a head is attached the
quota records persist there (``--state-path``) and per-job accounting
federates via the resource-reporter tick.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from ray_tpu._private import failpoints as _fp
from ray_tpu._private.config import cfg
from ray_tpu._private.lock_sanitizer import tracked_lock
from ray_tpu.exceptions import AdmissionRejectedError
from ray_tpu.tenancy.policy import FairShareLedger
from ray_tpu.tenancy.quota import QUOTA_RESOURCES, JobQuota
from ray_tpu.util.metrics import Counter, Gauge

ADMITTED = "admitted"
QUEUED = "queued"
REJECTED = "rejected"

#: pre-built counter tags — admit() runs per submit; building a dict
#: per call shows up in drain-rate profiles
_VERDICT_TAGS = {v: {"verdict": v} for v in (ADMITTED, QUEUED, REJECTED)}

#: gauge refresh + usage federation are throttled to this period.
_REFRESH_S = 0.2
_REPORT_S = 1.0

_admission_total = Counter(
    "ray_tpu_admission_total",
    "admission verdicts by outcome", ("verdict",))
_job_running = Gauge(
    "ray_tpu_job_running_tasks",
    "tasks currently executing per job", ("job_id",))
_job_queued = Gauge(
    "ray_tpu_job_queued_tasks",
    "tasks held in node backlogs per job", ("job_id",))
_job_quota = Gauge(
    "ray_tpu_job_quota_bytes",
    "configured hard quota caps per job and resource axis",
    ("job_id", "resource"))


class TenancyManager:
    """Driver-side tenancy authority: ledger + verdicts + federation."""

    def __init__(self, runtime: Any = None,
                 enabled: Optional[bool] = None,
                 capacity_fn=None,
                 default_weight: Optional[float] = None,
                 queue_max: Optional[int] = None) -> None:
        conf = cfg()
        self.enabled = (bool(conf.fairshare)
                        if enabled is None else bool(enabled))
        self.queue_max = int(conf.admission_queue_max
                             if queue_max is None else queue_max)
        self._runtime = runtime
        if capacity_fn is None and runtime is not None:
            capacity_fn = runtime.cluster_resources
        self.ledger = FairShareLedger(
            capacity_fn or (lambda: {}),
            default_weight=float(conf.job_default_weight
                                 if default_weight is None
                                 else default_weight))
        self._lock = tracked_lock("tenancy.manager", reentrant=False)
        #: guarded by self._lock — per-job OVER-CAP submits awaiting
        #: dispatch (the REJECTED bound; admitted flow never counts)
        self._pending: Dict[str, int] = {}
        #: guarded by self._lock — per-job demand submitted but not yet
        #: dispatched. The submit-time verdict folds this in so a BURST
        #: of submits sees its own outstanding demand: usage alone made
        #: the QUEUED verdict a race against the dispatch pass (the
        #: async core coalesces dispatch wakes, so a tight submit loop
        #: can finish before the first task is ever marked running).
        self._inflight: Dict[str, Dict[str, float]] = {}
        #: guarded by self._lock — quota/weight records awaiting head sync
        self._dirty: Dict[str, Dict[str, Any]] = {}
        #: guarded by self._lock
        self._records: Dict[str, Dict[str, Any]] = {}
        #: guarded by self._lock — live object attribution (oid hex ->
        #: (job, nbytes)) so frees debit the job that put the object
        self._objects: Dict[str, Any] = {}
        #: guarded by self._lock
        self._gauges_at = 0.0
        #: guarded by self._lock
        self._reported_at = 0.0

    # ------------------------------------------------------------------
    # job records / quotas
    # ------------------------------------------------------------------
    def ensure_job(self, job: str, weight: Optional[float] = None,
                   name: Optional[str] = None) -> None:
        from ray_tpu.tenancy.context import canonical_job
        job, derived = canonical_job(job)
        name = name if name is not None else derived
        self.ledger.ensure(job, weight=weight)
        if weight is not None or name is not None:
            with self._lock:
                rec = self._records.setdefault(job, {})
                if weight is not None:
                    rec["weight"] = float(weight)
                if name is not None:
                    rec["name"] = name
                self._dirty[job] = dict(rec)

    def set_quota(self, job: str,
                  hard: Optional[Dict[str, float]] = None,
                  soft: Optional[Dict[str, float]] = None,
                  weight: Optional[float] = None) -> None:
        from ray_tpu.tenancy.context import canonical_job
        job, name = canonical_job(job)
        quota = JobQuota(hard=hard or {}, soft=soft or {})
        self.ledger.set_quota(job, quota)
        if weight is not None:
            self.ledger.set_weight(job, weight)
        for res in QUOTA_RESOURCES:
            cap = quota.hard_cap(res)
            if cap is not None:
                _job_quota.set(cap, tags={"job_id": job, "resource": res})
            else:
                _job_quota.remove(tags={"job_id": job, "resource": res})
        with self._lock:
            rec = self._records.setdefault(job, {})
            rec["quota"] = quota.to_wire()
            if weight is not None:
                rec["weight"] = float(weight)
            if name is not None:
                rec["name"] = name
            self._dirty[job] = dict(rec)

    def adopt_record(self, job: str, rec: Dict[str, Any]) -> None:
        """Apply a record pulled from the head (no re-dirty)."""
        quota = JobQuota.from_wire(rec.get("quota"))
        self.ledger.set_quota(job, quota)
        if rec.get("weight") is not None:
            self.ledger.set_weight(job, float(rec["weight"]))
        with self._lock:
            self._records[job] = dict(rec)

    # ------------------------------------------------------------------
    # submit-time verdict
    # ------------------------------------------------------------------
    def admit(self, spec: Any) -> str:
        """Verdict for one submit. Raises AdmissionRejectedError on
        REJECTED; otherwise the spec proceeds into scheduling (the
        dispatch-side gate enforces QUEUED)."""
        job = spec.job_id.hex() if spec.job_id is not None else ""
        verdict = ADMITTED
        demand = spec.resources
        flight = self._inflight.get(job)  # raylint: disable=guarded-by
        if flight:
            # this task ON TOP OF the job's own not-yet-dispatched
            # submits — deterministic under a burst, dispatcher-timing
            # independent (lock-free peek; a stale read only shades
            # the advisory verdict, never correctness)
            demand = dict(demand)
            for res, v in flight.items():
                demand[res] = demand.get(res, 0.0) + v
        if self.ledger.over_hard_cap(job, demand):
            verdict = QUEUED
        if _fp.ENABLED:
            try:
                act = _fp.fire("admission.verdict", job=job,
                               verdict=verdict)
                if act is _fp.DROP:
                    verdict = ADMITTED   # decision lost: fail open
            except Exception:
                verdict = QUEUED         # decision path failed: degrade
        if verdict != ADMITTED:
            # only over-cap work counts against the bounded pending
            # queue — the ADMITTED fast path stays lock-free
            with self._lock:
                pending = self._pending.get(job, 0)
                if pending >= self.queue_max:
                    verdict = REJECTED
                else:
                    self._pending[job] = pending + 1
        if verdict != REJECTED and self.ledger.any_caps():
            # the submit's demand counts as in flight until dispatch
            # marks it running (note_admitted). Only paid once a quota
            # exists somewhere — quota-free clusters keep the lock-free
            # submit path.
            with self._lock:
                flight = self._inflight.setdefault(job, {})
                for res, v in spec.resources.items():
                    flight[res] = flight.get(res, 0.0) + float(v)
        _admission_total.inc(tags=_VERDICT_TAGS[verdict])
        if verdict == REJECTED:
            raise AdmissionRejectedError(
                f"job {job or '<driver>'}: admission queue full "
                f"({self.queue_max} pending tasks over quota); "
                f"retry after completions free capacity")
        return verdict

    # ------------------------------------------------------------------
    # dispatch hooks (called by Node)
    # ------------------------------------------------------------------
    def prefers_spread(self, job: str) -> bool:
        """Placement consult for ``ClusterScheduler.pick_node``: a job
        at a hard cap or over a soft cap spreads its queued work across
        nodes instead of packing, so per-node quota gates free
        uniformly and one node's backlog never pins the job."""
        return (self.ledger.at_hard_cap(job)
                or self.ledger.over_soft_cap(job))

    def order_buckets(self, items: List[Any]) -> List[Any]:
        # single-tenant fast path: with one job present the deficit
        # ordering is the identity — skip the ledger round-trip the
        # dispatch loop would otherwise pay every round
        first = None
        for (job, _key), _n in items:
            if first is None:
                first = job
            elif job != first:
                return self.ledger.order(items)
        return [k for k, _n in items]

    def admit_cap(self, job: str, demand: Dict[str, float],
                  want: int) -> int:
        return self.ledger.admit_cap(job, demand, want)

    def note_admitted(self, job: str, demand: Dict[str, float],
                      n: int) -> None:
        self.ledger.note_admitted(job, demand, n)
        # only over-cap (QUEUED) submits increment _pending, but a
        # dispatched group can mix previously-queued and admitted
        # tasks, so the decrement floors at 0 — the bound errs toward
        # fewer rejections, never spurious ones. Lock-free peek keeps
        # the common no-backlog drain path out of the lock.
        if self._pending.get(job, 0) > 0:  # raylint: disable=guarded-by
            with self._lock:
                left = self._pending.get(job, 0) - n
                self._pending[job] = left if left > 0 else 0
        if self._inflight.get(job):  # raylint: disable=guarded-by
            # retire the dispatched demand from the inflight view. A
            # dispatched group can mix resource shapes, so per-resource
            # floors at zero — over-subtraction CORRECTS leaks (tasks
            # cancelled before dispatch) rather than compounding them.
            with self._lock:
                flight = self._inflight.get(job)
                if flight:
                    for res, v in demand.items():
                        left = flight.get(res, 0.0) - float(v) * n
                        if left > 1e-9:
                            flight[res] = left
                        else:
                            flight.pop(res, None)
                    if not flight:
                        self._inflight.pop(job, None)
        self._refresh_gauges()

    def note_done(self, job: str, resources: Dict[str, float]) -> None:
        self.ledger.note_done(job, resources)

    def note_object_bytes(self, job: str, delta: float) -> None:
        self.ledger.note_object_bytes(job, delta)

    def note_put(self, oid_hex: str, job: str, nbytes: int) -> None:
        with self._lock:
            self._objects[oid_hex] = (job, int(nbytes))
        self.ledger.note_object_bytes(job, nbytes)

    def note_free(self, oid_hex: str) -> None:
        with self._lock:
            entry = self._objects.pop(oid_hex, None)
        if entry is not None:
            self.ledger.note_object_bytes(entry[0], -entry[1])

    def observe_queued(self, node: str, counts: Dict[str, int]) -> None:
        self.ledger.observe_queued(node, counts)
        self._refresh_gauges()

    def _refresh_gauges(self) -> None:
        now = time.monotonic()
        # lock-free throttle peek: this runs per dispatch round; the
        # stale-read race only delays one refresh by a round
        if now - self._gauges_at < _REFRESH_S:  # raylint: disable=guarded-by
            return
        with self._lock:
            if now - self._gauges_at < _REFRESH_S:
                return
            self._gauges_at = now
        snap = self.ledger.snapshot()
        for job, row in snap.items():
            tags = {"job_id": job or "<driver>"}
            _job_running.set(float(row["running"]), tags=tags)
            _job_queued.set(float(row["queued"]), tags=tags)
        with self._lock:
            # reconcile the pending bound: specs that died before
            # dispatch (cancel, unschedulable) never hit note_admitted
            # and would otherwise leak counts. Only a FULLY idle job is
            # reset — reconciling against observed backlog depth while
            # work is in flight would race submits mid-bucketing and
            # deflate the rejection bound.
            for job, row in snap.items():
                if int(row["queued"]) == 0 and int(row["running"]) == 0:
                    if self._pending.get(job, 0) > 0:
                        self._pending[job] = 0
                    self._inflight.pop(job, None)

    # ------------------------------------------------------------------
    # views / federation
    # ------------------------------------------------------------------
    def jobs_view(self) -> Dict[str, Dict[str, Any]]:
        snap = self.ledger.snapshot()
        with self._lock:
            for job, row in snap.items():
                row["pending"] = self._pending.get(job, 0)
                rec = self._records.get(job)
                if rec and rec.get("name"):
                    row["name"] = rec["name"]
        return snap

    def maybe_sync(self, backend: Any) -> None:
        """Federation tick (piggybacks the resource reporter): push
        dirty quota records to the head (persisted) and to daemons that
        advertised the ``tenancy`` hello capability, then report usage.
        All RPCs run outside the manager lock."""
        head = getattr(backend, "head", None)
        if head is None:
            return
        now = time.monotonic()
        with self._lock:
            dirty = dict(self._dirty)
            report_due = now - self._reported_at >= _REPORT_S
            if report_due:
                self._reported_at = now
        if not dirty and not report_due:
            return
        if _fp.ENABLED:
            try:
                if _fp.fire("tenancy.quota_sync",
                            dirty=len(dirty)) is _fp.DROP:
                    return   # records stay dirty; retried next tick
            except Exception:
                return
        try:
            for job, rec in dirty.items():
                head.tenancy_set(job, rec)
            if report_due:
                head.tenancy_report(self.jobs_view())
            if dirty or (report_due and self.ledger.any_caps()):
                table = {}
                with self._lock:
                    table = {j: dict(r)
                             for j, r in self._records.items()}
                # over-quota jobs ride along so node memory monitors
                # can point OOM preemption at them first (pressure.py
                # TenantAwarePolicy — only meaningful once caps exist)
                over = [j for j in table if self.ledger.at_hard_cap(j)]
                for handle in getattr(backend, "daemons", {}).values():
                    if getattr(handle, "_tenancy_supported", False):
                        handle.client.call("tenancy_sync", jobs=table,
                                           over_quota=over)
        except Exception:
            return   # still dirty; retried next tick
        with self._lock:
            for job in dirty:
                if self._dirty.get(job) == dirty[job]:
                    del self._dirty[job]

    def load_from_head(self, head: Any) -> None:
        """Adopt quota records persisted at the head (other drivers or
        a previous incarnation may have set them)."""
        try:
            records = head.tenancy_get() or {}
        except Exception:
            return
        for job, rec in records.items():
            if isinstance(rec, dict) and (rec.get("quota")
                                          or rec.get("weight")):
                self.adopt_record(job, rec)
