"""Multi-tenant fair share: per-job quotas, DRF admission, deficit dispatch.

The cluster runs many jobs in one pool; this package arbitrates it
(the GCS-side role of the reference architecture). Three layers:

- :mod:`ray_tpu.tenancy.quota` — per-job hard/soft caps over
  {CPU, TPU, memory, object_store_bytes};
- :mod:`ray_tpu.tenancy.policy` — the fair-share ledger: weighted
  dominant-resource shares (DRF) plus deficit accounting, so node
  dispatch admits whole same-shape task groups in deficit order
  (batch-DAG scheduling per arXiv 2002.07062) instead of FIFO;
- :mod:`ray_tpu.tenancy.admission` — submit-time verdicts
  (ADMITTED / QUEUED / REJECTED), bounded per-job pending queues with
  backpressure to the submitting driver, and head/daemon federation.

Everything is gated on the ``fairshare`` config flag; with it off the
dispatch hot path is untouched (``Node.tenancy`` stays ``None``).
"""

from ray_tpu.tenancy.admission import (ADMITTED, QUEUED, REJECTED,
                                       TenancyManager)
from ray_tpu.tenancy.context import current_job_id, job_context
from ray_tpu.tenancy.policy import FairShareLedger
from ray_tpu.tenancy.quota import QUOTA_RESOURCES, JobQuota

__all__ = [
    "ADMITTED", "QUEUED", "REJECTED", "TenancyManager",
    "current_job_id", "job_context",
    "FairShareLedger", "JobQuota", "QUOTA_RESOURCES",
]
