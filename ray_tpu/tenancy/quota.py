"""Per-job quotas: hard/soft caps over the schedulable resource axes.

A *hard* cap is enforced at dispatch: a job at its cap has further
tasks held in the node backlog (verdict QUEUED) until its own releases
free headroom. A *soft* cap only demotes the job's placement (spread
instead of pack) and its deficit priority — work still runs when the
cluster is idle. ``object_store_bytes`` is accounted driver-side at
``put()`` time and checked at admission rather than at dispatch (the
dispatch ledger deals in task resource vectors, not object payloads).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

#: the quota axes; "memory" and "object_store_bytes" are byte counts,
#: CPU/TPU are slot counts (same units as TaskSpec.resources).
QUOTA_RESOURCES = ("CPU", "TPU", "memory", "object_store_bytes")


def _clean(caps: Optional[Dict[str, float]]) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for key, val in (caps or {}).items():
        if key not in QUOTA_RESOURCES:
            raise ValueError(
                f"unknown quota resource {key!r}; "
                f"expected one of {QUOTA_RESOURCES}")
        out[key] = float(val)
    return out


@dataclass
class JobQuota:
    """Caps for one job. Missing keys mean unlimited."""

    hard: Dict[str, float] = field(default_factory=dict)
    soft: Dict[str, float] = field(default_factory=dict)

    def __post_init__(self) -> None:
        self.hard = _clean(self.hard)
        self.soft = _clean(self.soft)

    def hard_cap(self, resource: str) -> Optional[float]:
        return self.hard.get(resource)

    def soft_cap(self, resource: str) -> Optional[float]:
        return self.soft.get(resource)

    def to_wire(self) -> Dict[str, Any]:
        return {"hard": dict(self.hard), "soft": dict(self.soft)}

    @classmethod
    def from_wire(cls, wire: Optional[Dict[str, Any]]) -> "JobQuota":
        wire = wire or {}
        return cls(hard=dict(wire.get("hard") or {}),
                   soft=dict(wire.get("soft") or {}))
