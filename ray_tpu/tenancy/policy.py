"""Fair-share ledger: weighted DRF shares plus deficit accounting.

Policy (reference: DRF, Ghodsi et al., adapted to batch dispatch per
arXiv 2002.07062): each job j has a weight w_j; its *dominant share*
is max_r usage_j[r] / capacity[r]. Node dispatch asks the ledger to
order the ready same-shape task groups; each ordering round every job
with pending work accrues a deficit quantum proportional to its weight
share, and admitting n tasks of demand d spends
``n * dominant_cost(d)`` of that deficit. Groups are then admitted
whole, highest deficit first — a light job's small groups cut ahead of
a saturating job's backlog without preempting anything, and a job's
same-shape batch is never interleaved task-at-a-time.

Hard quota caps clamp how many tasks of a group may admit
(:meth:`FairShareLedger.admit_cap`); clamped groups stay in the node
backlog (verdict semantics: QUEUED, not lost).

Thread model: dispatch loops (one per node), the driver submit path,
and the federation ticker all call in. All state is guarded by one
non-reentrant lock; the per-task completion path stays lock-free by
appending to ``_done_log`` (a GIL-atomic list append) which is folded
into usage at the next locked entry point.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from ray_tpu._private.lock_sanitizer import tracked_lock
from ray_tpu.tenancy.quota import JobQuota

#: deficit credit granted per ordering round, split by weight share.
QUANTUM = 1.0
#: deficit is clamped to ±CAP quanta so an idle-then-bursty job cannot
#: bank unbounded credit (and a greedy one cannot dig an endless hole).
DEFICIT_CAP = 4.0
#: seconds the cluster-capacity callable result is cached.
_CAPACITY_TTL_S = 2.0
_EPS = 1e-9


class _JobShare:
    """Ledger row for one job (all fields guarded by the ledger lock)."""

    __slots__ = ("weight", "usage", "running", "deficit", "queued",
                 "object_bytes", "quota")

    def __init__(self, weight: float) -> None:
        self.weight = weight
        self.usage: Dict[str, float] = {}
        self.running = 0
        self.deficit = 0.0
        self.queued = 0
        self.object_bytes = 0.0
        self.quota = JobQuota()


class FairShareLedger:
    """Weighted-DRF usage accounting with deficit-ordered admission."""

    def __init__(self,
                 capacity: "Callable[[], Dict[str, float]] | Dict[str, float]",
                 default_weight: float = 1.0) -> None:
        self._capacity_fn = (capacity if callable(capacity)
                             else (lambda: capacity))
        self._default_weight = max(float(default_weight), _EPS)
        self._lock = tracked_lock("tenancy.ledger", reentrant=False)
        #: guarded by self._lock
        self._jobs: Dict[str, _JobShare] = {}
        #: guarded by self._lock
        self._queued_by_node: Dict[str, Dict[str, int]] = {}
        #: guarded by self._lock
        self._capacity: Dict[str, float] = {}
        #: guarded by self._lock
        self._capacity_at = 0.0
        # completion log: appended WITHOUT the lock (list.append is
        # GIL-atomic), folded into usage under the lock. Keeps the
        # per-task drain path at one append instead of a lock acquire.
        self._done_log: List[Tuple[str, Dict[str, float]]] = []
        # lock-free fast-path flag: False until ANY job declares a hard
        # or soft cap. Quota checks read it before taking the lock so a
        # fairshare-on cluster with no quotas configured pays no lock
        # traffic per submit/dispatch (a set_quota racing a check takes
        # effect on the next check — same staleness as losing the lock
        # race). Written only under self._lock.
        self._any_caps = False

    # ------------------------------------------------------------------
    # registration / configuration
    # ------------------------------------------------------------------
    def ensure(self, job: str, weight: Optional[float] = None) -> None:
        with self._lock:
            self._ensure_locked(job, weight)

    def set_weight(self, job: str, weight: float) -> None:
        with self._lock:
            self._ensure_locked(job).weight = max(float(weight), _EPS)

    def set_quota(self, job: str, quota: JobQuota) -> None:
        with self._lock:
            self._ensure_locked(job).quota = quota
            self._any_caps = any(
                s.quota.hard or s.quota.soft
                for s in self._jobs.values())

    def get_quota(self, job: str) -> JobQuota:
        with self._lock:
            return self._ensure_locked(job).quota

    def get_weight(self, job: str) -> float:
        with self._lock:
            return self._ensure_locked(job).weight

    def _ensure_locked(self, job: str,
                       weight: Optional[float] = None) -> _JobShare:
        # caller holds self._lock (lexical check can't see through the
        # _locked-suffix convention)
        share = self._jobs.get(job)      # raylint: disable=guarded-by
        if share is None:
            share = _JobShare(self._default_weight)
            self._jobs[job] = share      # raylint: disable=guarded-by
        if weight is not None:
            share.weight = max(float(weight), _EPS)
        return share

    # ------------------------------------------------------------------
    # DRF math
    # ------------------------------------------------------------------
    def _capacity_locked(self) -> Dict[str, float]:
        # caller holds self._lock
        now = time.monotonic()
        stale = now - self._capacity_at > _CAPACITY_TTL_S  # raylint: disable=guarded-by
        if stale or not self._capacity:  # raylint: disable=guarded-by
            try:
                self._capacity = dict(self._capacity_fn() or {})  # raylint: disable=guarded-by
            except Exception:
                self._capacity = self._capacity or {}  # raylint: disable=guarded-by
            self._capacity_at = now    # raylint: disable=guarded-by
        return self._capacity          # raylint: disable=guarded-by

    def _dominant_cost_locked(self, demand: Dict[str, float]) -> float:
        cap = self._capacity_locked()
        cost = 0.0
        for res, need in demand.items():
            total = cap.get(res, 0.0)
            if total > _EPS and need > 0:
                cost = max(cost, need / total)
        # a demand entirely off the capacity map still costs something,
        # or deficits would never be spent and ordering would freeze
        return cost if cost > _EPS else _EPS

    def _dominant_share_locked(self, share: _JobShare) -> float:
        cap = self._capacity_locked()
        dom = 0.0
        for res, used in share.usage.items():
            total = cap.get(res, 0.0)
            if total > _EPS and used > 0:
                dom = max(dom, used / total)
        return dom

    def dominant_cost(self, demand: Dict[str, float]) -> float:
        with self._lock:
            return self._dominant_cost_locked(demand)

    def dominant_share(self, job: str) -> float:
        with self._lock:
            self._fold_done_locked()
            return self._dominant_share_locked(self._ensure_locked(job))

    # ------------------------------------------------------------------
    # quota checks
    # ------------------------------------------------------------------
    def any_caps(self) -> bool:
        """Lock-free: has ANY job declared a hard/soft cap? Admission
        reads this to skip inflight bookkeeping on quota-free clusters
        (same staleness contract as the quota checks below)."""
        return self._any_caps

    def over_hard_cap(self, job: str, demand: Dict[str, float]) -> bool:
        """Would one more task of ``demand`` put ``job`` over a hard cap?
        Also true while the job's tracked object-store bytes exceed a
        hard ``object_store_bytes`` cap."""
        if not self._any_caps:
            return False
        with self._lock:
            self._fold_done_locked()
            share = self._ensure_locked(job)
            obj_cap = share.quota.hard_cap("object_store_bytes")
            if obj_cap is not None and share.object_bytes > obj_cap + _EPS:
                return True
            for res, need in demand.items():
                cap = share.quota.hard_cap(res)
                if cap is not None and (share.usage.get(res, 0.0) + need
                                        > cap + _EPS):
                    return True
            return False

    def at_hard_cap(self, job: str) -> bool:
        """Is the job's current usage at (or past) any hard cap?"""
        if not self._any_caps:
            return False
        with self._lock:
            self._fold_done_locked()
            share = self._ensure_locked(job)
            obj_cap = share.quota.hard_cap("object_store_bytes")
            if obj_cap is not None and share.object_bytes > obj_cap + _EPS:
                return True
            for res, cap in share.quota.hard.items():
                if res == "object_store_bytes":
                    continue
                if share.usage.get(res, 0.0) >= cap - _EPS:
                    return True
            return False

    def over_soft_cap(self, job: str) -> bool:
        if not self._any_caps:
            return False
        with self._lock:
            self._fold_done_locked()
            share = self._ensure_locked(job)
            obj_cap = share.quota.soft_cap("object_store_bytes")
            if obj_cap is not None and share.object_bytes > obj_cap + _EPS:
                return True
            for res, used in share.usage.items():
                cap = share.quota.soft_cap(res)
                if cap is not None and used > cap + _EPS:
                    return True
            return False

    def admit_cap(self, job: str, demand: Dict[str, float],
                  want: int) -> int:
        """Clamp a same-shape group of ``want`` tasks to the job's hard
        caps given its current usage. 0 means the whole group stays
        queued until the job's own releases free headroom."""
        if want <= 0:
            return 0
        if not self._any_caps:
            return want
        with self._lock:
            self._fold_done_locked()
            share = self._ensure_locked(job)
            obj_cap = share.quota.hard_cap("object_store_bytes")
            if obj_cap is not None and share.object_bytes > obj_cap + _EPS:
                return 0
            allowed = want
            for res, need in demand.items():
                cap = share.quota.hard_cap(res)
                if cap is None or need <= 0:
                    continue
                head = cap - share.usage.get(res, 0.0)
                allowed = min(allowed, int((head + _EPS) // need))
                if allowed <= 0:
                    return 0
            return allowed

    # ------------------------------------------------------------------
    # deficit-ordered admission
    # ------------------------------------------------------------------
    def order(self, items: Iterable[Tuple[Tuple[str, Any], int]]
              ) -> List[Tuple[str, Any]]:
        """Order ready groups for one dispatch round.

        ``items`` is ``[((job, shape_key), n_pending), ...]``. Every job
        present accrues a weight-proportional deficit quantum, then keys
        come back sorted highest deficit first (ties: lowest weighted
        dominant share, then job id; FIFO order is preserved within a
        job — Python's sort is stable).
        """
        items = list(items)
        if not items:
            return []
        pending: Dict[str, int] = {}
        for (job, _shape), n in items:
            pending[job] = pending.get(job, 0) + max(int(n), 0)
        with self._lock:
            self._fold_done_locked()
            total_w = 0.0
            for job in pending:
                total_w += self._ensure_locked(job).weight
            prio: Dict[str, Tuple[float, float, str]] = {}
            for job in pending:
                share = self._jobs[job]
                share.deficit = min(
                    DEFICIT_CAP,
                    share.deficit + QUANTUM * share.weight / total_w)
                # soft-cap demotion: an over-soft job only runs after
                # every within-soft job's groups were considered
                demote = 1.0 if self._over_soft_locked(share) else 0.0
                prio[job] = (demote, -share.deficit,
                             self._dominant_share_locked(share)
                             / share.weight)
        return [key for key, _n in
                sorted(items, key=lambda kv: prio[kv[0][0]] + (kv[0][0],))]

    def _over_soft_locked(self, share: _JobShare) -> bool:
        obj_cap = share.quota.soft_cap("object_store_bytes")
        if obj_cap is not None and share.object_bytes > obj_cap + _EPS:
            return True
        for res, used in share.usage.items():
            cap = share.quota.soft_cap(res)
            if cap is not None and used > cap + _EPS:
                return True
        return False

    # ------------------------------------------------------------------
    # usage accounting
    # ------------------------------------------------------------------
    def note_admitted(self, job: str, demand: Dict[str, float],
                      n: int) -> None:
        if n <= 0:
            return
        with self._lock:
            share = self._ensure_locked(job)
            for res, need in demand.items():
                share.usage[res] = share.usage.get(res, 0.0) + need * n
            share.running += n
            share.deficit = max(
                -DEFICIT_CAP,
                share.deficit - n * self._dominant_cost_locked(demand))

    def note_done(self, job: str, resources: Dict[str, float]) -> None:
        """Per-task completion; lock-free (folded at next locked call)."""
        self._done_log.append((job, resources))

    def _fold_done_locked(self) -> None:
        # caller holds self._lock
        if not self._done_log:
            return
        log, self._done_log = self._done_log, []
        for job, resources in log:
            share = self._jobs.get(job)  # raylint: disable=guarded-by
            if share is None:
                continue
            for res, need in resources.items():
                left = share.usage.get(res, 0.0) - need
                share.usage[res] = left if left > _EPS else 0.0
            if share.running > 0:
                share.running -= 1
            if share.running == 0 and share.queued == 0:
                # queue-empty deficit forfeit applied here too: nodes
                # skip observe_queued when their backlog counts are
                # unchanged, so the last completion (not the next
                # dispatch round) must land the DRR reset
                share.deficit = 0.0

    def note_object_bytes(self, job: str, delta: float) -> None:
        with self._lock:
            share = self._ensure_locked(job)
            share.object_bytes = max(0.0, share.object_bytes + delta)

    def observe_queued(self, node: str, counts: Dict[str, int]) -> None:
        """One node's per-job backlog depth after a dispatch round. A
        job with nothing queued or running anywhere forfeits its banked
        deficit (standard deficit-round-robin queue-empty reset)."""
        with self._lock:
            self._fold_done_locked()
            self._queued_by_node[node] = dict(counts)
            totals: Dict[str, int] = {}
            for per_node in self._queued_by_node.values():
                for job, n in per_node.items():
                    totals[job] = totals.get(job, 0) + n
            for job, share in self._jobs.items():
                share.queued = totals.get(job, 0)
                if share.queued == 0 and share.running == 0:
                    share.deficit = 0.0

    # ------------------------------------------------------------------
    # views
    # ------------------------------------------------------------------
    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            self._fold_done_locked()
            out: Dict[str, Dict[str, Any]] = {}
            for job, share in self._jobs.items():
                out[job] = {
                    "weight": share.weight,
                    "usage": dict(share.usage),
                    "running": share.running,
                    "queued": share.queued,
                    "object_bytes": share.object_bytes,
                    "deficit": round(share.deficit, 6),
                    "dominant_share": round(
                        self._dominant_share_locked(share), 6),
                    "quota": share.quota.to_wire(),
                }
            return out
