"""Driver-side job context: which tenant owns the work being submitted.

Resolution order for stamping ``TaskSpec.job_id`` at submit time:

1. an explicit :func:`job_context` scope (multi-job drivers — loadgen
   ``--jobs``, the job manager supervisor);
2. the executing task's own ``job_id`` from the runtime task context —
   this is what makes children of an actor task inherit the root job
   instead of falling back to the driver's ambient id;
3. the runtime's ambient ``job_id``.

Contextvars do not cross ``threading.Thread`` boundaries, so thread
pools that submit on behalf of a job must re-enter :func:`job_context`
per call (the loadgen multi-job runner wraps its per-request target).
"""

from __future__ import annotations

import contextlib
import contextvars
from typing import Any, Iterator, Optional

from ray_tpu._private import runtime_context
from ray_tpu._private.ids import JobID

_job_ctx: contextvars.ContextVar = contextvars.ContextVar(
    "ray_tpu_tenancy_job", default=None)


def _coerce(job_id: Any) -> JobID:
    if isinstance(job_id, JobID):
        return job_id
    if isinstance(job_id, bytes):
        return JobID(job_id)
    s = str(job_id)
    try:
        return JobID.from_hex(s)
    except ValueError:
        # human-readable tenant name ("tenant-a", "raysubmit_..."):
        # derive a stable JobID so the same name always maps to the
        # same tenant across drivers and restarts
        import hashlib
        return JobID(hashlib.blake2b(
            s.encode(), digest_size=JobID.SIZE).digest())


def canonical_job(job_id: Any):
    """``(canonical_hex, name)`` for any job designator: JobID / raw
    bytes / hex string pass through (name ``None``); a human-readable
    tenant name hashes to its stable hex and comes back as the name.
    Quota/weight APIs use this so ``set_quota("tenant-a", ...)`` keys
    the same ledger row that submits under ``job_context("tenant-a")``
    are stamped with."""
    jid = _coerce(job_id)
    name = None
    if not isinstance(job_id, (JobID, bytes)):
        s = str(job_id)
        if jid.hex() != s.lower():
            name = s
    return jid.hex(), name


@contextlib.contextmanager
def job_context(job_id: Any, weight: Optional[float] = None,
                runtime: Any = None) -> Iterator[JobID]:
    """Run a ``with`` block as tenant ``job_id``; submits inside stamp
    it. Registers the job (and optional weight) with the runtime's
    tenancy manager when one is active."""
    jid = _coerce(job_id)
    if runtime is None:
        from ray_tpu._private import worker
        runtime = worker.global_runtime()
    ten = getattr(runtime, "tenancy", None)
    if ten is not None:
        name = None
        if not isinstance(job_id, (JobID, bytes)):
            name = str(job_id)
        ten.ensure_job(jid.hex(), weight=weight, name=name)
    token = _job_ctx.set(jid)
    try:
        yield jid
    finally:
        _job_ctx.reset(token)


def current_job_id(runtime: Any = None) -> Optional[JobID]:
    """The job the current code path is acting for (see module doc)."""
    jid = _job_ctx.get()
    if jid is not None:
        return jid
    task_ctx = runtime_context._ctx.get()
    if task_ctx is not None and task_ctx.job_id is not None:
        return task_ctx.job_id
    return getattr(runtime, "job_id", None)
