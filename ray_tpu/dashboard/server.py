"""Dashboard HTTP server.

Endpoints (reference: dashboard modules `node`, `state`, `metrics`,
`job` — SURVEY.md §1 L3):
  GET /api/nodes              cluster nodes + resources
  GET /api/tasks              task table
  GET /api/actors             actor table
  GET /api/placement_groups   placement groups
  GET /api/objects            object table
  GET /api/jobs               per-tenant fair-share state (weights,
                              quotas, usage, deficits) merged with the
                              head's persisted quota records
  GET /api/cluster_status     resources + runtime stats summary
  GET /api/timeline           MERGED chrome-trace JSON: driver, daemon,
                              and worker lanes (head-store spans with
                              clock correction applied)
  GET /api/config             resolved flag table + provenance
  GET /api/profile            cluster-wide stack profile as speedscope
                              JSON (burst fan-out + head aggregates;
                              ?duration=N seconds, clamped to 30)
  GET /api/metrics            cluster-wide metric samples as JSON
  GET /metrics                CLUSTER-WIDE Prometheus exposition: this
                              process's registry merged with every
                              daemon's heartbeat-federated snapshot,
                              node_id-labeled (the metrics-agent role)
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple


class _DashboardHandler(BaseHTTPRequestHandler):
    def log_message(self, *args):
        pass

    def _json(self, payload, code: int = 200) -> None:
        body = json.dumps(payload, default=str).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _text(self, text: str, code: int = 200) -> None:
        body = text.encode()
        self.send_response(code)
        self.send_header("Content-Type", "text/plain; version=0.0.4")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        from ray_tpu._private import worker as _worker
        from ray_tpu.util import state as state_api
        from ray_tpu.util.metrics import (cluster_metrics_json,
                                          cluster_prometheus_text)

        path = self.path.split("?")[0].rstrip("/")
        query = {}
        if "?" in self.path:
            from urllib.parse import parse_qsl
            query = dict(parse_qsl(self.path.split("?", 1)[1]))
        try:
            if path in ("", "/"):
                from ray_tpu.dashboard.ui import INDEX_HTML
                body = INDEX_HTML.encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/html; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif path == "/api/profile":
                # cluster-wide stack profile: burst fan-out to every
                # process + the head's federated continuous aggregates,
                # as a speedscope document (one lane per process)
                out = state_api.cluster_profile(
                    duration_s=min(float(query.get("duration", 2)), 30))
                self._json(out["speedscope"])
            elif path == "/api/profile/cpu":
                from ray_tpu.util.profiling import sample_cpu_profile
                self._json(sample_cpu_profile(
                    duration_s=min(float(query.get("duration", 5)), 30)))
            elif path == "/api/profile/memory":
                from ray_tpu.util.profiling import memory_snapshot
                self._json(memory_snapshot())
            elif path == "/metrics":
                self._text(cluster_prometheus_text())
            elif path == "/api/metrics":
                self._json(cluster_metrics_json())
            elif path == "/api/nodes":
                self._json(state_api.list_nodes())
            elif path == "/api/tasks":
                self._json(state_api.list_tasks())
            elif path == "/api/actors":
                self._json(state_api.list_actors())
            elif path == "/api/placement_groups":
                self._json(state_api.list_placement_groups())
            elif path == "/api/objects":
                self._json(state_api.list_objects())
            elif path == "/api/timeline":
                self._json(state_api.cluster_timeline())
            elif path == "/api/config":
                # the resolved flag table with provenance (the
                # ray_config_def.h surface, observable)
                from ray_tpu._private.config import cfg
                self._json(cfg().describe())
            elif path == "/api/jobs":
                # per-tenant fair-share state: this driver's live
                # ledger view, overlaid with quota/weight records
                # persisted at the head (other drivers' jobs appear
                # through the head federation)
                rt = _worker.global_runtime()
                ten = getattr(rt, "tenancy", None)
                jobs = ten.jobs_view() if ten is not None else {}
                backend = getattr(rt, "cluster_backend", None)
                head = getattr(backend, "head", None)
                if head is not None:
                    try:
                        for job, rec in (head.tenancy_get() or {}).items():
                            row = jobs.setdefault(str(job), {})
                            for k, v in dict(rec).items():
                                row.setdefault(k, v)
                    except Exception:
                        pass  # head unreachable: local view only
                self._json({
                    "fairshare_enabled": bool(
                        ten is not None and ten.enabled),
                    "jobs": jobs,
                })
            elif path == "/api/cluster_status":
                rt = _worker.global_runtime()
                import ray_tpu
                self._json({
                    "cluster_resources": ray_tpu.cluster_resources(),
                    "available_resources": ray_tpu.available_resources(),
                    "stats": dict(rt.stats),
                    "task_summary": state_api.summarize_tasks(),
                })
            elif path == "/api/serve":
                # library observability (reference: dashboard serve
                # module): live application/deployment state. ONLY a
                # missing controller maps to the empty state — a
                # failing controller surfaces as the usual 500.
                from ray_tpu.serve.api import _get_controller
                try:
                    controller = _get_controller(create=False)
                except Exception:
                    self._json({"applications": {}})
                else:
                    import ray_tpu
                    self._json(ray_tpu.get(controller.status.remote(),
                                           timeout=10))
            elif path == "/api/train":
                # train-run lifecycle (reference: dashboard train
                # module over export_train_state.proto): export events
                # when the FLAG enables emission, else a hint — and no
                # side-effectful logger creation when disabled
                from ray_tpu._private.export_events import (
                    export_enabled, get_export_logger)
                enabled = export_enabled()
                events = []
                if enabled:
                    logger = get_export_logger()
                    if logger is not None:
                        events = logger.read("TRAIN_RUN")
                self._json({"train_runs": events,
                            "export_events_enabled": enabled})
            elif path == "/api/data":
                # per-dataset operator metrics (reference: dashboard
                # data module over StatsManager)
                from ray_tpu.data.context import DatasetStats
                self._json({"datasets": DatasetStats.recent()})
            elif path == "/api":
                self._json({"endpoints": [
                    "/api/nodes", "/api/tasks", "/api/actors",
                    "/api/placement_groups", "/api/objects", "/api/jobs",
                    "/api/cluster_status", "/api/timeline", "/api/config",
                    "/api/serve", "/api/train", "/api/data",
                    "/api/profile", "/api/profile/cpu",
                    "/api/profile/memory",
                    "/api/metrics", "/metrics", "/"]})
            else:
                self._json({"error": f"unknown path {path}"}, 404)
        except Exception as e:
            self._json({"error": repr(e)}, 500)


_server: Optional[ThreadingHTTPServer] = None


def start_dashboard(port: int = 8265, host: str = "127.0.0.1"
                    ) -> Tuple[str, int]:
    """Start (or return the running) dashboard; returns (host, port)."""
    global _server
    if _server is not None:
        return _server.server_address
    _server = ThreadingHTTPServer((host, port), _DashboardHandler)
    threading.Thread(target=_server.serve_forever, daemon=True,
                     name="dashboard").start()
    return _server.server_address


def stop_dashboard() -> None:
    global _server
    if _server is not None:
        _server.shutdown()
        _server = None
