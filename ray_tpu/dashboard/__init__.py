"""Dashboard (reference: `dashboard/head.py:48` + modules): REST state
endpoints + Prometheus metrics over a threaded stdlib HTTP server (the
React frontend of the reference is out of scope; the API surface is the
parity target)."""

from ray_tpu.dashboard.server import start_dashboard

__all__ = ["start_dashboard"]
