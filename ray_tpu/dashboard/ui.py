"""Dashboard web UI: one self-contained HTML page over the REST API.

Reference: ``dashboard/client/src`` is a 196-file React app; this build
serves the same operational views (cluster overview, nodes, tasks,
actors, placement groups, live profiling) as a single vanilla-JS page —
no build chain, served straight from the head process.
"""

INDEX_HTML = """<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>ray_tpu dashboard</title>
<style>
  :root { color-scheme: dark; }
  body { font: 13px/1.5 -apple-system, 'Segoe UI', Roboto, sans-serif;
         margin: 0; background: #111417; color: #e6e6e6; }
  header { padding: 10px 20px; background: #1a2026;
           border-bottom: 1px solid #2c343c; display: flex;
           align-items: baseline; gap: 16px; }
  header h1 { font-size: 16px; margin: 0; color: #7dd3fc; }
  nav button { background: none; border: none; color: #9ca3af;
               padding: 6px 10px; cursor: pointer; font-size: 13px; }
  nav button.active { color: #7dd3fc;
                      border-bottom: 2px solid #7dd3fc; }
  main { padding: 16px 20px; }
  table { border-collapse: collapse; width: 100%; margin-top: 8px; }
  th, td { text-align: left; padding: 4px 10px;
           border-bottom: 1px solid #232a31; font-size: 12px; }
  th { color: #9ca3af; font-weight: 500; }
  .cards { display: flex; gap: 14px; flex-wrap: wrap; }
  .card { background: #1a2026; border: 1px solid #2c343c;
          border-radius: 8px; padding: 12px 16px; min-width: 160px; }
  .card .v { font-size: 20px; color: #7dd3fc; }
  .card .k { color: #9ca3af; font-size: 11px;
             text-transform: uppercase; letter-spacing: .05em; }
  pre { background: #0c0f12; padding: 10px; border-radius: 6px;
        overflow: auto; max-height: 480px; font-size: 11px; }
  .ok { color: #4ade80; } .bad { color: #f87171; }
  button.act { background: #1f2937; color: #e6e6e6;
               border: 1px solid #374151; border-radius: 6px;
               padding: 5px 12px; cursor: pointer; }
</style>
</head>
<body>
<header>
  <h1>ray_tpu</h1>
  <nav id="nav"></nav>
  <span id="ts" style="margin-left:auto;color:#6b7280"></span>
</header>
<main id="main">loading…</main>
<script>
const TABS = ["overview","nodes","tasks","actors","placement groups",
              "profiling"];
let tab = "overview";
const $ = (h) => { const d = document.createElement("div");
                   d.innerHTML = h; return d; };
const fmt = (o) => JSON.stringify(o);

function nav() {
  const n = document.getElementById("nav"); n.innerHTML = "";
  for (const t of TABS) {
    const b = document.createElement("button");
    b.textContent = t; if (t === tab) b.className = "active";
    b.onclick = () => { tab = t; render(); };
    n.appendChild(b);
  }
}

async function j(path) { return (await fetch(path)).json(); }

function table(rows, cols) {
  if (!rows || !rows.length) return "<p style='color:#6b7280'>none</p>";
  let h = "<table><tr>" + cols.map(c=>`<th>${c}</th>`).join("") + "</tr>";
  for (const r of rows)
    h += "<tr>" + cols.map(c=>`<td>${
      typeof r[c]==="object" ? fmt(r[c]) : (r[c] ?? "")}</td>`).join("")
      + "</tr>";
  return h + "</table>";
}

async function render() {
  nav();
  const m = document.getElementById("main");
  document.getElementById("ts").textContent =
      new Date().toLocaleTimeString();
  try {
    if (tab === "overview") {
      const s = await j("/api/cluster_status");
      const card = (k,v) =>
        `<div class="card"><div class="v">${v}</div>` +
        `<div class="k">${k}</div></div>`;
      m.innerHTML = "<div class='cards'>"
        + card("cluster CPUs", s.cluster_resources.CPU ?? 0)
        + card("available CPUs", s.available_resources.CPU ?? 0)
        + card("cluster TPUs", s.cluster_resources.TPU ?? 0)
        + card("tasks finished", s.stats.tasks_finished)
        + card("tasks retried", s.stats.tasks_retried)
        + card("actor restarts", s.stats.actor_restarts)
        + "</div><h3>task summary</h3><pre>"
        + JSON.stringify(s.task_summary, null, 2) + "</pre>";
    } else if (tab === "nodes") {
      m.innerHTML = table(await j("/api/nodes"),
        ["node_id","alive","resources","available"]);
    } else if (tab === "tasks") {
      const t = await j("/api/tasks");
      m.innerHTML = table(t.slice(-200).reverse(),
        ["task_id","name","state","node_id"]);
    } else if (tab === "actors") {
      m.innerHTML = table(await j("/api/actors"),
        ["actor_id","class_name","state","name","num_restarts"]);
    } else if (tab === "placement groups") {
      m.innerHTML = table(await j("/api/placement_groups"),
        ["placement_group_id","name","strategy","state","bundles"]);
    } else if (tab === "profiling") {
      m.innerHTML = `
        <button class="act" id="cpu">sample CPU (3s)</button>
        <button class="act" id="mem">memory snapshot</button>
        <pre id="out">pick one…</pre>`;
      document.getElementById("cpu").onclick = async () => {
        document.getElementById("out").textContent = "sampling 3s…";
        const p = await j("/api/profile/cpu?duration=3");
        document.getElementById("out").textContent =
          `samples: ${p.samples}\\n\\nTOP FRAMES\\n` +
          p.top.map(t=>`${String(t.pct).padStart(5)}%  ${t.frame}`)
               .join("\\n") +
          "\\n\\nCOLLAPSED STACKS (flamegraph format)\\n" +
          p.collapsed.slice(0, 80).join("\\n");
      };
      document.getElementById("mem").onclick = async () => {
        const p = await j("/api/profile/memory");
        document.getElementById("out").textContent =
          JSON.stringify(p, null, 2);
      };
      return; // no auto-refresh while profiling
    }
  } catch (e) {
    m.innerHTML = `<p class="bad">dashboard error: ${e}</p>`;
  }
}
render();
setInterval(() => { if (tab !== "profiling") render(); }, 3000);
</script>
</body>
</html>
"""
