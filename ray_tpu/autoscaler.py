"""Autoscaler v1: the LEGACY monitor loop — superseded by
:mod:`ray_tpu.autoscaler_v2`.

Use ``autoscaler_v2.Reconciler`` for anything new: it is the real
implementation (GCS-state reconciler, instance state machine, TPU
slice-typed node catalog, provider seam), mirroring the reference's v2
rewrite. This module stays only as the thin v1-shaped surface
(StandardAutoscaler/LoadMetrics/NodeProvider names) for parity with
`autoscaler/_private/autoscaler.py` and for the fake-provider test
fixture (`autoscaler/_private/fake_multi_node/node_provider.py` role).

TPU-first note: a real TPU provider allocates whole ICI slices (a node
type = a slice topology), so `node_resources` carries `TPU` counts and
the bin-packing stays shape-aware via resource dims.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Dict, List, Optional


class NodeProvider:
    """Minimal provider interface (create/terminate/list)."""

    def create_node(self) -> Any:
        raise NotImplementedError

    def terminate_node(self, node) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[Any]:
        raise NotImplementedError


class FakeNodeProvider(NodeProvider):
    """Adds/removes virtual nodes in the running runtime (test fixture)."""

    def __init__(self, runtime, node_resources: Dict[str, float],
                 object_store_memory: int = 256 * 1024 * 1024):
        self.runtime = runtime
        self.node_resources = dict(node_resources)
        self.object_store_memory = object_store_memory
        self._created: List[Any] = []

    def create_node(self):
        node = self.runtime.add_node(dict(self.node_resources),
                                     object_store_memory=
                                     self.object_store_memory)
        self._created.append(node)
        return node

    def terminate_node(self, node) -> None:
        if node in self._created:
            self._created.remove(node)
        self.runtime.remove_node(node)

    def non_terminated_nodes(self):
        return [n for n in self._created if n.alive]


class StandardAutoscaler:
    """Demand-driven reconciler over a NodeProvider."""

    def __init__(self, runtime, provider: NodeProvider, *,
                 min_nodes: int = 0, max_nodes: int = 8,
                 idle_timeout_s: float = 5.0,
                 upscaling_speed: int = 2):
        self.runtime = runtime
        self.provider = provider
        self.min_nodes = min_nodes
        self.max_nodes = max_nodes
        self.idle_timeout_s = idle_timeout_s
        self.upscaling_speed = upscaling_speed
        self._idle_since: Dict[Any, float] = {}
        self.stats = {"launched": 0, "terminated": 0, "updates": 0}

    # -- load metrics ----------------------------------------------------
    def pending_demand(self) -> Dict[str, float]:
        """Unserved resource demand (queued tasks + pending PG bundles)."""
        demand: Dict[str, float] = {}
        for node in self.runtime.nodes():
            with node._pending_lock:
                for k, v in node._pending_demand.items():
                    if k.startswith("_pg_"):
                        k = k.split("_", 4)[-1]  # unscope bundle resources
                    demand[k] = demand.get(k, 0.0) + v
        for pg in list(getattr(self.runtime.pg_manager, "_pending", [])):
            for bundle in pg.bundles:
                for k, v in bundle.resources.items():
                    demand[k] = demand.get(k, 0.0) + v
        return demand

    def available(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for node in self.runtime.nodes():
            if not node.alive:
                continue
            for k, v in node.ledger.available().items():
                if k.startswith("_pg_"):
                    continue
                out[k] = out.get(k, 0.0) + v
        return out

    # -- reconcile -------------------------------------------------------
    def update(self) -> None:
        self.stats["updates"] += 1
        demand = self.pending_demand()
        avail = self.available()
        unmet = {k: v - avail.get(k, 0.0) for k, v in demand.items()
                 if v > avail.get(k, 0.0) + 1e-9}
        managed = self.provider.non_terminated_nodes()
        total_nodes = sum(1 for n in self.runtime.nodes() if n.alive)

        if unmet and total_nodes < self.max_nodes:
            # bin-pack: nodes needed to cover the biggest unmet dimension
            per_node = getattr(self.provider, "node_resources", {})
            need = 1
            for k, miss in unmet.items():
                if per_node.get(k, 0.0) > 0:
                    need = max(need, math.ceil(miss / per_node[k]))
            need = min(need, self.upscaling_speed,
                       self.max_nodes - total_nodes)
            for _ in range(max(need, 0)):
                self.provider.create_node()
                self.stats["launched"] += 1
            return

        # scale down idle managed nodes
        now = time.time()
        for node in managed:
            if self._is_idle(node):
                since = self._idle_since.setdefault(node, now)
                if (now - since >= self.idle_timeout_s
                        and total_nodes > self.min_nodes
                        and len(managed) > 0):
                    self.provider.terminate_node(node)
                    self._idle_since.pop(node, None)
                    self.stats["terminated"] += 1
                    total_nodes -= 1
            else:
                self._idle_since.pop(node, None)

    def _is_idle(self, node) -> bool:
        with node._running_lock:
            running = len(node._running)
        with node._pending_lock:
            pending = sum(node._pending_demand.values())
        return running == 0 and pending == 0 and not node.actors

    # -- monitor loop ----------------------------------------------------
    def start(self, interval_s: float = 1.0) -> threading.Event:
        stop = threading.Event()

        def loop():
            while not stop.wait(interval_s):
                try:
                    self.update()
                except Exception:
                    pass

        threading.Thread(target=loop, daemon=True,
                         name="autoscaler").start()
        return stop
