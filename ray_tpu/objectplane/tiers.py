"""The explicit object-store tier model and its observability.

Every stored entry carries one of three tiers (SNIPPETS.md target: the
TPU-HBM tier extension of the plasma host store):

- ``host-shm``   — host bytes, ideally parked in the node's C++ shm
  arena (zero-copy for every process on the node);
- ``device-hbm`` — ``jax.Array`` pytrees resident in accelerator HBM;
  never serialized through host memory on the local path;
- ``spilled``    — pressure-evicted to disk, restored on demand.

Tier occupancy is observable as ``ray_tpu_object_store_bytes{tier}``
(gauge, per process — federated cluster-wide by the head) and the
zero-copy hit rate as ``ray_tpu_object_zero_copy_gets_total``
(docs/observability.md).
"""

from __future__ import annotations

from typing import Dict

TIER_HOST = "host-shm"
TIER_DEVICE = "device-hbm"
TIER_SPILLED = "spilled"

TIERS = (TIER_HOST, TIER_DEVICE, TIER_SPILLED)


def _store_bytes_gauge():
    from ray_tpu.util.metrics import Gauge
    return Gauge("ray_tpu_object_store_bytes",
                 "object store occupancy by tier (bytes)",
                 tag_keys=("tier",))


def count_zero_copy_get(n: int = 1) -> None:
    """One consumer resolved an object as a view backed by the shared
    arena — no payload serialization, no payload round trip."""
    try:
        from ray_tpu.util.metrics import Counter
        Counter("ray_tpu_object_zero_copy_gets_total",
                "object gets served as zero-copy arena views").inc(n)
    except Exception:
        pass    # metrics must never fail the data path


def count_grants_reclaimed(n: int, reason: str) -> None:
    """Crash reclamation dropped ``n`` external slot refs a dead client
    never released — ``reason`` says which death signal fired (worker
    pipe EOF = ``death``, RPC connection close = ``disconnect``, the
    heartbeat orphan sweep = ``sweep``)."""
    try:
        from ray_tpu.util.metrics import Counter
        Counter("ray_tpu_arena_grants_reclaimed_total",
                "external arena slot refs reclaimed from dead clients",
                tag_keys=("reason",)).inc(n, tags={"reason": reason})
    except Exception:
        pass    # metrics must never fail the data path


def count_spilled_bytes(n: int) -> None:
    """The daemon spilled ``n`` bytes of cold, sealed, unpinned arena
    entries to disk under memory pressure (tier host-shm -> spilled)."""
    try:
        from ray_tpu.util.metrics import Counter
        Counter("ray_tpu_arena_spilled_bytes_total",
                "host-shm arena bytes spilled to disk under memory "
                "pressure").inc(n)
    except Exception:
        pass    # metrics must never fail the data path


def count_restored_bytes(n: int) -> None:
    """A read path restored ``n`` spilled bytes back into the arena
    (tier spilled -> host-shm)."""
    try:
        from ray_tpu.util.metrics import Counter
        Counter("ray_tpu_arena_restored_bytes_total",
                "spilled arena bytes restored into the arena on "
                "demand").inc(n)
    except Exception:
        pass    # metrics must never fail the data path


def count_stale_reservations(n: int = 1) -> None:
    """The orphan sweep aborted ``n`` direct-put reservations whose
    writer died between reserve and seal (bytes un-stranded)."""
    try:
        from ray_tpu.util.metrics import Counter
        Counter("ray_tpu_arena_stale_reservations_total",
                "reserved-but-never-sealed arena entries aborted by "
                "the TTL sweep").inc(n)
    except Exception:
        pass    # metrics must never fail the data path


def raw_put_eligible(value):
    """(dtype_str, shape) when ``value`` qualifies for the RAW tier on
    a direct put, else None — THE single eligibility predicate, shared
    by the worker and driver put paths so the gate can never diverge.
    Raw rides direct puts, so the size gate is
    max(raw_tier_min_bytes, direct_put_min_bytes) (see config.py)."""
    import numpy as np

    from ray_tpu._private.config import cfg
    if (not isinstance(value, np.ndarray) or value.dtype == object
            or not value.flags.c_contiguous
            or value.nbytes < max(int(cfg().direct_put_min_bytes),
                                  int(cfg().raw_tier_min_bytes))):
        return None
    return (value.dtype.str, tuple(value.shape))


def publish_tier_bytes(tier: str, value: int) -> None:
    """Set one tier's occupancy gauge directly (stores that already
    track their own byte counts, e.g. the daemon object table)."""
    try:
        _store_bytes_gauge().set(float(max(value, 0)),
                                 tags={"tier": tier})
    except Exception:
        pass    # metrics must never fail the data path


class TierAccounting:
    """(tier -> bytes) occupancy ledger. Per-store instances chain
    deltas into the process-wide aggregate (``process_tiers()``), which
    is the one that mirrors into the
    ``ray_tpu_object_store_bytes{tier}`` gauge — several stores in one
    process (one per virtual node) must not fight over the series."""

    def __init__(self, publish: bool = False, chain=None):
        from ray_tpu._private.lock_sanitizer import tracked_lock
        self._lock = tracked_lock("objectplane.tiers", reentrant=False)
        self._bytes: Dict[str, int] = {}    #: guarded by self._lock
        self._publish_gauge = publish
        self._chain = chain

    def add(self, tier: str, nbytes: int) -> None:
        with self._lock:
            self._bytes[tier] = self._bytes.get(tier, 0) + nbytes
            value = max(self._bytes[tier], 0)
        if self._publish_gauge:
            publish_tier_bytes(tier, value)
        if self._chain is not None:
            self._chain.add(tier, nbytes)

    def move(self, src: str, dst: str, nbytes: int) -> None:
        """Tier transition (e.g. host-shm -> spilled on pressure)."""
        self.add(src, -nbytes)
        self.add(dst, nbytes)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._bytes)

    def clear(self) -> None:
        """Zero this ledger, backing the deltas out of the chain too
        (store close must not leave phantom occupancy behind)."""
        with self._lock:
            drained = dict(self._bytes)
            self._bytes.clear()
        for tier, value in drained.items():
            if self._publish_gauge:
                publish_tier_bytes(tier, 0)
            if self._chain is not None and value:
                self._chain.add(tier, -value)


_PROCESS_TIERS = TierAccounting(publish=True)


def process_tiers() -> TierAccounting:
    """The process-wide tier aggregate (feeds the gauge)."""
    return _PROCESS_TIERS


def store_accounting() -> TierAccounting:
    """A per-store ledger chained into the process aggregate."""
    return TierAccounting(chain=_PROCESS_TIERS)
