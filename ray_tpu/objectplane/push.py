"""PushManager: proactive daemon-to-daemon object transfer.

Reference capability: ``object_manager.cc:354 Push`` + ``push_manager.h``
— the peer of the pull engine (``daemon.PullManager``). A push moves a
hot object to a node that is ABOUT to need it (dep prefetch at dispatch,
drain migration) instead of waiting for that node to pull.

Dedup rules (the tentpole contract):

- **in-flight dedupe** — a second push of the same (object, destination)
  joins the running transfer instead of re-sending bytes;
- **directory dedupe** — never push to a node that already holds a copy
  per the owner's object directory (``locate_fn``), and probe the
  receiver's table before the first chunk;
- **pull dedupe** — the receiver answers ``have`` as soon as the object
  lands (e.g. a concurrent pull completed it); the sender aborts the
  remaining chunks — a chunk a pull already transferred is never pushed.

Chunks are read straight from the sender's arena
(``ObjectTable.read_range`` — a pinned zero-copy view per chunk, no
intermediate whole-object copy) and assembled receiver-side by
:class:`PushReceiver` into one buffer, exactly like the pull path.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Dict, Optional, Tuple

from ray_tpu._private import failpoints as _fp


def _push_chunk_size() -> int:
    from ray_tpu._private.config import cfg
    return cfg().pull_chunk     # one transfer granularity for both engines


class _Push:
    __slots__ = ("oid", "to_addr", "ref", "raw", "event", "ok",
                 "skipped", "error")

    def __init__(self, oid: bytes, to_addr: Tuple[str, int], ref: bytes):
        self.oid = oid
        self.to_addr = to_addr
        self.ref = ref          # logical ObjectID (receiver oid-index)
        self.raw = None         # raw-tier (dtype, shape), sender-filled
        self.event = threading.Event()
        self.ok = False
        self.skipped = False    # destination already held a copy
        self.error = ""


class PushManager:
    """Sender-side push engine for one daemon."""

    def __init__(self, objects, peer_fn, locate_fn=None,
                 chunk: Optional[int] = None, num_workers: int = 2):
        self.objects = objects
        self._peer = peer_fn            # addr -> rpc.Client
        self._locate = locate_fn        # oid -> [addr] holding a copy
        self.chunk = chunk if chunk is not None else _push_chunk_size()
        self._cv = threading.Condition()
        self._q: deque = deque()                    #: guarded by self._cv
        # (oid, addr) -> _Push: in-flight dedupe table
        self._inflight: Dict[Tuple[bytes, Tuple[str, int]], _Push] = {}  #: guarded by self._cv
        self.stats = {"pushes_started": 0, "pushes_deduped": 0,
                      "pushes_skipped_held": 0, "pushes_failed": 0,
                      "pushes_aborted_by_pull": 0,
                      "chunks_pushed": 0, "bytes_pushed": 0}
        for i in range(num_workers):
            threading.Thread(target=self._loop, daemon=True,
                             name=f"push-worker-{i}").start()

    def inflight_count(self) -> int:
        """Pushes currently queued or transferring (dedupe-table size)."""
        with self._cv:
            return len(self._inflight)

    def request(self, oid: bytes, to_addr, ref: bytes = b"") -> _Push:
        """Enqueue (or join) a push; callers may wait on the returned
        event or fire-and-forget."""
        to_addr = tuple(to_addr)
        key = (oid, to_addr)
        with self._cv:
            existing = self._inflight.get(key)
            if existing is not None:
                self.stats["pushes_deduped"] += 1
                return existing
            push = _Push(oid, to_addr, ref)
            self._inflight[key] = push
            self.stats["pushes_started"] += 1
            self._q.append(push)
            self._cv.notify()
        return push

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._q:
                    self._cv.wait()
                push = self._q.popleft()
            try:
                self._transfer(push)
                push.ok = True
            except Exception as e:  # noqa: BLE001 — reported to waiter
                push.error = repr(e)
                with self._cv:
                    self.stats["pushes_failed"] += 1
            finally:
                with self._cv:
                    self._inflight.pop((push.oid, push.to_addr), None)
                push.event.set()

    def _transfer(self, push: _Push) -> None:
        if _fp.ENABLED:
            # error arm fails this push attempt (the object still
            # travels on demand via the pull path); delay arm
            # stretches the transfer window
            _fp.fire("daemon.push_transfer")
        size = self.objects.nbytes_of(push.oid)
        if size is None:
            raise KeyError(f"push source lost {push.oid!r}")
        # directory dedupe: the owner's object directory already lists
        # the destination as a holder -> nothing to do
        if self._locate is not None:
            try:
                holders = {tuple(a) for a in self._locate(push.oid)}
            except Exception:
                holders = set()
            if push.to_addr in holders:
                push.skipped = True
                with self._cv:
                    self.stats["pushes_skipped_held"] += 1
                return
        peer = self._peer(push.to_addr)
        # receiver probe: a copy that landed outside the directory's
        # view (e.g. a just-finished pull) also dedupes
        meta = peer.call("object_meta", oid=push.oid, timeout=30.0)
        if not meta.get("missing"):
            push.skipped = True
            with self._cv:
                self.stats["pushes_skipped_held"] += 1
            return
        # raw-tier (dtype, shape) travels with the chunks so the
        # receiver's oid index serves the pushed copy as zero-copy
        # views, not as bytes that look like a pickle
        raw_for = getattr(self.objects, "raw_for", None)
        push.raw = raw_for(push.oid) if raw_for is not None else None
        for off in range(0, size, self.chunk):
            want = min(self.chunk, size - off)
            blob = self.objects.read_range(push.oid, off, want)
            if blob is None:    # evicted mid-push
                raise KeyError(f"push source evicted {push.oid!r}")
            out = peer.call("push_chunk", oid=push.oid, off=off,
                            total=size, blob=blob,
                            ref=push.ref,
                            raw=(list(push.raw) if push.raw else None),
                            timeout=60.0)
            with self._cv:
                self.stats["chunks_pushed"] += 1
                self.stats["bytes_pushed"] += len(blob)
            if out.get("have"):
                # the receiver got a copy some other way (a pull landed
                # it): never push a chunk a pull already transferred
                with self._cv:
                    self.stats["pushes_aborted_by_pull"] += 1
                return


class PushReceiver:
    """Receiver-side chunk assembly (the ``object_buffer_pool`` role for
    the push direction): chunks land in one preallocated buffer; the
    completed object enters the local table like a pulled one."""

    # partially received buffers older than this are abandoned
    # transfers (sender crashed mid-push) and get swept
    PENDING_MAX_AGE_S = 120.0

    def __init__(self, objects, register_oid=None):
        from ray_tpu._private.lock_sanitizer import tracked_lock
        self.objects = objects
        self._register_oid = register_oid
        self._lock = tracked_lock("objectplane.push_rx", reentrant=False)
        # oid -> [bytearray, {offset: nbytes}, total, last_touch]:
        # covered-INTERVAL accounting — concurrent senders (even with
        # different chunk sizes) must not sum overlapping chunks past
        # `total` and land a buffer with holes
        self._pending: Dict[bytes, list] = {}   #: guarded by self._lock
        self.stats = {"chunks_received": 0, "objects_received": 0,
                      "dropped_duplicate": 0, "pending_expired": 0}

    @staticmethod
    def _covered(ranges: Dict[int, int]) -> int:
        """Total bytes covered by the union of (offset, len) ranges."""
        covered = 0
        end = -1
        for off in sorted(ranges):
            stop = off + ranges[off]
            if off > end:
                covered += stop - off
                end = stop
            elif stop > end:
                covered += stop - end
                end = stop
        return covered

    def chunk(self, oid: bytes, off: int, total: int, blob: bytes,
              ref: bytes = b"", raw=None) -> Dict[str, Any]:
        import time as _time
        if self.objects.contains(oid):
            # a pull (or an earlier push) already landed it: tell the
            # sender to stop pushing chunks
            with self._lock:
                self._pending.pop(oid, None)
                self.stats["dropped_duplicate"] += 1
            return {"ok": True, "have": True}
        done = False
        with self._lock:
            entry = self._pending.get(oid)
            if entry is None or entry[2] != total:
                entry = self._pending[oid] = [bytearray(total), {},
                                              total, 0.0]
            buf, ranges, _, _ = entry
            buf[off:off + len(blob)] = blob
            ranges[off] = max(ranges.get(off, 0), len(blob))
            entry[3] = _time.monotonic()
            self.stats["chunks_received"] += 1
            if self._covered(ranges) >= total:
                done = True
                self._pending.pop(oid, None)
        if done:
            self.objects.put(oid, bytes(buf))
            if ref and self._register_oid is not None:
                try:
                    self._register_oid(ref, oid,
                                       raw=tuple(raw) if raw else None)
                except Exception:
                    pass
            with self._lock:
                self.stats["objects_received"] += 1
        return {"ok": True}

    def sweep(self, max_age_s: float = PENDING_MAX_AGE_S) -> int:
        """Drop partial buffers no chunk has touched for ``max_age_s``
        (an abandoned transfer — its sender crashed or gave up): a 1GB
        object abandoned after chunk one must not hold receiver RAM
        forever. Called from the daemon heartbeat loop."""
        import time as _time
        cutoff = _time.monotonic() - max_age_s
        with self._lock:
            stale = [oid for oid, e in self._pending.items()
                     if e[3] < cutoff]
            for oid in stale:
                self._pending.pop(oid, None)
            self.stats["pending_expired"] += len(stale)
        return len(stale)
