"""Zero-copy object plane: the cross-process data path for one node.

Reference capability (NOT a port): plasma + the object manager
(``src/ray/object_manager/``) — a node-level store that every process on
the node maps (``plasma/``: mmap'd segments handed to clients, LRU of
sealed-unreferenced), plus proactive node-to-node transfer with dedup
(``object_manager.cc:354 Push``, ``push_manager.h``).

Three pieces:

- :mod:`~ray_tpu.objectplane.tiers` — the explicit
  (host-shm | device-HBM | spilled) tier model and its metrics
  (``ray_tpu_object_store_bytes{tier}``,
  ``ray_tpu_object_zero_copy_gets_total``);
- :mod:`~ray_tpu.objectplane.arena` — worker-side attach to the node
  daemon's shm arena: read-only ``np.frombuffer`` views with a
  process-shared per-object ref/release protocol (eviction can never
  unmap a buffer a worker still views), and direct puts that reserve +
  write arena space in place (only a seal message crosses the wire);
- :mod:`~ray_tpu.objectplane.push` — ``PushManager``: proactive
  daemon-to-daemon pushes of hot objects, deduplicated in flight and
  against the owner's object directory, chunks read straight from the
  arena.

See docs/object_plane.md for the protocol and knob table.
"""

from ray_tpu.objectplane.tiers import (TIER_DEVICE, TIER_HOST,  # noqa: F401
                                       TIER_SPILLED)
from ray_tpu.objectplane.arena import (WorkerArena, configure,  # noqa: F401
                                       get_arena,
                                       sweep_stale_segments)
from ray_tpu.objectplane.push import PushManager, PushReceiver  # noqa: F401
