"""Worker-side attach to the node daemon's shm arena.

The daemon hands its arena's (segment name, capacity) to every worker in
the boot frame (``worker_process._make_boot``). Workers map the segment
lazily on first use (``ShmObjectStore.attach`` — shm_open by name, the
fd-passing role of plasma's fling.cc) and then:

- resolve host-tier deps as ``np.frombuffer`` views over (offset,
  nbytes) metadata from the daemon — zero serialization for raw-tier
  arrays, zero payload round trip for pickled entries;
- hold a PROCESS-SHARED per-object refcount (the arena header's slot
  table) for every live view, released by a ``weakref.finalize`` when
  the consumer drops the array — LRU eviction in the daemon can never
  unmap a buffer a worker still views;
- direct-put large results by writing a daemon-reserved range in place;
  only the seal message crosses the wire.

Failure is never fatal: an attach that cannot map the segment (no
native build, hardened /dev/shm, the ``shm.attach`` failpoint) disables
the plane for this process and every operation falls back to the
classic per-task RPC path.

Crash safety: every slot ref the daemon increments on this process's
behalf is charged to a per-client grant ledger keyed by this process's
identity (workers: pid+generation, drivers: a connection-scoped id
minted at hello). If this process dies without releasing — SIGKILL mid-
view, mid-direct-put, whatever — the daemon's death signal (worker pipe
EOF or RPC disconnect) funnels into ``reclaim_client``, which drops the
outstanding grants, aborts unsealed reservations, and reaps; a
heartbeat orphan sweep backstops any signal the event path missed. A
crashed client therefore leaks nothing past the next beat — no daemon
restart needed (docs/object_plane.md "crash reclamation").
"""

from __future__ import annotations

import os
import weakref
from typing import Dict, List, Optional

import numpy as np

from ray_tpu._private import failpoints as _fp


class WorkerArena:
    """One process's attachment to a node arena."""

    def __init__(self, name: str, capacity: int):
        from ray_tpu._private.lock_sanitizer import tracked_lock
        self.name = name
        self.capacity = capacity
        self._lock = tracked_lock("objectplane.worker_arena",
                                  reentrant=False)
        self._store = None          #: guarded by self._lock
        self._failed = False        #: guarded by self._lock
        # live zero-copy views per slot: eviction safety is enforced by
        # the shared slot refcounts; this registry is the local mirror
        # (introspection + exactly-one release per dropped view)
        self._views: Dict[int, int] = {}    #: guarded by self._lock
        self.stats = {"zero_copy_gets": 0, "direct_puts": 0,
                      "attach_failures": 0, "released": 0}

    # -- attach ----------------------------------------------------------
    def store(self):
        """The attached handle, or None when the plane is unavailable
        (then callers take the classic RPC path — never task failure)."""
        with self._lock:
            if self._store is not None:
                return self._store
            if self._failed:
                return None
            try:
                if _fp.ENABLED:
                    # drop/error arm = the mapping fails (hardened
                    # /dev/shm, wrong segment): per-task RPC fallback,
                    # not task failure
                    if _fp.fire("shm.attach",
                                arena=self.name) is _fp.DROP:
                        raise RuntimeError("shm.attach failpoint drop")
                from ray_tpu.native_store import ShmObjectStore
                self._store = ShmObjectStore.attach(self.name)
            except Exception:
                self._failed = True
                self.stats["attach_failures"] += 1
                return None
            return self._store

    @property
    def attached(self) -> bool:
        with self._lock:
            return self._store is not None

    # -- zero-copy reads -------------------------------------------------
    def view(self, off: int, size: int, slot: int,
             dtype: Optional[str] = None,
             shape=None) -> np.ndarray:
        """Read-only view over arena bytes whose slot ref was already
        taken on our behalf (daemon-side ``get_ext``); a finalizer on
        the returned array drops the ref exactly once."""
        store = self.store()
        if store is None:
            raise RuntimeError("arena not attached")
        try:
            base = store.view_range(off, size)
        except Exception:
            # the granted ref is OURS from the moment the caller hands
            # off: a failed mapping (e.g. meta from a re-created,
            # smaller arena) must release it, not pin the object forever
            self.release_slot(slot)
            raise
        with self._lock:
            self._views[slot] = self._views.get(slot, 0) + 1
        self.stats["zero_copy_gets"] += 1
        # finalizer on the BASE frombuffer array, never a derived view:
        # numpy collapses base chains (a slice of the reshaped array
        # bases on `base`, not on the reshape), so only `base` dying
        # proves no view of the bytes survives
        weakref.finalize(base, self._release_slot, slot)
        arr = base
        if dtype is not None:
            arr = arr.view(np.dtype(dtype))
            if shape is not None:
                arr = arr.reshape(tuple(shape))
        from ray_tpu.objectplane.tiers import count_zero_copy_get
        count_zero_copy_get()
        return arr

    def _release_slot(self, slot: int) -> None:
        with self._lock:
            n = self._views.get(slot, 0) - 1
            if n <= 0:
                self._views.pop(slot, None)
            else:
                self._views[slot] = n
            store = self._store
        self.stats["released"] += 1
        if store is not None:
            try:
                store.ext_release(slot)
            except Exception:
                pass

    def release_slot(self, slot: int) -> None:
        """Drop a granted slot ref that never became a view (a failed
        resolve after the daemon already increfed on our behalf)."""
        store = self.store()
        if store is not None:
            try:
                store.ext_release(slot)
            except Exception:
                pass

    def live_views(self) -> int:
        with self._lock:
            return sum(self._views.values())

    # -- direct put ------------------------------------------------------
    def write(self, off: int, payload) -> None:
        """Fill a daemon-reserved (unsealed) range in place."""
        store = self.store()
        if store is None:
            raise RuntimeError("arena not attached")
        store.write_range(off, payload)
        self.stats["direct_puts"] += 1


# ---------------------------------------------------------------------------
# process-global arena (configured from the worker boot frame)
# ---------------------------------------------------------------------------

_ARENA: List[Optional[WorkerArena]] = [None]
_DISABLED: List[bool] = [False]


def configure(name: str, capacity: int) -> None:
    """Install this process's node arena (worker boot)."""
    _ARENA[0] = WorkerArena(name, capacity)


def get_arena() -> Optional[WorkerArena]:
    if _DISABLED[0]:
        return None
    return _ARENA[0]


def set_disabled(flag: bool) -> None:
    """Force the classic RPC path (tests: mixed classic/attached
    consumers on one daemon)."""
    _DISABLED[0] = bool(flag)


def arena_stats() -> Dict[str, int]:
    a = _ARENA[0]
    if a is None:
        return {}
    out = dict(a.stats)
    out["live_views"] = a.live_views()
    out["attached"] = int(a.attached)
    return out


# ---------------------------------------------------------------------------
# stale-segment hygiene (daemon startup)
# ---------------------------------------------------------------------------

def sweep_stale_segments(prefix: str) -> List[str]:
    """Unlink orphaned /dev/shm segments left by a previous crashed
    daemon of the same node (a SIGKILL'd daemon never reaches
    ``close(unlink=True)``; without the sweep its arena leaks until
    reboot AND a restarted daemon of the same node id would map the
    stale bytes). Called before the new arena is created, scoped to
    this node's deterministic name prefix so other daemons'/drivers'
    live segments are never touched."""
    removed: List[str] = []
    if not prefix:
        return removed
    base = "/dev/shm"
    try:
        names = os.listdir(base)
    except OSError:
        return removed
    for fname in names:
        if fname.startswith(prefix):
            try:
                os.unlink(os.path.join(base, fname))
                removed.append(fname)
            except OSError:
                pass
    return removed
