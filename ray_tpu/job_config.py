"""Per-job configuration (reference: `python/ray/job_config.py` —
JobConfig carries the job-level runtime env, metadata, and code search
path, serialized to the GCS at driver connect). Here it is a validated
bundle handed to ``ray_tpu.init(job_config=...)``; the runtime env
becomes the job-default runtime env and metadata lands in the job
table."""

from __future__ import annotations

from typing import Any, Dict, List, Optional


class JobConfig:
    def __init__(self,
                 runtime_env: Optional[Dict[str, Any]] = None,
                 metadata: Optional[Dict[str, str]] = None,
                 code_search_path: Optional[List[str]] = None,
                 default_actor_lifetime: str = "non_detached"):
        if default_actor_lifetime not in ("non_detached", "detached"):
            raise ValueError(
                f"default_actor_lifetime must be 'non_detached' or "
                f"'detached', got {default_actor_lifetime!r}")
        if runtime_env is not None:
            from ray_tpu.runtime_env import RuntimeEnv
            runtime_env = dict(RuntimeEnv(**runtime_env))  # validate
        self.runtime_env = runtime_env
        self.metadata = dict(metadata or {})
        self.code_search_path = list(code_search_path or [])
        self.default_actor_lifetime = default_actor_lifetime

    def serialize(self) -> Dict[str, Any]:
        return {"runtime_env": self.runtime_env,
                "metadata": self.metadata,
                "code_search_path": self.code_search_path,
                "default_actor_lifetime": self.default_actor_lifetime}
