"""`@remote` functions (reference: python/ray/remote_function.py)."""

from __future__ import annotations

import functools
import inspect
from typing import Any, Dict, List, Optional, Union

from ray_tpu._private import worker
from ray_tpu._private.ids import ObjectID, TaskID
from ray_tpu.tenancy import context as _tenancy_ctx
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.runtime_env_packaging import \
    prepare_runtime_env as _prepare_runtime_env
from ray_tpu._private.task_spec import (DEFAULT_TASK_OPTIONS, TaskKind,
                                        TaskSpec, resources_from_options,
                                        validate_options)


class ObjectRefGenerator:
    """Iterator over the streamed returns of a generator task.

    Each `next()` yields an ObjectRef as soon as the producer reports the
    item — before the task finishes (reference: ``_raylet.pyx``
    ObjectRefGenerator, proto ``ReportGeneratorItemReturns``).
    """

    def __init__(self, task_id: TaskID):
        import threading

        self._task_id = task_id
        self._index = 0
        # multiple threads may share one generator (fan-out consumers);
        # index claims must be atomic or items are delivered twice, and
        # a claim that errors (timeout/transient RPC) returns to the
        # hole set so ANOTHER consumer re-claims it — exactly-once even
        # when consumers fail interleaved
        self._lock = threading.Lock()
        self._holes: set = set()

    def __getstate__(self):
        return {"_task_id": self._task_id, "_index": self._index,
                "_holes": set(self._holes)}

    def __setstate__(self, d):
        import threading

        self._task_id = d["_task_id"]
        self._index = d["_index"]
        self._holes = set(d.get("_holes", ()))
        self._lock = threading.Lock()

    def __iter__(self) -> "ObjectRefGenerator":
        return self

    def __next__(self) -> ObjectRef:
        return self.next()

    def next(self, timeout: Optional[float] = None) -> ObjectRef:
        """``next(gen)`` with a deadline: raises ``GetTimeoutError``
        after ``timeout`` seconds; the claimed index returns to the
        hole set so a retry (or another consumer) re-claims it."""
        rt = worker.global_worker()
        state = rt.generator_state(self._task_id)
        with self._lock:
            if self._holes:
                index = min(self._holes)
                self._holes.discard(index)
            else:
                index = self._index
                self._index += 1
        try:
            return state.next_ref(index, timeout=timeout)
        except BaseException:
            with self._lock:
                self._holes.add(index)
            raise

    def __aiter__(self):
        return self

    async def __anext__(self):
        import asyncio
        loop = asyncio.get_event_loop()
        try:
            return await loop.run_in_executor(None, self.__next__)
        except StopIteration:
            raise StopAsyncIteration

    def completed(self) -> bool:
        rt = worker.global_worker()
        return rt.generator_state(self._task_id).finished


class RemoteFunction:
    def __init__(self, func, default_options: Dict[str, Any]):
        self._function = func
        merged = dict(DEFAULT_TASK_OPTIONS)
        merged.update(default_options)
        self._default_options = validate_options(merged, for_actor=False)
        functools.update_wrapper(self, func)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"remote function {self._function.__name__} cannot be called "
            f"directly; use {self._function.__name__}.remote()")

    def options(self, **options) -> "_OptionsWrapper":
        merged = dict(self._default_options)
        merged.update(options)
        validate_options(merged, for_actor=False)
        return _OptionsWrapper(self, merged)

    def remote(self, *args, **kwargs):
        return self._remote(args, kwargs, self._default_options)

    def bind(self, *args, **kwargs):
        """Build a DAG node (reference: dag/function_node.py)."""
        from ray_tpu.dag.node import FunctionNode
        return FunctionNode(self, args, kwargs)

    @property
    def _function_name(self) -> str:
        return getattr(self._function, "__name__", "fn")

    def _remote(self, args, kwargs, options) -> Union[ObjectRef,
                                                      List[ObjectRef],
                                                      ObjectRefGenerator]:
        rt = worker.global_worker()
        num_returns = options.get("num_returns", 1)
        if (num_returns == 1
                and inspect.isgeneratorfunction(self._function)):
            num_returns = "streaming"
        n_ids = 1 if not isinstance(num_returns, int) else max(num_returns, 1)
        task_id = TaskID.from_random()
        spec = TaskSpec(
            task_id=task_id,
            kind=TaskKind.NORMAL,
            name=options.get("name") or self._function.__qualname__,
            func=self._function,
            args=tuple(args),
            kwargs=dict(kwargs),
            resources=resources_from_options(options),
            num_returns=num_returns,
            return_ids=[ObjectID.from_random() for _ in range(n_ids)],
            max_retries=options.get("max_retries", 3),
            retry_exceptions=options.get("retry_exceptions", False),
            runtime_env=_prepare_runtime_env(
                options.get("runtime_env")),
            scheduling_strategy=worker.capture_parent_pg_strategy(
                options.get("scheduling_strategy", "DEFAULT")),
            job_id=_tenancy_ctx.current_job_id(rt),
            backpressure_num_objects=options.get(
                "_generator_backpressure_num_objects", -1),
            label_selector=options.get("label_selector"),
            in_process=bool(options.get("_in_process")),
        )
        refs = rt.submit_task(spec)
        if num_returns == "streaming":
            return ObjectRefGenerator(task_id)
        if isinstance(num_returns, int) and num_returns != 1:
            return refs if num_returns > 0 else None
        return refs[0]


class _OptionsWrapper:
    def __init__(self, remote_fn: RemoteFunction, options: Dict[str, Any]):
        self._remote_fn = remote_fn
        self._options = options

    def remote(self, *args, **kwargs):
        return self._remote_fn._remote(args, kwargs, self._options)
