"""Multi-host coordination: jax.distributed + pod topology env plumbing.

Reference capability: Ray Train's rendezvous role (``train/torch/config.py``
sets up the process group; ``_private/accelerators/tpu.py`` reads pod
topology env vars). TPU-native shape (SURVEY §5.8): within a slice, the
collectives are XLA-over-ICI and need no runtime help; ACROSS hosts the
only control-plane requirement is the jax coordination service —
``jax.distributed.initialize(coordinator, num_processes, process_id)`` —
after which every jitted program sees the global device set and pjit
shardings span hosts (DCN axes included).

This module resolves the rendezvous from (in priority order):
1. explicit arguments,
2. ray_tpu cluster metadata (head KV rendezvous — daemons elect host 0),
3. TPU pod environment (``TPU_WORKER_HOSTNAMES`` / ``TPU_WORKER_ID``,
   the GKE/TPU-VM contract),
and is idempotent. Single-process calls are a no-op (the common CI path).
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

_initialized = False

COORDINATOR_PORT = 8476


def pod_topology_from_env() -> Optional[Tuple[str, int, int]]:
    """(coordinator_address, num_processes, process_id) from the TPU pod
    env contract, or None when not on a pod."""
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES")
    worker_id = os.environ.get("TPU_WORKER_ID")
    if not hostnames or worker_id is None:
        return None
    hosts = [h.strip() for h in hostnames.split(",") if h.strip()]
    if len(hosts) <= 1:
        return None
    return (f"{hosts[0]}:{COORDINATOR_PORT}", len(hosts), int(worker_id))


def _routable_ip() -> str:
    """This host's routable interface IP. gethostbyname(hostname) often
    resolves to loopback (127.0.1.1 in /etc/hosts); the UDP-connect trick
    asks the kernel which interface would route outward — no packet is
    sent."""
    import socket

    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("8.8.8.8", 80))
        return s.getsockname()[0]
    except OSError:
        return socket.gethostbyname(socket.gethostname())
    finally:
        s.close()


def rendezvous_via_kv(kv, num_processes: int, process_id: int,
                      run_id: str = "default") -> Tuple[str, int, int]:
    """Elect host 0's address through the cluster KV (the reference's
    internal-KV NCCLUniqueID exchange, SURVEY §5.8 plane 3). ``run_id``
    namespaces the key so a re-formed cluster or a second concurrent job
    never reads a stale coordinator from an earlier run."""
    key = f"multihost::{run_id}::coordinator".encode()
    if process_id == 0:
        addr = f"{_routable_ip()}:{COORDINATOR_PORT}"
        kv.kv_put(key, addr.encode())
        return addr, num_processes, 0
    import time

    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        raw = kv.kv_get(key)
        if raw:
            return raw.decode(), num_processes, process_id
        time.sleep(0.2)
    raise TimeoutError("coordinator address never published to the KV")


def initialize_multihost(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None) -> bool:
    """Bring up the jax coordination service for this host. Returns True
    when a MULTI-host runtime was initialized (False = single host, which
    needs nothing). Idempotent."""
    global _initialized
    if _initialized:
        return True

    if coordinator_address is not None and (num_processes is None
                                            or process_id is None):
        raise ValueError(
            "an explicit coordinator_address also needs num_processes "
            "and process_id")
    if coordinator_address is None:
        topo = pod_topology_from_env()
        if topo is not None:
            coordinator_address, num_processes, process_id = topo
        elif num_processes and num_processes > 1 \
                and process_id is not None:
            # resolution priority 2: elect through the cluster KV
            from ray_tpu._private import worker

            rt = worker.global_runtime()
            if rt is None:
                return False
            coordinator_address, num_processes, process_id = \
                rendezvous_via_kv(rt.gcs, num_processes, process_id,
                                  run_id=rt.namespace)
        else:
            return False
    if num_processes is None or num_processes <= 1:
        return False

    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)
    _initialized = True
    return True


def multihost_mesh(spec, *, devices=None):
    """Build a global mesh spanning every host's devices; call AFTER
    initialize_multihost. Per-host data loading should shard by
    ``jax.process_index()``."""
    import jax

    from ray_tpu.parallel.mesh import build_mesh

    return build_mesh(spec, devices if devices is not None
                      else jax.devices())


def process_shard(n: int) -> Tuple[int, int]:
    """(start, stop) rows of an n-row global batch for THIS host."""
    import jax

    per = n // max(jax.process_count(), 1)
    start = jax.process_index() * per
    return start, start + per
