"""TPU parallelism layer: topology, meshes, sharding rules, pipelining.

This package is the TPU-native replacement for the reference's parallelism
plumbing (SURVEY.md §2.3): mesh axes instead of process groups, XLA
collectives over ICI instead of NCCL, SPMD pipeline scans instead of
compiled actor DAGs.
"""

from ray_tpu.parallel.mesh import (
    DEFAULT_AXIS_ORDER,
    DEFAULT_RULES,
    MeshSpec,
    build_mesh,
    logical_to_spec,
    mesh_from_string,
    named_sharding,
    replicated,
    shard_constraint,
)
from ray_tpu.parallel.pipeline import pipeline_apply, pipelined
from ray_tpu.parallel.topology import (
    SubSlice,
    TpuTopology,
    detect_local_topology,
    parse_topology,
)

__all__ = [
    "MeshSpec", "build_mesh", "mesh_from_string", "named_sharding",
    "logical_to_spec", "shard_constraint", "replicated", "DEFAULT_RULES",
    "DEFAULT_AXIS_ORDER", "TpuTopology", "SubSlice", "detect_local_topology",
    "parse_topology", "pipeline_apply", "pipelined",
]
