"""TPU slice / ICI topology model and sub-slice allocation.

The reference only *detects* TPU topology for resource bookkeeping
(``python/ray/_private/accelerators/tpu.py:15-58`` — GKE/GCE metadata,
``TPU_VISIBLE_CHIPS``, pod env vars). A TPU-native framework needs the
topology as a first-class scheduling structure: placement-group bundles must
map to ICI-contiguous sub-slices (SURVEY.md §7 phase 3), and mesh axes must
be laid out so heavy collectives ride ICI, not DCN.

Model: a slice is an axis-aligned box of chips in a 2D/3D torus. Hosts own
contiguous sub-boxes (e.g. v5p: 4 chips/host in a (2,2,1) block). Sub-slice
allocation hands out axis-aligned sub-boxes, which is exactly what the XLA
runtime requires for a mesh over ICI.
"""

from __future__ import annotations

import itertools
import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

# generation -> (chips per host, host block shape, torus dims)
TPU_GENERATIONS = {
    "v4": (4, (2, 2, 1), 3),
    "v5p": (4, (2, 2, 1), 3),
    "v5e": (4, (2, 2), 2),
    "v5litepod": (4, (2, 2), 2),
    "v6e": (4, (2, 2), 2),
}


def parse_topology(spec: str) -> Tuple[int, ...]:
    """'4x4x4' -> (4, 4, 4)."""
    try:
        dims = tuple(int(x) for x in spec.lower().split("x"))
    except ValueError:
        raise ValueError(f"bad topology spec {spec!r} (want e.g. '4x4x4')")
    if not dims or any(d <= 0 for d in dims):
        raise ValueError(f"bad topology spec {spec!r}")
    return dims


@dataclass(frozen=True)
class Chip:
    coords: Tuple[int, ...]
    host_index: int


@dataclass
class SubSlice:
    """An axis-aligned box of chips handed to one mesh / placement bundle."""

    origin: Tuple[int, ...]
    shape: Tuple[int, ...]

    @property
    def num_chips(self) -> int:
        return math.prod(self.shape)

    def chips(self) -> List[Tuple[int, ...]]:
        ranges = [range(o, o + s) for o, s in zip(self.origin, self.shape)]
        return list(itertools.product(*ranges))

    def contains(self, coords: Tuple[int, ...]) -> bool:
        return all(o <= c < o + s
                   for c, o, s in zip(coords, self.origin, self.shape))


class TpuTopology:
    """One TPU slice: chips on a torus, grouped into hosts."""

    def __init__(self, generation: str, topology: str):
        gen = generation.lower()
        if gen not in TPU_GENERATIONS:
            raise ValueError(f"unknown TPU generation {generation!r}; "
                             f"known: {sorted(TPU_GENERATIONS)}")
        self.generation = gen
        self.chips_per_host, host_block, ndims = TPU_GENERATIONS[gen]
        self.dims = parse_topology(topology)
        if len(self.dims) != ndims:
            raise ValueError(
                f"{generation} topologies are {ndims}-D, got {topology!r}")
        self.host_block = host_block
        for d, hb in zip(self.dims, host_block):
            if d % hb != 0:
                raise ValueError(
                    f"topology {topology} not divisible by host block "
                    f"{host_block}")
        self.hosts_grid = tuple(d // hb
                                for d, hb in zip(self.dims, host_block))
        self.num_hosts = math.prod(self.hosts_grid)
        self.num_chips = math.prod(self.dims)
        self._allocated: List[SubSlice] = []

    def __repr__(self):
        return (f"TpuTopology({self.generation}-{self.num_chips}, "
                f"{'x'.join(map(str, self.dims))}, {self.num_hosts} hosts)")

    # -- host mapping ------------------------------------------------------
    def host_of(self, coords: Tuple[int, ...]) -> int:
        idx = 0
        for c, hb, hg in zip(coords, self.host_block, self.hosts_grid):
            idx = idx * hg + (c // hb)
        return idx

    def chips(self) -> List[Chip]:
        out = []
        for coords in itertools.product(*(range(d) for d in self.dims)):
            out.append(Chip(coords, self.host_of(coords)))
        return out

    def hosts_of_subslice(self, sub: SubSlice) -> List[int]:
        return sorted({self.host_of(c) for c in sub.chips()})

    # -- sub-slice allocation (for placement-group bundles) ----------------
    def allocate(self, num_chips: int,
                 max_hosts: Optional[int] = None,
                 accept=None) -> Optional[SubSlice]:
        """Allocate an ICI-contiguous sub-slice of the given chip count.

        Chooses the most cube-like axis-aligned box with that volume that
        fits in the remaining space (greedy first-fit over origins).
        ``max_hosts`` restricts candidates to boxes spanning at most that
        many hosts (STRICT_PACK: 1 — the box must sit inside one host's
        chip block). ``accept(cand)`` lets the caller veto candidates
        that don't suit its bundle->host packing (e.g. host-sized
        bundles need host-block-aligned boxes) — the search then moves
        on to the next shape/origin instead of failing outright.
        """
        shapes = self._candidate_shapes(num_chips)
        for shape in shapes:
            for origin in itertools.product(
                    *(range(0, d - s + 1)
                      for d, s in zip(self.dims, shape))):
                cand = SubSlice(origin, shape)
                if any(self._overlaps(cand, a) for a in self._allocated):
                    continue
                if (max_hosts is not None
                        and len(self.hosts_of_subslice(cand)) > max_hosts):
                    continue
                if accept is not None and not accept(cand):
                    continue
                self._allocated.append(cand)
                return cand
        return None

    def free(self, sub: SubSlice) -> None:
        self._allocated = [a for a in self._allocated if a is not sub]

    def _candidate_shapes(self, volume: int) -> List[Tuple[int, ...]]:
        """All axis-aligned box shapes with the given volume, most
        cube-like (lowest surface area -> best bisection bandwidth) first."""
        nd = len(self.dims)
        out = set()

        def rec(rem: int, dims_left: int, cur: Tuple[int, ...]):
            if dims_left == 1:
                if rem <= self.dims[nd - 1]:
                    out.add(cur + (rem,))
                return
            axis = nd - dims_left
            for d in range(1, min(rem, self.dims[axis]) + 1):
                if rem % d == 0:
                    rec(rem // d, dims_left - 1, cur + (d,))

        rec(volume, nd, ())
        return sorted(out, key=lambda s: (max(s) / max(min(s), 1), s))

    @staticmethod
    def _overlaps(a: SubSlice, b: SubSlice) -> bool:
        return all(ao < bo + bs and bo < ao + as_
                   for ao, as_, bo, bs in zip(a.origin, a.shape,
                                              b.origin, b.shape))


class TpuTopologyManager:
    """Cluster-side view of one TPU slice: binds runtime nodes to torus
    hosts and hands out ICI-contiguous sub-slices under a lock.

    Reference capability: bundle placement policy
    (``src/ray/raylet/scheduling/policy/bundle_scheduling_policy.h``) —
    but where the reference packs by resource count only, TPU gang
    bundles must land on the hosts of one axis-aligned sub-slice or the
    mesh's collectives fall off ICI onto DCN.
    """

    def __init__(self, topology: TpuTopology):
        import threading

        self.topology = topology
        self._lock = threading.RLock()
        self._host_of_node: Dict[object, int] = {}   # node_id -> host idx
        self._node_of_host: Dict[int, object] = {}

    @staticmethod
    def from_spec(spec: str) -> "TpuTopologyManager":
        """'v5p:4x4x4' -> manager over that slice."""
        gen, _, topo = spec.partition(":")
        if not topo:
            raise ValueError(
                f"bad tpu_topology {spec!r} (want '<gen>:<AxBxC>')")
        return TpuTopologyManager(TpuTopology(gen, topo))

    # -- node <-> host binding (first-seen order, stable) ------------------
    def bind_nodes(self, node_ids: Sequence) -> None:
        with self._lock:
            for nid in node_ids:
                if nid in self._host_of_node:
                    continue
                for h in range(self.topology.num_hosts):
                    if h not in self._node_of_host:
                        self._host_of_node[nid] = h
                        self._node_of_host[h] = nid
                        break

    def unbind_node(self, node_id) -> None:
        with self._lock:
            h = self._host_of_node.pop(node_id, None)
            if h is not None:
                self._node_of_host.pop(h, None)

    def node_of_host(self, host: int):
        with self._lock:
            return self._node_of_host.get(host)

    # -- allocation --------------------------------------------------------
    def allocate(self, num_chips: int,
                 max_hosts: Optional[int] = None,
                 accept=None) -> Optional[SubSlice]:
        with self._lock:
            return self.topology.allocate(num_chips, max_hosts=max_hosts,
                                          accept=accept)

    def free(self, sub: SubSlice) -> None:
        with self._lock:
            self.topology.free(sub)

    def chips_by_host(self, sub: SubSlice) -> Dict[int, List[Tuple[int, ...]]]:
        """host index -> the sub-slice chips that host owns."""
        out: Dict[int, List[Tuple[int, ...]]] = {}
        for c in sub.chips():
            out.setdefault(self.topology.host_of(c), []).append(c)
        return out


def detect_local_topology() -> Optional[TpuTopology]:
    """Best-effort topology detection from the JAX runtime / env vars.

    Parity with the detection duties of the reference's
    ``_private/accelerators/tpu.py`` (env vars + metadata) — here the JAX
    client is the authority when present.
    """
    import os

    env_type = os.environ.get("TPU_ACCELERATOR_TYPE")  # e.g. "v5p-64"
    env_topo = os.environ.get("TPU_TOPOLOGY")  # e.g. "4x4x4"
    if env_type and env_topo:
        gen = env_type.split("-")[0]
        try:
            return TpuTopology(gen, env_topo)
        except ValueError:
            pass
    try:
        import jax
        devs = [d for d in jax.devices() if d.platform != "cpu"]
        if not devs:
            return None
        n = len(devs)
        # Single-host fallback: model as a flat 2D slice.
        if n in (1, 4, 8):
            return TpuTopology("v5e", f"{max(n // 2, 1)}x{min(n, 2)}")
    except Exception:
        return None
    return None
