"""Pipeline parallelism as a single SPMD program.

The reference expresses pipeline schedules as compiled actor DAGs with NCCL
channels (``dag/compiled_dag_node.py:809``, ``dag/collective_node.py``;
schedule construction ``dag/dag_node_operation.py``). On TPU the idiomatic
equivalent is radically simpler: the pipeline is a *single jitted SPMD
program* over a ``pp`` mesh axis — each device group holds one stage's
weights, microbatch activations rotate between neighbors with
``lax.ppermute`` (ICI neighbor exchange), and the whole schedule is a
``lax.scan``. Autodiff through the scan gives the backward pipeline schedule
for free; XLA overlaps the ppermute with compute.

Schedule: GPipe-style fill/drain — ``num_microbatches + num_stages - 1``
ticks. Device i computes stage i; at tick t stage 0 ingests microbatch t and
the last stage emits microbatch ``t - (num_stages-1)``.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def pipeline_apply(stage_fn: Callable,
                   local_params: Any,
                   microbatches: jnp.ndarray,
                   *,
                   axis_name: str = "pp",
                   num_stages: int,
                   num_microbatches: int) -> jnp.ndarray:
    """Run microbatches through the stage pipeline. Call INSIDE shard_map.

    Args:
      stage_fn: ``(params, x) -> y`` with ``y.shape == x.shape`` at stage
        boundaries (the transformer hidden-state contract).
      local_params: this device group's stage parameters (stage dim already
        stripped by shard_map).
      microbatches: ``[num_microbatches, ...]`` batch of stage-0 inputs,
        replicated over the pp axis.
      num_stages / num_microbatches: static schedule sizes.

    Returns:
      ``[num_microbatches, ...]`` outputs of the LAST stage, valid on every
      pp rank (broadcast at the end).
    """
    stage_idx = lax.axis_index(axis_name)
    n_ticks = num_microbatches + num_stages - 1
    perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]

    mb_shape = microbatches.shape[1:]
    act0 = jnp.zeros(mb_shape, microbatches.dtype)
    out0 = jnp.zeros((num_microbatches,) + mb_shape, microbatches.dtype)

    def tick(carry, t):
        act, outputs = carry
        mb_idx = jnp.clip(t, 0, num_microbatches - 1)
        fresh = lax.dynamic_index_in_dim(microbatches, mb_idx, 0,
                                         keepdims=False)
        x = jnp.where(stage_idx == 0, fresh, act)
        y = stage_fn(local_params, x)
        out_idx = t - (num_stages - 1)
        valid = jnp.logical_and(stage_idx == num_stages - 1, out_idx >= 0)
        oi = jnp.clip(out_idx, 0, num_microbatches - 1)
        prev = lax.dynamic_index_in_dim(outputs, oi, 0, keepdims=False)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(valid, y, prev), oi, 0)
        act_next = lax.ppermute(y, axis_name, perm)
        return (act_next, outputs), None

    (_, outputs), _ = lax.scan(tick, (act0, out0), jnp.arange(n_ticks))
    # Broadcast the last stage's outputs to every pp rank so downstream
    # (loss, metrics) is uniform SPMD: psum of a one-hot-masked buffer.
    is_last = (stage_idx == num_stages - 1).astype(outputs.dtype)
    outputs = lax.psum(outputs * is_last, axis_name)
    return outputs


def pipelined(stage_fn: Callable,
              mesh: Mesh,
              *,
              num_microbatches: int,
              axis_name: str = "pp",
              param_specs: Optional[Any] = None,
              batch_axes: Tuple[str, ...] = ("dp", "fsdp")) -> Callable:
    """Wrap a stage function into a full-batch pipelined forward.

    Returns ``f(stacked_params, batch) -> outputs`` jittable over the mesh:
      - ``stacked_params``: pytree with a leading ``num_stages`` dim,
        sharded along ``pp``.
      - ``batch``: ``[global_batch, ...]`` sharded along the data axes;
        reshaped to microbatches internally.
      - ``param_specs``: optional pytree of ``PartitionSpec`` (leading dim
        must be the pp axis) so stage weights can ALSO shard over other
        axes (e.g. Megatron tp) — inside the shard_map the stage_fn sees
        its local shard and owns the matching collectives.
    """
    from ray_tpu.parallel.mesh import shard_map_compat

    num_stages = mesh.shape[axis_name]

    def in_params_spec(leaf_ndim):
        return P(axis_name, *([None] * (leaf_ndim - 1)))

    def run(stacked_params, batch):
        def inner(params, mb):
            # shard_map gives params with a leading stage dim of size 1.
            local = jax.tree_util.tree_map(lambda p: p[0], params)
            return pipeline_apply(
                stage_fn, local, mb, axis_name=axis_name,
                num_stages=num_stages,
                num_microbatches=num_microbatches)

        p_specs = (param_specs if param_specs is not None
                   else jax.tree_util.tree_map(
                       lambda p: in_params_spec(p.ndim), stacked_params))
        # microbatch the (locally sharded) batch dim
        mb = batch.reshape((num_microbatches, -1) + batch.shape[1:])
        mb_spec = P(None, batch_axes, *([None] * (batch.ndim - 1)))
        out = shard_map_compat(
            inner, mesh,
            (p_specs, mb_spec),
            mb_spec,
        )(stacked_params, mb)
        return out.reshape((-1,) + out.shape[2:])

    return run
