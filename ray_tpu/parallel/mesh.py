"""Device mesh construction with named parallelism axes.

This replaces the reference's process-group plumbing (Train's
``torch/config.py`` NCCL rendezvous) with the JAX-native structure: a
``jax.sharding.Mesh`` whose axes are the parallelism strategies of
SURVEY.md §2.3 —

  dp    data parallel (gradient all-reduce over ICI)
  fsdp  sharded data parallel (weight all-gather / grad reduce-scatter)
  pp    pipeline parallel (microbatch ppermute ring)
  tp    tensor parallel (Megatron-style within-layer sharding)
  sp    sequence/context parallel (ring attention neighbor exchange)
  ep    expert parallel (MoE all-to-all dispatch)

Axis order matters on hardware: the innermost (fastest-varying) axes should
map to the closest ICI neighbors. We order axes (pp, dp, fsdp, sp, tp, ep)
outer→inner by default so tp/ep collectives ride the shortest links, matching
the scaling-book recipe.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# outer -> inner hardware order
DEFAULT_AXIS_ORDER = ("pp", "dp", "fsdp", "sp", "tp", "ep")


@dataclass(frozen=True)
class MeshSpec:
    """Sizes for each named parallelism axis (1 = unused but present)."""

    dp: int = 1
    fsdp: int = 1
    pp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1

    @property
    def num_devices(self) -> int:
        return self.dp * self.fsdp * self.pp * self.tp * self.sp * self.ep

    def sizes(self) -> Dict[str, int]:
        return {"pp": self.pp, "dp": self.dp, "fsdp": self.fsdp,
                "sp": self.sp, "tp": self.tp, "ep": self.ep}

    @staticmethod
    def auto(num_devices: int, *, tp: int = 1, pp: int = 1, sp: int = 1,
             ep: int = 1, fsdp: int = 1) -> "MeshSpec":
        """Fill dp with whatever is left after the explicit axes."""
        used = tp * pp * sp * ep * fsdp
        if num_devices % used != 0:
            raise ValueError(
                f"{num_devices} devices not divisible by tp*pp*sp*ep*fsdp="
                f"{used}")
        return MeshSpec(dp=num_devices // used, fsdp=fsdp, pp=pp, tp=tp,
                        sp=sp, ep=ep)


def build_mesh(spec: MeshSpec,
               devices: Optional[Sequence] = None,
               axis_order: Tuple[str, ...] = DEFAULT_AXIS_ORDER) -> Mesh:
    """Build a Mesh with all six named axes (size-1 axes included).

    Keeping unused axes (size 1) in the mesh means model sharding rules can
    always reference the full axis vocabulary; XLA elides trivial axes.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if spec.num_devices != n:
        raise ValueError(
            f"mesh spec needs {spec.num_devices} devices "
            f"(={spec.sizes()}), got {n}")
    sizes = spec.sizes()
    shape = tuple(sizes[a] for a in axis_order)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, axis_order)


def mesh_from_string(desc: str, devices: Optional[Sequence] = None) -> Mesh:
    """Build a mesh from 'dp=2,tp=2,sp=2' style descriptions."""
    kwargs: Dict[str, int] = {}
    for part in desc.replace(" ", "").split(","):
        if not part:
            continue
        k, _, v = part.partition("=")
        kwargs[k] = int(v)
    return build_mesh(MeshSpec(**kwargs), devices)


def shard_map_compat(fn, mesh, in_specs, out_specs):
    """shard_map across jax versions: import location and the replication-
    check kwarg (check_vma vs check_rep) both moved; every SPMD module
    shares this one compat seam."""
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map
    try:
        return shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)
    except TypeError:  # older jax spells it check_rep
        return shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)


# ---------------------------------------------------------------------------
# Logical axis rules: map tensor-dimension names to mesh axes.
# ---------------------------------------------------------------------------

# Megatron-style sharding vocabulary for transformer weights/activations.
DEFAULT_RULES: Dict[str, Optional[object]] = {
    # activations
    "batch": ("dp", "fsdp"),   # batch dim sharded over data axes
    "seq": "sp",               # sequence dim sharded for context parallelism
    "embed": None,             # activation embed dim replicated
    "heads": "tp",             # attention heads over tensor axis
    "kv_heads": "tp",
    "head_dim": None,
    # weights
    "embed_in": "fsdp",        # weight embed dim sharded for ZeRO/FSDP
    "mlp": "tp",               # FFN hidden over tensor axis
    "vocab": "tp",             # embedding/LM-head vocab over tensor axis
    "experts": "ep",           # MoE expert dim
    "stages": "pp",            # stacked pipeline stage dim
}


def logical_to_spec(names: Sequence[Optional[str]],
                    rules: Optional[Dict] = None) -> PartitionSpec:
    """('batch','seq','embed') -> PartitionSpec(('dp','fsdp'),'sp',None)."""
    rules = {**DEFAULT_RULES, **(rules or {})}
    out = []
    for name in names:
        if name is None:
            out.append(None)
        else:
            if name not in rules:
                raise KeyError(f"no sharding rule for logical axis {name!r}")
            out.append(rules[name])
    return PartitionSpec(*out)


def named_sharding(mesh: Mesh, *names: Optional[str],
                   rules: Optional[Dict] = None) -> NamedSharding:
    return NamedSharding(mesh, logical_to_spec(names, rules))


def shard_constraint(x, mesh: Mesh, *names: Optional[str],
                     rules: Optional[Dict] = None):
    """with_sharding_constraint by logical axis names."""
    return jax.lax.with_sharding_constraint(
        x, named_sharding(mesh, *names, rules=rules))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def local_mesh_devices(n: Optional[int] = None) -> List:
    """Devices for a mesh; n=None -> all."""
    devs = jax.devices()
    return devs if n is None else devs[:n]
