"""Model multiplexing (reference: `python/ray/serve/multiplex.py` —
``@serve.multiplexed`` caches up to N models per replica, LRU-evicted;
requests carry a model id that routes to a replica holding it)."""

from __future__ import annotations

import functools
import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional

_current_model_id = threading.local()


def get_multiplexed_model_id() -> str:
    """Inside a replica: the model id of the current request."""
    return getattr(_current_model_id, "value", "")


def _set_model_id(model_id: str):
    _current_model_id.value = model_id


class _ModelCache:
    def __init__(self, loader: Callable[[Any, str], Any],
                 max_num_models: int):
        self.loader = loader
        self.max_num_models = max_num_models
        self._models: "OrderedDict[str, Any]" = OrderedDict()
        self._lock = threading.Lock()

    def get(self, owner, model_id: str) -> Any:
        with self._lock:
            if model_id in self._models:
                self._models.move_to_end(model_id)
                return self._models[model_id]
        model = (self.loader(owner, model_id) if owner is not None
                 else self.loader(model_id))
        with self._lock:
            self._models[model_id] = model
            self._models.move_to_end(model_id)
            while len(self._models) > self.max_num_models:
                old_id, old = self._models.popitem(last=False)
                unload = getattr(old, "__del__", None)
        return model

    def ids(self):
        with self._lock:
            return list(self._models)


def multiplexed(max_num_models_per_replica: int = 3):
    """Decorator over an async-or-sync model loader method/function:

        @serve.multiplexed(max_num_models_per_replica=2)
        def get_model(self, model_id: str): ...

    The wrapped loader becomes an LRU-cached lookup.
    """
    def wrap(loader):
        cache = _ModelCache(loader, max_num_models_per_replica)

        @functools.wraps(loader)
        def wrapper(*args):
            if len(args) == 2:
                owner, model_id = args
            else:
                owner, (model_id,) = None, args
            return cache.get(owner, model_id)

        wrapper._model_cache = cache
        return wrapper
    return wrap
