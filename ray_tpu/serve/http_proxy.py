"""Asyncio HTTP ingress for Serve (the ASGI-proxy role).

Reference: ``python/ray/serve/_private/proxy.py:697`` (HTTPProxy — an
ASGI app under uvicorn) and ``:1009`` (the streaming response path).
The stdlib ``ThreadingHTTPServer`` it replaces spends a thread per
CONNECTION and has no ingress backpressure; this plane is one asyncio
event loop:

- connections scale without threads (keep-alive supported),
- an explicit in-flight cap (``max_ongoing_requests``) sheds load with
  503 + Retry-After the moment the data plane saturates — the
  reference's proxy backpressure contract,
- per-request work awaits the data plane (``ObjectRef.as_future``) so
  a slow replica never blocks the accept loop,
- SSE streaming pulls replica chunks through an executor, flushing
  each the moment it lands (TTFT = first chunk, not handler return).

HTTP/1.1 subset: request line + headers + Content-Length bodies (no
chunked request decoding — JSON ingress clients all send a length).
"""

from __future__ import annotations

import asyncio
import json
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Optional

from ray_tpu.serve.router import DeploymentHandle

_MAX_HEADER = 64 * 1024
_MAX_BODY = 64 * 1024 * 1024


class AsyncHTTPProxy:
    """One event loop serving every running application."""

    def __init__(self, handles: Dict[str, DeploymentHandle],
                 host: str = "127.0.0.1", port: int = 8000,
                 max_ongoing_requests: int = 200,
                 request_timeout_s: float = 60.0):
        self.handles = handles
        self.host = host
        self.port = port
        self.max_ongoing = max_ongoing_requests
        self.request_timeout_s = request_timeout_s
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        # streaming generators block in ray get per chunk: bounded pool
        self._pool = ThreadPoolExecutor(max_workers=32,
                                        thread_name_prefix="proxy-stream")
        self._ongoing = 0
        self.stats = {"requests": 0, "shed": 0, "streams": 0}

    # -- lifecycle --------------------------------------------------------
    def start(self) -> int:
        """Run the loop in a daemon thread; returns the bound port."""
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="serve-http-proxy")
        self._thread.start()
        if not self._started.wait(10.0):
            raise RuntimeError("HTTP proxy failed to start")
        return self.port

    def _run(self) -> None:
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)

        async def boot():
            # limit must exceed _MAX_HEADER or readuntil raises
            # LimitOverrunError before the 431 check can answer
            self._server = await asyncio.start_server(
                self._serve_conn, self.host, self.port,
                limit=_MAX_HEADER * 2)
            self.port = self._server.sockets[0].getsockname()[1]
            self._started.set()

        self._loop.run_until_complete(boot())
        try:
            self._loop.run_forever()
        finally:
            self._loop.close()

    def stop(self) -> None:
        loop = self._loop
        if loop is None:
            return

        def shutdown():  #: loop-only
            if self._server is not None:
                self._server.close()
            loop.stop()

        try:
            loop.call_soon_threadsafe(shutdown)
        except RuntimeError:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._pool.shutdown(wait=False)

    # -- connection loop ---------------------------------------------------
    async def _serve_conn(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                keep_alive = await self._serve_one(reader, writer)
                if not keep_alive:
                    break
        except (asyncio.IncompleteReadError, ConnectionError,
                asyncio.LimitOverrunError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _serve_one(self, reader, writer) -> bool:
        try:
            head = await reader.readuntil(b"\r\n\r\n")
        except asyncio.LimitOverrunError:
            await self._plain(writer, 431, {"error": "headers too large"})
            return False
        if len(head) > _MAX_HEADER:
            await self._plain(writer, 431, {"error": "headers too large"})
            return False
        lines = head.decode("latin-1").split("\r\n")
        try:
            method, path, version = lines[0].split(" ", 2)
        except ValueError:
            await self._plain(writer, 400, {"error": "bad request line"})
            return False
        headers = {}
        for ln in lines[1:]:
            if ":" in ln:
                k, v = ln.split(":", 1)
                headers[k.strip().lower()] = v.strip()
        length = int(headers.get("content-length", 0) or 0)
        if length > _MAX_BODY:
            await self._plain(writer, 413, {"error": "body too large"})
            return False
        raw = await reader.readexactly(length) if length else b""
        keep_alive = (headers.get("connection", "").lower() != "close"
                      and version != "HTTP/1.0")

        # ingress backpressure: shed BEFORE touching the data plane
        if self._ongoing >= self.max_ongoing:
            self.stats["shed"] += 1
            await self._plain(writer, 503,
                              {"error": "too many ongoing requests"},
                              extra_headers={"Retry-After": "1"})
            return keep_alive
        self._ongoing += 1
        self.stats["requests"] += 1
        try:
            streamed = await self._handle_request(writer, path, headers,
                                                  raw)
        finally:
            self._ongoing -= 1
        # SSE responses are EOF-terminated (no Content-Length): the
        # advertised 'Connection: close' must actually happen or
        # EOF-reading clients hang until timeout
        return keep_alive and not streamed

    # -- request handling --------------------------------------------------
    def _route(self, path: str) -> Optional[DeploymentHandle]:
        app = path.strip("/").split("/")[0] or "default"
        return self.handles.get(app) or self.handles.get("default")

    async def _handle_request(self, writer, path: str,
                              headers: Dict[str, str],
                              raw: bytes) -> bool:
        """Returns True when the response was a stream (conn closes)."""
        handle = self._route(path)
        if handle is None:
            await self._plain(writer, 404, {"error": "no such application"})
            return False
        try:
            payload: Any = json.loads(raw) if raw else {}
        except json.JSONDecodeError:
            payload = raw.decode(errors="replace")
        wants_stream = ("text/event-stream" in headers.get("accept", "")
                        or (isinstance(payload, dict)
                            and payload.get("stream") is True))
        if wants_stream:
            await self._stream(writer, handle, payload)
            return True
        loop = asyncio.get_running_loop()
        timeout = self.request_timeout_s

        def resolve():
            import ray_tpu
            resp = handle.remote(payload)
            # bounded get: a stuck replica must release this pool slot
            return ray_tpu.get(resp.ref, timeout=timeout)

        try:
            # the bounded pool is the thread budget (no thread per
            # request); asyncio.wait_for gives the client its 504 even
            # if the pool itself is saturated
            result = await asyncio.wait_for(
                loop.run_in_executor(self._pool, resolve),
                timeout=timeout + 5.0)
            await self._plain(writer, 200, result)
        except asyncio.TimeoutError:
            await self._plain(writer, 504, {"error": "request timed out"})
        except Exception as e:  # noqa: BLE001 — surfaced to the client
            if type(e).__name__ == "GetTimeoutError":
                await self._plain(writer, 504,
                                  {"error": "request timed out"})
            else:
                await self._plain(writer, 500, {"error": repr(e)})
        return False

    async def _stream(self, writer, handle, payload) -> None:
        """SSE: chunks flush as the replica yields them (proxy.py:1009)."""
        self.stats["streams"] += 1
        loop = asyncio.get_running_loop()

        def submit():
            # .remote() is a full rpc round trip (lease + push): run it
            # on the stream pool, never on the event loop
            return handle.options(stream=True).remote(payload)

        try:
            gen = await loop.run_in_executor(self._pool, submit)
        except Exception as e:  # noqa: BLE001
            await self._plain(writer, 500, {"error": repr(e)})
            return
        writer.write(b"HTTP/1.1 200 OK\r\n"
                     b"Content-Type: text/event-stream\r\n"
                     b"Cache-Control: no-cache\r\n"
                     b"Connection: close\r\n\r\n")
        await writer.drain()
        it = iter(gen)

        def next_chunk():
            try:
                return False, next(it)
            except StopIteration:
                return True, None

        try:
            while True:
                done, chunk = await loop.run_in_executor(self._pool,
                                                         next_chunk)
                if done:
                    writer.write(b"data: [DONE]\n\n")
                    await writer.drain()
                    break
                writer.write(f"data: {json.dumps(chunk)}\n\n".encode())
                await writer.drain()
        except (ConnectionError, asyncio.CancelledError):
            pass           # client went away mid-stream
        except Exception as e:  # noqa: BLE001 — last-gasp error event
            try:
                writer.write(
                    f"data: {json.dumps({'error': repr(e)})}\n\n".encode())
                await writer.drain()
            except Exception:
                pass

    async def _plain(self, writer, code: int, payload: Any,
                     extra_headers: Optional[Dict[str, str]] = None
                     ) -> None:
        body = json.dumps(payload).encode()
        reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
                  413: "Payload Too Large", 431: "Headers Too Large",
                  500: "Internal Server Error",
                  503: "Service Unavailable",
                  504: "Gateway Timeout"}.get(code, "OK")
        head = [f"HTTP/1.1 {code} {reason}",
                "Content-Type: application/json",
                f"Content-Length: {len(body)}"]
        for k, v in (extra_headers or {}).items():
            head.append(f"{k}: {v}")
        writer.write(("\r\n".join(head) + "\r\n\r\n").encode() + body)
        await writer.drain()


