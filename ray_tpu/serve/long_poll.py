"""Long-poll config propagation: push, not periodic pull.

Reference: ``serve/_private/long_poll.py`` — ``LongPollHost`` (:222) holds
listeners' requests open and completes them the moment a key's snapshot
changes; ``LongPollClient`` (:70) keeps one in-flight listen per host and
applies updates via callbacks. This removes the staleness window of
poll-on-interval: a deploy/scale/death is visible to every router at
publish time + one actor-call latency.

Host side lives inside the ServeController actor (its ``max_concurrency``
bounds concurrently parked listens); client side is a daemon thread per
DeploymentHandle.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

LISTEN_TIMEOUT_S = 30.0  # parked listens return empty after this (keeps
                         # actor slots cycling; client re-issues at once)


class LongPollHost:
    """Versioned key/snapshot store with blocking listens."""

    def __init__(self):
        self._cond = threading.Condition()
        self._state: Dict[str, Tuple[int, Any]] = {}

    def publish(self, key: str, snapshot: Any) -> None:
        with self._cond:
            version = self._state.get(key, (0, None))[0] + 1
            self._state[key] = (version, snapshot)
            self._cond.notify_all()

    def get(self, key: str) -> Optional[Any]:
        with self._cond:
            entry = self._state.get(key)
            return entry[1] if entry else None

    def listen(self, keys_to_versions: Dict[str, int],
               timeout_s: float = LISTEN_TIMEOUT_S) -> Dict[str, Any]:
        """Block until any watched key moves past the caller's version;
        returns {key: {"version": v, "snapshot": s}} for changed keys
        (empty dict on timeout — the client just re-listens)."""
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while True:
                changed = {
                    key: {"version": v, "snapshot": snap}
                    for key, (v, snap) in self._state.items()
                    if key in keys_to_versions
                    and v > keys_to_versions[key]}
                if changed:
                    return changed
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return {}
                self._cond.wait(remaining)


class LongPollClient:
    """One daemon thread keeps a listen open against the host actor and
    applies snapshot updates through callbacks."""

    def __init__(self, host_actor,
                 key_callbacks: Dict[str, Callable[[Any, int], None]]):
        self._host = host_actor
        self._callbacks = dict(key_callbacks)
        self._versions = {key: -1 for key in key_callbacks}
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="serve-long-poll")
        self._thread.start()

    def _loop(self) -> None:
        import ray_tpu

        while not self._stop.is_set():
            if not ray_tpu.is_initialized():
                return  # runtime shut down under us
            try:
                updates = ray_tpu.get(
                    self._host.listen_for_change.remote(
                        dict(self._versions)),
                    timeout=LISTEN_TIMEOUT_S + 15)
            except Exception:
                if self._stop.wait(0.5):
                    return
                continue
            for key, update in updates.items():
                self._versions[key] = update["version"]
                try:
                    self._callbacks[key](update["snapshot"],
                                         update["version"])
                except Exception:
                    pass

    def stop(self) -> None:
        self._stop.set()
