"""serve API: run/shutdown/status/get_deployment_handle + HTTP proxy.

Reference: `serve/api.py:691` (serve.run), `serve/_private/proxy.py:697`
(HTTPProxy ASGI). The proxy here is a threaded stdlib HTTP server that
JSON-decodes request bodies and routes to the application's ingress
handle — the data plane (handle → P2C router → replica actor) is identical
in shape to the reference.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.serve.deployment import Application
from ray_tpu.serve.router import DeploymentHandle

_apps: Dict[str, str] = {}       # app name -> ingress deployment name
_http_server = None
_http_thread = None


def _get_controller(create: bool = True):
    try:
        return ray_tpu.get_actor("serve_controller")
    except Exception:
        if not create:
            raise
    from ray_tpu.serve.controller import ServeController
    controller_cls = ray_tpu.remote(ServeController)
    handle = controller_cls.options(
        name="serve_controller", lifetime="detached",
        # Control-plane actor: holds live handles/locks and brokers
        # device-owning replicas — stays in the mesh-owning process.
        _in_process=True,
        max_concurrency=32).remote()
    ray_tpu.get(handle.ping.remote())
    return handle


def run(app: Application, *, name: str = "default",
        route_prefix: Optional[str] = None,
        blocking: bool = False,
        local_testing_mode: bool = False):
    """Deploy an application; returns the ingress handle. With
    ``local_testing_mode`` the graph runs fully in-process (reference:
    serve/_private/local_testing_mode.py)."""
    if local_testing_mode:
        from ray_tpu.serve.local_testing import run_local
        return run_local(app)
    controller = _get_controller()
    ingress = ray_tpu.get(controller.deploy_application.remote(app))
    _apps[name] = ingress
    return DeploymentHandle(ingress, controller)


def get_deployment_handle(deployment_name: str,
                          app_name: str = "default") -> DeploymentHandle:
    return DeploymentHandle(deployment_name, _get_controller(create=False))

def get_app_handle(app_name: str = "default") -> DeploymentHandle:
    return DeploymentHandle(_apps[app_name], _get_controller(create=False))


def status() -> Dict[str, Any]:
    controller = _get_controller(create=False)
    return ray_tpu.get(controller.status.remote())


def delete(app_name: str) -> None:
    controller = _get_controller(create=False)
    ingress = _apps.pop(app_name, None)
    if ingress:
        ray_tpu.get(controller.delete_deployment.remote(ingress))


def shutdown() -> None:
    global _http_server, _http_thread
    if _http_server is not None:
        if hasattr(_http_server, "shutdown"):
            _http_server.shutdown()     # legacy ThreadingHTTPServer
        else:
            _http_server.stop()         # asyncio proxy
        _http_server = None
        _http_thread = None
    try:
        controller = _get_controller(create=False)
        ray_tpu.get(controller.shutdown.remote())
        ray_tpu.kill(controller)
    except Exception:
        pass
    _apps.clear()


# ---------------------------------------------------------------------------
# HTTP proxy
# ---------------------------------------------------------------------------

class _ProxyHandler(BaseHTTPRequestHandler):
    handles: Dict[str, DeploymentHandle] = {}

    def log_message(self, *args):  # quiet
        pass

    def _route(self) -> Optional[DeploymentHandle]:
        app = self.path.strip("/").split("/")[0] or "default"
        return self.handles.get(app) or self.handles.get("default")

    def _respond(self, code: int, payload: Any) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _respond_stream(self, handle, payload) -> None:
        """SSE response (reference: proxy.py:1009 streaming path): each
        replica chunk is flushed as a ``data:`` event the moment it
        arrives — the client reads chunk 1 while generation continues."""
        try:
            gen = handle.options(stream=True).remote(payload)
        except Exception as e:
            self._respond(500, {"error": repr(e)})
            return
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.end_headers()
        try:
            for chunk in gen:
                self.wfile.write(
                    f"data: {json.dumps(chunk)}\n\n".encode())
                self.wfile.flush()
            self.wfile.write(b"data: [DONE]\n\n")
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away mid-stream
        except Exception as e:
            try:
                self.wfile.write(
                    f"data: {json.dumps({'error': repr(e)})}\n\n".encode())
                self.wfile.flush()
            except OSError:
                pass

    def do_POST(self):
        handle = self._route()
        if handle is None:
            self._respond(404, {"error": "no such application"})
            return
        length = int(self.headers.get("Content-Length", 0))
        raw = self.rfile.read(length) if length else b"{}"
        try:
            payload = json.loads(raw) if raw else {}
        except json.JSONDecodeError:
            payload = raw.decode()
        wants_stream = ("text/event-stream"
                        in self.headers.get("Accept", "")
                        or (isinstance(payload, dict)
                            and payload.get("stream") is True))
        if wants_stream:
            self._respond_stream(handle, payload)
            return
        try:
            result = handle.remote(payload).result(timeout=60)
            self._respond(200, result)
        except Exception as e:
            self._respond(500, {"error": repr(e)})

    def do_GET(self):
        self.do_POST()


def start_http_proxy(port: int = 8000, host: str = "127.0.0.1", *,
                     max_ongoing_requests: int = 200,
                     request_timeout_s: float = 60.0,
                     legacy_threaded: bool = False) -> int:
    """Start the HTTP ingress serving all running applications; returns
    the bound port (0 picks a free one).

    Default plane: the asyncio proxy (``serve/http_proxy.py`` — one
    event loop, keep-alive, SSE streaming, and ingress backpressure
    shedding 503s past ``max_ongoing_requests``; reference:
    ``serve/_private/proxy.py:697``). ``legacy_threaded=True`` keeps the
    old thread-per-connection stdlib server."""
    global _http_server, _http_thread
    if _http_server is not None:
        return (_http_server.server_address[1]
                if hasattr(_http_server, "server_address")
                else _http_server.port)
    controller = _get_controller(create=False)
    handles = {app: DeploymentHandle(ingress, controller)
               for app, ingress in _apps.items()}
    if legacy_threaded:
        _ProxyHandler.handles = handles
        _http_server = ThreadingHTTPServer((host, port), _ProxyHandler)
        _http_thread = threading.Thread(
            target=_http_server.serve_forever, daemon=True)
        _http_thread.start()
        return _http_server.server_address[1]
    from ray_tpu.serve.http_proxy import AsyncHTTPProxy
    _http_server = AsyncHTTPProxy(
        handles, host=host, port=port,
        max_ongoing_requests=max_ongoing_requests,
        request_timeout_s=request_timeout_s)
    return _http_server.start()
