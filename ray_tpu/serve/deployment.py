"""Deployment API (reference: `python/ray/serve/api.py` @serve.deployment,
`serve/deployment.py`): a deployment wraps a user class/function with
replica-count / autoscaling / batching options; ``.bind()`` builds an
application graph for model composition via handles."""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple, Union


@dataclasses.dataclass
class AutoscalingConfig:
    """Reference: `serve/config.py` AutoscalingConfig +
    `serve/autoscaling_policy.py:12` target-ongoing-requests policy."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 0.0
    downscale_delay_s: float = 2.0
    # Federated-metrics downscale guard: while the cluster-wide mean
    # task QUEUE-phase latency over the last controller tick (from the
    # head's ray_tpu_task_phase_seconds federation) exceeds this,
    # downscale is deferred — depth counts can read low mid-burst while
    # queueing latency says the cluster is still behind. Only applies
    # while the deployment itself reports load (the signal is cluster-
    # wide; unrelated work must not pin an IDLE deployment at peak).
    # <=0 disables.
    downscale_queue_guard_s: float = 0.5


@dataclasses.dataclass
class Deployment:
    func_or_class: Union[type, Callable]
    name: str
    num_replicas: int = 1
    autoscaling_config: Optional[AutoscalingConfig] = None
    max_ongoing_requests: int = 16
    user_config: Optional[Dict[str, Any]] = None
    ray_actor_options: Optional[Dict[str, Any]] = None
    max_restarts: int = 3
    # Downscaled replicas DRAIN: routers stop picking them at the
    # membership publish, then the controller waits up to this long for
    # reported ongoing+queue to hit zero before the kill — in-flight
    # requests complete instead of burning (reference:
    # graceful_shutdown_timeout_s on the deployment config).
    graceful_shutdown_timeout_s: float = 10.0

    def options(self, **kwargs) -> "Deployment":
        return dataclasses.replace(self, **kwargs)

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)


class Application:
    """A bound deployment DAG node. Bound Application arguments become
    DeploymentHandles at replica init (model composition)."""

    def __init__(self, deployment: Deployment, args: Tuple, kwargs: Dict):
        self.deployment = deployment
        self.args = args
        self.kwargs = kwargs

    def dependencies(self) -> List["Application"]:
        out = []
        for a in list(self.args) + list(self.kwargs.values()):
            if isinstance(a, Application):
                out.append(a)
        return out


def deployment(_func_or_class=None, *, name: Optional[str] = None,
               num_replicas: Optional[int] = None,
               autoscaling_config: Optional[Union[Dict,
                                                  AutoscalingConfig]] = None,
               max_ongoing_requests: int = 16,
               user_config: Optional[Dict] = None,
               ray_actor_options: Optional[Dict] = None,
               graceful_shutdown_timeout_s: float = 10.0):
    """``@serve.deployment`` decorator."""
    def wrap(fc):
        asc = autoscaling_config
        if isinstance(asc, dict):
            asc = AutoscalingConfig(**asc)
        return Deployment(
            fc, name=name or fc.__name__,
            num_replicas=num_replicas or 1,
            autoscaling_config=asc,
            max_ongoing_requests=max_ongoing_requests,
            user_config=user_config,
            ray_actor_options=ray_actor_options,
            graceful_shutdown_timeout_s=graceful_shutdown_timeout_s)
    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap
