"""Replica placement: SPREAD by default + node compaction.

Reference: ``serve/_private/deployment_scheduler.py:275`` (SPREAD default
at :34, ``get_node_to_compact`` :638). Replicas of one deployment spread
across alive nodes (soft node affinity — availability under node loss);
a compaction pass finds the node with the fewest replicas whose replicas
all fit elsewhere and migrates them so the node can be released (the
downscale story for autoscaled clusters).

TPU note: only host-plane replicas spread; device-owning replicas (LLM
engines) are created ``_in_process`` in the mesh-owning driver and are
not subject to compaction.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ray_tpu._private.ids import NodeID


class DeploymentScheduler:
    def __init__(self):
        self._lock = threading.Lock()
        # deployment -> replica handle id -> node_id hex
        self._placements: Dict[str, Dict[int, str]] = {}
        self._rr = 0
        # node_hex -> blocked-until timestamp: a just-compacted node must
        # not immediately receive the replicas evicted from it
        self._blocked: Dict[str, float] = {}

    def block_node(self, node_hex: str, ttl_s: float = 60.0) -> None:
        import time
        with self._lock:
            self._blocked[node_hex] = time.time() + ttl_s

    def _is_blocked(self, node_hex: str) -> bool:
        import time
        with self._lock:
            until = self._blocked.get(node_hex)
            if until is None:
                return False
            if time.time() >= until:
                del self._blocked[node_hex]
                return False
            return True

    # -- placement --------------------------------------------------------
    def _alive_nodes(self) -> List:
        from ray_tpu._private import worker

        rt = worker.global_runtime()
        return rt.alive_nodes() if rt is not None else []

    def pick_node_for_replica(self, deployment: str) -> Optional[str]:
        """SPREAD: the alive node hosting the fewest replicas of this
        deployment (round-robin tiebreak)."""
        nodes = self._alive_nodes()
        unblocked = [n for n in nodes
                     if not self._is_blocked(n.node_id.hex())]
        nodes = unblocked or nodes
        if not nodes:
            return None
        with self._lock:
            counts = {}
            placed = self._placements.get(deployment, {})
            for node_hex in placed.values():
                counts[node_hex] = counts.get(node_hex, 0) + 1
            self._rr += 1
            ordered = sorted(
                nodes, key=lambda n: (counts.get(n.node_id.hex(), 0),
                                      (hash(n.node_id.hex()) + self._rr)
                                      % len(nodes)))
            return ordered[0].node_id.hex()

    def record(self, deployment: str, replica, node_hex: str) -> None:
        with self._lock:
            self._placements.setdefault(deployment, {})[id(replica)] = \
                node_hex

    def forget(self, deployment: str, replica) -> None:
        with self._lock:
            self._placements.get(deployment, {}).pop(id(replica), None)

    def forget_deployment(self, deployment: str) -> None:
        with self._lock:
            self._placements.pop(deployment, None)

    # -- compaction -------------------------------------------------------
    def get_node_to_compact(self) -> Optional[str]:
        """The node hosting the fewest (but >0) replicas, if every other
        alive node could absorb them (reference :638). Returns its hex id
        or None."""
        nodes = self._alive_nodes()
        if len(nodes) < 2:
            return None
        with self._lock:
            per_node: Dict[str, int] = {}
            for placed in self._placements.values():
                for node_hex in placed.values():
                    per_node[node_hex] = per_node.get(node_hex, 0) + 1
        candidates = [(count, node_hex)
                      for node_hex, count in per_node.items() if count > 0]
        if len(candidates) < 2:
            return None  # all replicas already on one node
        count, node_hex = min(candidates)
        if self._is_blocked(node_hex):
            return None
        others = [n for n in nodes if n.node_id.hex() != node_hex]
        if not others:
            return None
        # Availability gate: moving the victim's replicas must not shrink
        # any deployment's node-span below min(2, current span) — SPREAD
        # placement exists for fault tolerance; compaction must not
        # quietly collapse a 2-node deployment onto one node.
        with self._lock:
            for deployment, placed in self._placements.items():
                spans = set(placed.values())
                if node_hex not in spans:
                    continue
                span_after = len(spans - {node_hex})
                # only a MULTI-node deployment loses availability by the
                # move; a single-node deployment just relocates
                if len(spans) >= 2 and span_after < 2:
                    return None
        return node_hex

    def replicas_on(self, node_hex: str) -> List:
        with self._lock:
            out = []
            for deployment, placed in self._placements.items():
                for rid, n in placed.items():
                    if n == node_hex:
                        out.append((deployment, rid))
            return out
