"""DeploymentHandle + request router.

Reference: `serve/_private/router.py:341,365,676` (AsyncioRouter),
`serve/_private/request_router/pow_2_router.py:27` (power-of-two-choices on
queue length), `serve/_private/long_poll.py` (membership push). Replica
membership is PUSHED: each handle keeps a long-poll listen open against
the controller (serve/long_poll.py) and applies snapshots the moment a
deploy/scale/death publishes — no periodic-poll staleness window. Routing
is P2C over REPORTED replica depth (ongoing + engine queue, pushed by
replica reporters through the controller and fanned out on the
``depths::<name>`` long-poll key) plus the handle's own in-flight
delta — so independent client processes see each other's load instead
of only their own (reference: pow_2_router.py routes on replica queue
length, not handle-local counts).
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu


class DeploymentResponse:
    """Future-like response (reference: DeploymentResponse)."""

    def __init__(self, ref):
        self._ref = ref

    def result(self, timeout: Optional[float] = None) -> Any:
        return ray_tpu.get(self._ref, timeout=timeout)

    @property
    def ref(self):
        return self._ref


class DeploymentResponseGenerator:
    """Iterator over a streaming deployment response (reference:
    DeploymentResponseGenerator): yields each chunk as the replica
    produces it — chunk 1 arrives before the handler returns."""

    def __init__(self, ref_gen, on_done=None):
        self._ref_gen = ref_gen
        self._on_done = on_done
        self._finished = False

    def __iter__(self):
        return self

    def __next__(self) -> Any:
        return self.next()

    def next(self, timeout: Optional[float] = None) -> Any:
        """``next(gen)`` with a per-chunk deadline: raises
        ``GetTimeoutError`` when the replica produces no chunk within
        ``timeout`` seconds (the response is finished locally — an
        abandoning client must not leak router in-flight counts)."""
        try:
            if timeout is not None and hasattr(self._ref_gen, "next"):
                ref = self._ref_gen.next(timeout=timeout)
            else:
                ref = next(self._ref_gen)
        except StopIteration:
            self._finish()
            raise
        except Exception:
            self._finish()
            raise
        try:
            return ray_tpu.get(ref, timeout=timeout)
        except Exception:
            self._finish()
            raise

    def _finish(self) -> None:
        if not self._finished:
            self._finished = True
            if self._on_done is not None:
                self._on_done()

    def __del__(self):
        try:
            self._finish()
        except Exception:
            pass


class _HandleState:
    """Router state SHARED by a handle and all its method views: one
    replica set, one in-flight table, and at most ONE long-poll listener
    per deployment handle family (method composition must not multiply
    listener threads or parked controller listens)."""

    def __init__(self, deployment_name: str, controller,
                 seed: Optional[int] = None):
        self.deployment_name = deployment_name
        self.controller = controller
        self.lock = threading.Lock()
        self.replicas: List = []                 #: guarded by self.lock
        self.version = -1                        #: guarded by self.lock
        self.inflight: Dict[int, int] = {}       #: guarded by self.lock
        # reported depth per replica index (controller-published view of
        # ongoing + engine queue), valid for depths_version only
        self.depths: List[float] = []            #: guarded by self.lock
        self.depths_version = -1                 #: guarded by self.lock
        # urandom-seeded: a FIXED seed marched every client process
        # through identical P2C pairs in lockstep under many-client
        # load (the herd all picks the same victim); ``seed=`` keeps
        # tests deterministic.
        self.rng = random.Random(
            os.urandom(16) if seed is None else seed)
        self.long_poll = None

    def ensure_long_poll(self) -> None:
        with self.lock:
            if self.long_poll is not None:
                return
            self.long_poll = True  # claim under the lock; replaced below
        import weakref

        from ray_tpu.serve.long_poll import LongPollClient

        ref = weakref.ref(self)

        def on_update(snapshot, version):
            state = ref()
            if state is None:
                return
            with state.lock:
                state.replicas = snapshot["replicas"]
                state.version = snapshot.get("version", version)
                state.inflight = {i: 0
                                  for i in range(len(state.replicas))}
                # indexing changed: drop depths until a matching
                # snapshot arrives (next controller tick)
                if state.depths_version != state.version:
                    state.depths = []

        def on_depths(snapshot, version):
            state = ref()
            if state is None or not isinstance(snapshot, dict):
                return
            with state.lock:
                # depths are positional over the replica list of ONE
                # membership version; a mismatched snapshot (router
                # ahead or behind) would mis-score replicas
                if snapshot.get("version") == state.version:
                    state.depths = list(snapshot.get("depths") or [])
                    state.depths_version = snapshot["version"]

        try:
            client = LongPollClient(
                self.controller,
                {f"replicas::{self.deployment_name}": on_update,
                 f"depths::{self.deployment_name}": on_depths})
        except Exception:
            with self.lock:
                self.long_poll = None   # release the claim: retry later
            raise
        self.long_poll = client
        # stop the listener thread when the handle family is collected
        weakref.finalize(self, LongPollClient.stop, client)


class DeploymentHandle:
    def __init__(self, deployment_name: str, controller,
                 method_name: str = "__call__", _state=None,
                 _stream: bool = False):
        self.deployment_name = deployment_name
        self._controller = controller
        self._method_name = method_name
        self._stream = _stream
        self._state = _state or _HandleState(deployment_name, controller)
        self._children: Dict[str, "DeploymentHandle"] = {}

    # back-compat views onto the shared state
    @property
    def _lock(self):
        return self._state.lock

    @property
    def _replicas(self):
        return self._state.replicas

    @property
    def _version(self):
        return self._state.version

    @property
    def _inflight(self):
        return self._state.inflight

    def __getstate__(self):
        return {"deployment_name": self.deployment_name,
                "_controller": self._controller,
                "_method_name": self._method_name,
                "_stream": self._stream}

    def __setstate__(self, d):
        self.deployment_name = d["deployment_name"]
        self._controller = d["_controller"]
        self._method_name = d["_method_name"]
        self._stream = d.get("_stream", False)
        self._state = _HandleState(self.deployment_name, self._controller)
        self._children = {}

    # composition: handle.other_method.remote(...) — cached, sharing
    # the router state (one listener for the whole family)
    def __getattr__(self, name: str) -> "DeploymentHandle":
        if name.startswith("_") or name in ("deployment_name",):
            raise AttributeError(name)
        cached = self._children.get(name)
        if cached is None:
            cached = DeploymentHandle(self.deployment_name,
                                      self._controller, name,
                                      _state=self._state,
                                      _stream=self._stream)
            self._children[name] = cached
        return cached

    def options(self, method_name: Optional[str] = None, *,
                stream: Optional[bool] = None) -> "DeploymentHandle":
        """``stream=True`` makes ``remote()`` return a
        DeploymentResponseGenerator yielding chunks as the replica
        produces them (reference: handle.options(stream=True))."""
        out = self.__getattr__(method_name) if method_name else self
        if stream is None or stream == out._stream:
            return out
        return DeploymentHandle(out.deployment_name, out._controller,
                                out._method_name, _state=out._state,
                                _stream=stream)

    def _refresh(self, force: bool = False) -> None:
        state = self._state
        with state.lock:
            stale = force or not state.replicas
        if not stale:
            return
        info = ray_tpu.get(self._controller.get_replicas.remote(
            self.deployment_name))
        with state.lock:
            state.replicas = info["replicas"]
            state.version = info["version"]
            state.inflight = {i: 0 for i in range(len(state.replicas))}
            if state.depths_version != state.version:
                state.depths = []   # positional depths no longer valid

    def _score(self, idx: int) -> float:
        """Load estimate for one replica: the controller-reported depth
        (ongoing + engine queue across ALL clients, <=1 tick stale)
        plus this handle's own in-flight count (the not-yet-reported
        delta). Called under ``state.lock``."""
        state = self._state
        reported = (state.depths[idx]
                    if idx < len(state.depths) else 0.0)
        return reported + state.inflight.get(idx, 0)

    def _pick(self) -> int:
        """Power-of-two-choices on reported depth + local in-flight."""
        state = self._state
        n = len(state.replicas)
        if n == 1:
            return 0
        a, b = state.rng.sample(range(n), 2)
        return a if self._score(a) <= self._score(b) else b

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        state = self._state
        state.ensure_long_poll()
        self._refresh()  # fallback for the gap before the first push
        last_err = None
        for _ in range(3):
            with state.lock:
                if not state.replicas:
                    raise RuntimeError(
                        f"no replicas for {self.deployment_name}")
                idx = self._pick()
                replica = state.replicas[idx]
                state.inflight[idx] = state.inflight.get(idx, 0) + 1
            try:
                if self._stream:
                    ref_gen = replica.handle_request_streaming.options(
                        num_returns="streaming").remote(
                        self._method_name, args, kwargs)

                    def decrement(i=idx):
                        with state.lock:
                            state.inflight[i] = max(
                                0, state.inflight.get(i, 0) - 1)

                    return DeploymentResponseGenerator(
                        iter(ref_gen), on_done=decrement)
                ref = replica.handle_request.remote(
                    self._method_name, args, kwargs)
                resp = DeploymentResponse(ref)
                self._attach_decrement(resp, idx)
                return resp
            except Exception as e:       # replica died: refresh + retry
                last_err = e
                self._refresh(force=True)
        raise RuntimeError(
            f"routing to {self.deployment_name} failed: {last_err!r}")

    def _attach_decrement(self, resp: DeploymentResponse, idx: int) -> None:
        state = self._state

        def waiter():
            try:
                ray_tpu.get(resp._ref)
            except Exception:
                pass
            with state.lock:
                state.inflight[idx] = max(
                    0, state.inflight.get(idx, 0) - 1)
        threading.Thread(target=waiter, daemon=True).start()

    def __repr__(self):
        return (f"DeploymentHandle({self.deployment_name!r}, "
                f"method={self._method_name!r})")
