"""DeploymentHandle + request router.

Reference: `serve/_private/router.py:341,365,676` (AsyncioRouter),
`serve/_private/request_router/pow_2_router.py:27` (power-of-two-choices on
queue length), `serve/_private/long_poll.py` (membership push). Here the
handle pulls the replica set from the controller when its cached version
goes stale (poll-on-miss) and routes by P2C over locally-tracked in-flight
counts.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu


class DeploymentResponse:
    """Future-like response (reference: DeploymentResponse)."""

    def __init__(self, ref):
        self._ref = ref

    def result(self, timeout: Optional[float] = None) -> Any:
        return ray_tpu.get(self._ref, timeout=timeout)

    @property
    def ref(self):
        return self._ref


class DeploymentHandle:
    def __init__(self, deployment_name: str, controller,
                 method_name: str = "__call__"):
        self.deployment_name = deployment_name
        self._controller = controller
        self._method_name = method_name
        self._lock = threading.Lock()
        self._replicas: List = []
        self._version = -1
        self._inflight: Dict[int, int] = {}
        self._rng = random.Random(0)

    # composition: handle.other_method.remote(...)
    def __getattr__(self, name: str) -> "DeploymentHandle":
        if name.startswith("_"):
            raise AttributeError(name)
        h = DeploymentHandle(self.deployment_name, self._controller, name)
        h._replicas = self._replicas
        h._version = self._version
        return h

    def options(self, method_name: str) -> "DeploymentHandle":
        return self.__getattr__(method_name)

    def _refresh(self, force: bool = False) -> None:
        with self._lock:
            stale = force or not self._replicas
        if not stale:
            return
        info = ray_tpu.get(self._controller.get_replicas.remote(
            self.deployment_name))
        with self._lock:
            self._replicas = info["replicas"]
            self._version = info["version"]
            self._inflight = {i: 0 for i in range(len(self._replicas))}

    def _pick(self) -> int:
        """Power-of-two-choices on local in-flight counts."""
        n = len(self._replicas)
        if n == 1:
            return 0
        a, b = self._rng.sample(range(n), 2)
        return a if self._inflight.get(a, 0) <= self._inflight.get(b, 0) \
            else b

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        self._refresh()
        last_err = None
        for _ in range(3):
            with self._lock:
                if not self._replicas:
                    raise RuntimeError(
                        f"no replicas for {self.deployment_name}")
                idx = self._pick()
                replica = self._replicas[idx]
                self._inflight[idx] = self._inflight.get(idx, 0) + 1
            try:
                ref = replica.handle_request.remote(
                    self._method_name, args, kwargs)
                resp = DeploymentResponse(ref)
                self._attach_decrement(resp, idx)
                return resp
            except Exception as e:       # replica died: refresh + retry
                last_err = e
                self._refresh(force=True)
        raise RuntimeError(
            f"routing to {self.deployment_name} failed: {last_err!r}")

    def _attach_decrement(self, resp: DeploymentResponse, idx: int) -> None:
        def waiter():
            try:
                ray_tpu.get(resp._ref)
            except Exception:
                pass
            with self._lock:
                self._inflight[idx] = max(
                    0, self._inflight.get(idx, 0) - 1)
        threading.Thread(target=waiter, daemon=True).start()

    def __repr__(self):
        return (f"DeploymentHandle({self.deployment_name!r}, "
                f"method={self._method_name!r})")
