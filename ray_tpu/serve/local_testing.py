"""Local testing mode (reference: `serve/_private/local_testing_mode.py` —
run an application graph fully in-process, no cluster/actors, for unit
tests of deployment logic)."""

from __future__ import annotations

import concurrent.futures
import inspect
from typing import Any, Dict

from ray_tpu.serve.deployment import Application


class LocalDeploymentResponse:
    def __init__(self, future):
        self._future = future

    def result(self, timeout=None) -> Any:
        return self._future.result(timeout)


class LocalHandle:
    """Same surface as DeploymentHandle, backed by the in-process
    callable; calls run on a small thread pool so concurrent requests and
    @serve.batch still behave."""

    _pool = concurrent.futures.ThreadPoolExecutor(max_workers=16)

    def __init__(self, instance, method_name: str = "__call__"):
        self._instance = instance
        self._method_name = method_name

    def __getattr__(self, name: str) -> "LocalHandle":
        if name.startswith("_"):
            raise AttributeError(name)
        return LocalHandle(self._instance, name)

    def options(self, method_name: str) -> "LocalHandle":
        return LocalHandle(self._instance, method_name)

    def remote(self, *args, **kwargs) -> LocalDeploymentResponse:
        if self._method_name == "__call__":
            target = self._instance
        else:
            target = getattr(self._instance, self._method_name)
        return LocalDeploymentResponse(
            self._pool.submit(target, *args, **kwargs))


def run_local(app: Application) -> LocalHandle:
    """Build the application graph in-process; bound sub-apps become
    LocalHandles (model composition works unchanged)."""
    dep = app.deployment
    args = [run_local(a) if isinstance(a, Application) else a
            for a in app.args]
    kwargs = {k: run_local(v) if isinstance(v, Application) else v
              for k, v in app.kwargs.items()}
    fc = dep.func_or_class
    if inspect.isclass(fc):
        instance = fc(*args, **kwargs)
        if dep.user_config is not None and hasattr(instance,
                                                   "reconfigure"):
            instance.reconfigure(dep.user_config)
    else:
        instance = fc
    return LocalHandle(instance)
