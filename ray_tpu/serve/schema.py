"""Declarative Serve app config (reference: ``serve/schema.py``
ServeDeploySchema — the YAML the `serve deploy` CLI consumes).

Shape (YAML or JSON, or the equivalent dict):

    applications:
      - name: default                # app name (route key on the proxy)
        import_path: my_pkg.app:app  # module:attr — an Application, or
                                     # a builder callable(args) -> app
        args: {model: tiny}          # passed to a builder callable
        deployments:                 # per-deployment OVERRIDES by name
          - name: Model
            num_replicas: 2
            max_ongoing_requests: 8
            user_config: {threshold: 0.5}
            autoscaling_config: {min_replicas: 1, max_replicas: 4}

``serve.run_config(path_or_dict)`` imports each application, applies the
overrides, and deploys it; the CLI wraps this as
``ray-tpu serve-deploy <file>``.
"""

from __future__ import annotations

import importlib
import json
from typing import Any, Dict, List

from ray_tpu.serve.deployment import Application, AutoscalingConfig


def load_config_file(path: str) -> Dict[str, Any]:
    with open(path) as f:
        raw = f.read()
    if path.endswith((".yaml", ".yml")):
        import yaml

        return yaml.safe_load(raw)
    return json.loads(raw)


def _import_attr(import_path: str) -> Any:
    if ":" not in import_path:
        raise ValueError(
            f"import_path must be 'module:attr', got {import_path!r}")
    module_name, attr = import_path.split(":", 1)
    module = importlib.import_module(module_name)
    obj = module
    for part in attr.split("."):
        obj = getattr(obj, part)
    return obj


# options a config may override; internal fields (func_or_class) are not
# part of the declarative surface
OVERRIDABLE_OPTIONS = {"num_replicas", "autoscaling_config",
                       "max_ongoing_requests", "user_config",
                       "ray_actor_options", "max_restarts",
                       "graceful_shutdown_timeout_s"}


def _apply_overrides(app: Application,
                     overrides: List[Dict[str, Any]]) -> Application:
    """Rebuild the application graph with per-deployment option
    overrides matched by deployment name (reference: deployment_schema
    fields layered over the code's decorator defaults)."""
    by_name: Dict[str, Dict[str, Any]] = {}
    for o in overrides or []:
        if "name" not in o:
            raise ValueError(
                f"deployment override entry missing 'name': {o!r}")
        by_name[o["name"]] = dict(o)
    consumed: set = set()

    def rebuild(node: Application) -> Application:
        dep = node.deployment
        opts = by_name.get(dep.name)
        if opts:
            consumed.add(dep.name)
            opts = {k: v for k, v in opts.items() if k != "name"}
            unknown = set(opts) - OVERRIDABLE_OPTIONS
            if unknown:
                raise ValueError(
                    f"unknown deployment option(s) for "
                    f"{dep.name!r}: {sorted(unknown)} "
                    f"(overridable: {sorted(OVERRIDABLE_OPTIONS)})")
            asc = opts.get("autoscaling_config")
            if isinstance(asc, dict):
                opts["autoscaling_config"] = AutoscalingConfig(**asc)
            dep = dep.options(**opts)
        args = tuple(rebuild(a) if isinstance(a, Application) else a
                     for a in node.args)
        kwargs = {k: rebuild(v) if isinstance(v, Application) else v
                  for k, v in node.kwargs.items()}
        return Application(dep, args, kwargs)

    out = rebuild(app)
    dangling = set(by_name) - consumed
    if dangling:
        raise ValueError(
            f"deployment override(s) match no deployment in the "
            f"application: {sorted(dangling)} (a typo'd name would be "
            f"silently ignored otherwise)")
    return out


def build_app_from_config(app_config: Dict[str, Any]) -> Application:
    """One application entry -> a bound, override-applied Application."""
    target = _import_attr(app_config["import_path"])
    if isinstance(target, Application):
        app = target
        if app_config.get("args"):
            raise ValueError(
                f"{app_config['import_path']} is a bound Application; "
                "'args' requires a builder callable")
    elif callable(target):
        app = target(app_config.get("args") or {})
        if not isinstance(app, Application):
            raise TypeError(
                f"builder {app_config['import_path']} returned "
                f"{type(app).__name__}, expected a bound Application")
    else:
        raise TypeError(
            f"{app_config['import_path']} is neither an Application "
            "nor a builder callable")
    return _apply_overrides(app, app_config.get("deployments"))


def run_config(config: Any) -> Dict[str, Any]:
    """Deploy every application in a config file/dict (the `serve
    deploy` role). Returns {app_name: ingress handle}."""
    from ray_tpu.serve import api as serve_api

    if isinstance(config, str):
        # strings are always paths: a typo'd filename must raise
        # FileNotFoundError, not a confusing schema error
        config = load_config_file(config)
    if not isinstance(config, dict) or "applications" not in config:
        raise ValueError("serve config needs an 'applications' list")
    names = [a.get("name", "default") for a in config["applications"]]
    dupes = {n for n in names if names.count(n) > 1}
    if dupes:
        raise ValueError(
            f"duplicate application name(s) {sorted(dupes)}: a later "
            f"app would silently shadow the earlier one's route")
    handles: Dict[str, Any] = {}
    for app_config in config["applications"]:
        name = app_config.get("name", "default")
        app = build_app_from_config(app_config)
        handles[name] = serve_api.run(app, name=name)
    return handles
