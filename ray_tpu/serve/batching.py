"""``@serve.batch`` dynamic batching (reference: `serve/batching.py:104` —
queue requests, flush at max_batch_size or batch_wait_timeout_s, fan
results back out). Thread-based here because replicas execute requests on
a thread pool (max_concurrency), not an asyncio loop."""

from __future__ import annotations

import functools
import threading
from typing import Any, Callable, List, Optional


class _BatchQueue:
    def __init__(self, fn: Callable[[List[Any]], List[Any]],
                 max_batch_size: int, timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout_s = timeout_s
        self._lock = threading.Lock()
        self._items: List[Any] = []
        self._events: List[threading.Event] = []
        self._results: List[Any] = []
        self._flusher: Optional[threading.Timer] = None

    def submit(self, item: Any) -> Any:
        ev = threading.Event()
        to_run = None
        with self._lock:
            self._items.append(item)
            self._events.append(ev)
            if len(self._items) >= self.max_batch_size:
                to_run = self._take_locked()
            elif self._flusher is None:
                self._flusher = threading.Timer(self.timeout_s, self._flush)
                self._flusher.daemon = True
                self._flusher.start()
        if to_run is not None:   # run the user fn OUTSIDE the lock
            self._run_batch(*to_run)
        ev.wait()
        return ev.result

    def _take_locked(self):
        items, events = self._items, self._events
        self._items, self._events = [], []
        if self._flusher is not None:
            self._flusher.cancel()
            self._flusher = None
        return items, events

    def _flush(self):
        with self._lock:
            if not self._items:
                self._flusher = None
                return
            items, events = self._take_locked()
        self._run_batch(items, events)

    def _run_batch(self, items, events):
        try:
            results = self.fn(items)
            if len(results) != len(items):
                raise ValueError(
                    f"@serve.batch fn returned {len(results)} results for "
                    f"{len(items)} inputs")
        except Exception as e:
            results = [e] * len(items)
        for ev, res in zip(events, results):
            ev.result = res
            ev.set()


def batch(_fn=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Decorator: the wrapped fn receives a LIST of inputs and must return
    a list of outputs; callers invoke it with single items."""
    def wrap(fn):
        queues = {}
        qlock = threading.Lock()

        @functools.wraps(fn)
        def wrapper(*args):
            # methods: (self, item); functions: (item,)
            if len(args) == 2:
                owner, item = args
                key = id(owner)
                call = lambda items: fn(owner, items)  # noqa: E731
            elif len(args) == 1:
                item = args[0]
                key = None
                call = fn
            else:
                raise TypeError("@serve.batch methods take one argument")
            with qlock:
                q = queues.get(key)
                if q is None:
                    q = queues[key] = _BatchQueue(
                        call, max_batch_size, batch_wait_timeout_s)
            out = q.submit(item)
            if isinstance(out, Exception):
                raise out
            return out
        return wrapper
    if _fn is not None:
        return wrap(_fn)
    return wrap
